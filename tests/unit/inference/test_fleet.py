"""Serving-fleet tests: replicated engines, live migration, elastic
drain/join, fleet-scope chaos (ISSUE 12 acceptance).

Load-bearing checks:

* every output stream a fleet produces — across replica kills at every
  fleet chaos point, cooperative migrations mid-prefill and mid-decode,
  drains, circuit-breaker trips, and prefill/decode role splits — is
  **byte-identical** to an uninterrupted single-replica (dense oracle)
  run, and the acked prefix of a migrated request never diverges
  (``migrated_token_divergence`` stays 0);
* a drain empties its replica with zero dropped acked tokens and leaves
  its journal compacted (bounded segments);
* prefix-affinity consistent-hash routing beats random routing on the
  fleet-wide prefix hit rate;
* SLA tenancy and goodput survive a mid-trace replica kill under the
  loadgen's heavy-tailed multi-tenant replay, and the 3-replica fleet's
  goodput beats the single-replica baseline on the same trace;
* the real thing: a ``-m slow`` subprocess fleet dies by ``os._exit(137)``
  at the armed point and a fresh process adopts the journals and finishes
  every stream byte-identically.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.elasticity import FleetResizePolicy, valid_fleet_sizes
from deepspeed_tpu.inference import decode
from deepspeed_tpu.inference.fleet import (
    ConsistentHashRing,
    FleetRouter,
    ReplicaHandle,
    UID_STRIDE,
    prefix_chain_keys,
)
from deepspeed_tpu.inference.journal import RequestJournal
from deepspeed_tpu.inference.scheduler import PagedServer
from deepspeed_tpu.inference.traffic import MultiTenantServer, TenantSpec
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.utils import chaos
from deepspeed_tpu.utils.loadgen import TenantLoad, VirtualClock, make_trace, replay

CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    max_seq_len=64,
    norm="rmsnorm",
    position="rope",
    activation="swiglu",
    use_bias=False,
    tie_embeddings=False,
    flash_attention=False,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def _dense(cfg, params, prompt, n, eos=None):
    return np.asarray(decode.generate(cfg, params, prompt[None], n, eos_token_id=eos))[0]


def _server(cfg, params, journal_dir=None, tenants=None, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("prefix_cache", True)
    journal = RequestJournal(journal_dir) if journal_dir else None
    srv = PagedServer(cfg, params, journal=journal, **kw)
    if tenants:
        srv = MultiTenantServer(srv, tenants=tenants)
    return srv


def _fleet(cfg, params, n=3, tmp=None, names=None, tenants=None, **router_kw):
    handles = []
    for i in range(n):
        name = names[i] if names else f"r{i}"
        jdir = os.path.join(str(tmp), name) if tmp is not None else None
        handles.append(
            ReplicaHandle(
                name=name,
                server=_server(cfg, params, journal_dir=jdir, tenants=tenants),
                journal_dir=jdir,
            )
        )
    return FleetRouter(handles, **router_kw)


def _prompts(seed=7, n=6, shared_frac=2):
    rs = np.random.RandomState(seed)
    sysp = rs.randint(0, CFG["vocab_size"], (16,)).astype(np.int32)
    out = []
    for i in range(n):
        tail = rs.randint(0, CFG["vocab_size"], (int(rs.randint(3, 8)),)).astype(np.int32)
        out.append(np.concatenate([sysp, tail]) if i % shared_frac == 0 else tail)
    return out


def _assert_oracle(router, cfg, params, prompts, budgets, uids):
    for p, n, u in zip(prompts, budgets, uids):
        if u is None:
            continue
        out = router.take_result(u)
        assert out is not None, f"request {u} never finished"
        np.testing.assert_array_equal(out, _dense(cfg, params, p, n))


# ---------------------------------------------------------------------------
# host-side units: chain keys, the ring, uid strides
# ---------------------------------------------------------------------------
def test_chain_keys_and_ring_units():
    rs = np.random.RandomState(0)
    sysp = rs.randint(0, 128, (16,)).astype(np.int32)
    a = np.concatenate([sysp, rs.randint(0, 128, (5,)).astype(np.int32)])
    b = np.concatenate([sysp, rs.randint(0, 128, (5,)).astype(np.int32)])
    ka, kb = prefix_chain_keys(a, 8), prefix_chain_keys(b, 8)
    # the shared 16-token system prompt = 2 full pages: identical chain
    assert ka[:2] == kb[:2] and len(ka) == 2
    # the final partial block never keys (it cannot be a cached full page)
    assert prefix_chain_keys(sysp[:9], 8) == prefix_chain_keys(sysp[:15], 8)
    # a one-token-longer prompt crossing the boundary adds a key
    assert len(prefix_chain_keys(sysp, 8)) == 1  # 16 tokens: cap leaves 1 block
    assert prefix_chain_keys(np.asarray([1, 2], np.int32), 8) == []

    ring = ConsistentHashRing(vnodes=16)
    for n in ("a", "b", "c"):
        ring.add(n)
    keys = list(range(0, 2**32, 2**26))
    before = {k: ring.lookup(k, lambda n: True) for k in keys}
    assert set(before.values()) == {"a", "b", "c"}  # all nodes own arcs
    ring.remove("b")
    after = {k: ring.lookup(k, lambda n: True) for k in keys}
    for k in keys:
        # consistent hashing: only the removed node's arcs moved
        if before[k] != "b":
            assert after[k] == before[k]
        else:
            assert after[k] in ("a", "c")
    # exclusion predicate: a key whose owner is unacceptable walks on
    assert ring.lookup(keys[0], lambda n: n == "c") == "c"
    assert ring.lookup(keys[0], lambda n: False) is None


def test_uid_strides_and_geometry_guard(model_and_params):
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=3)
    bases = sorted(h.uid_base for h in router.replicas.values())
    assert bases == [0, UID_STRIDE, 2 * UID_STRIDE]
    uids = [router.submit(p, max_new_tokens=2) for p in _prompts(n=6)]
    assert len(set(uids)) == 6  # fleet-wide unique
    router.run()
    # mixed pool geometry is rejected up front (it would retrace programs)
    with pytest.raises(ValueError, match="pool geometry"):
        FleetRouter([
            ReplicaHandle(name="x", server=_server(cfg, params)),
            ReplicaHandle(name="y", server=_server(cfg, params, page_size=4)),
        ])
    with pytest.raises(ValueError, match="pool geometry"):
        router.join(_server(cfg, params, max_slots=2))


# ---------------------------------------------------------------------------
# acceptance: byte-identical streams, healthy fleet
# ---------------------------------------------------------------------------
def test_fleet_streams_byte_identical_and_spread(model_and_params):
    """A healthy 3-replica fleet serves a shared-prefix mix byte-identically
    to the dense oracle, spreads distinct prompts across replicas, and the
    merged stats reconcile."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=3)
    prompts = _prompts(n=8)
    budgets = [8, 5, 10, 6, 7, 9, 4, 8]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    router.run()
    _assert_oracle(router, cfg, params, prompts, budgets, uids)
    served = {
        n: h.inner.stats["finished"] for n, h in router.replicas.items()
    }
    assert sum(served.values()) == 8
    assert sum(1 for v in served.values() if v > 0) >= 2, served
    merged = router.serve_stats()
    assert merged["finished"] == 8
    assert merged["ttft_ms"]["count"] == 8
    assert merged["fleet"]["routed"] == 8
    assert merged["fleet"]["migrated_token_divergence"] == 0


# ---------------------------------------------------------------------------
# acceptance: replica kill at every fleet chaos point
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("hit", [2, 5, 9])
def test_replica_kill_chaos_byte_identical(model_and_params, tmp_path, hit):
    """An in-process chaos kill of one replica at a deterministic step
    arrival: its live requests re-route onto the survivors from its
    journal and EVERY stream finishes byte-identical to an uninterrupted
    single-replica run — the acked prefix never diverges."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=3, tmp=tmp_path)
    prompts = _prompts(n=6)
    budgets = [10, 7, 12, 8, 9, 11]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("fleet.replica_kill", hit=hit)]))
    try:
        router.run()
    finally:
        chaos.uninstall()
    fs = router.fleet_stats()
    assert fs["replica_kills"] == 1
    assert fs["n_active"] == 2
    assert fs["migrated_token_divergence"] == 0
    _assert_oracle(router, cfg, params, prompts, budgets, uids)
    # survivors' pools stayed internally consistent through the adoption
    for h in router.replicas.values():
        if h.state != "dead":
            h.inner.pool.integrity_check()


def test_replica_kill_without_journal_shadow_fallback(model_and_params):
    """Journal-less replicas fall back to the router's shadow submissions:
    the dead replica's streams recompute from scratch — still
    byte-identical under greedy."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2)  # no tmp: no journals
    prompts = _prompts(seed=11, n=4)
    budgets = [9, 6, 8, 7]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    for _ in range(3):
        router.step()
    victim = next(
        n for n, h in router.replicas.items() if h.inner.has_work()
    )
    router.kill_replica(victim)
    router.run()
    assert router.fleet_stats()["replica_kills"] == 1
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


def test_mid_migration_crash_no_loss_no_duplicates(model_and_params, tmp_path):
    """A kill in the mid-migration window (state off the source scheduler,
    target not yet seeded) is the source dying: failing it replays the
    source journal — the request is neither lost nor duplicated, and its
    acked tokens survive verbatim."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path)
    prompts = _prompts(seed=13, n=4)
    budgets = [10, 8, 9, 7]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    for _ in range(5):
        router.step()
    live_uid = next(u for u in uids if u in router._where)
    src = router._where[live_uid]
    chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("fleet.mid_migration", hit=1)]))
    try:
        with pytest.raises(chaos.ChaosKilled):
            router.migrate(live_uid)
    finally:
        chaos.uninstall()
    # the supervisor's move: the source died mid-migration
    router.fail_replica(src, reason="died mid-migration")
    router.run()
    fs = router.fleet_stats()
    assert fs["migrated_token_divergence"] == 0
    assert len(router._results) == 4  # no duplicates, nothing lost
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


def test_mid_drain_kill_recovers(model_and_params, tmp_path):
    """The draining replica dies between two drain migrations: the
    remainder re-routes from its journal with zero acked tokens
    dropped."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path)
    prompts = _prompts(seed=17, n=5)
    budgets = [9, 8, 10, 7, 9]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    for _ in range(4):
        router.step()
    victim = next(n for n, h in router.replicas.items() if h.inner.has_work())
    chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("fleet.mid_drain", hit=2)]))
    try:
        router.drain(victim)  # the router catches the kill internally
    finally:
        chaos.uninstall()
    assert router.replicas[victim].state == "dead"
    router.run()
    assert router.fleet_stats()["migrated_token_divergence"] == 0
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


# ---------------------------------------------------------------------------
# live migration: mid-decode, mid-prefill, drain
# ---------------------------------------------------------------------------
def test_migration_mid_decode_byte_identical(model_and_params, tmp_path):
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path)
    prompts = _prompts(seed=19, n=4)
    budgets = [12, 9, 11, 10]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    # step until some request is mid-stream (>= 2 tokens emitted, not done)
    mid = None
    for _ in range(30):
        router.step()
        for h in router.replicas.values():
            for r in h.inner._active:
                if len(r.generated) >= 2 and not r.done:
                    mid = r.uid
                    break
            if mid:
                break
        if mid:
            break
    assert mid is not None, "no request reached mid-stream decode"
    src = router._where[mid]
    acked_before = list(
        next(
            r
            for r in router.replicas[src].inner._active
            if r.uid == mid
        ).generated
    )
    assert router.migrate(mid)
    tgt = router._where[mid]
    assert tgt != src
    # the post-migration pool assert ran inside migrate; re-check both
    for name in (src, tgt):
        router.replicas[name].inner.pool.integrity_check()
    router.run()
    fs = router.fleet_stats()
    assert fs["migrations"] >= 1
    assert fs["migrated_token_divergence"] == 0
    out = router.result(mid)
    idx = uids.index(mid)
    p = prompts[idx]
    # the acked prefix rode the migration verbatim
    np.testing.assert_array_equal(
        out[p.size : p.size + len(acked_before)], np.asarray(acked_before)
    )
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


def test_migration_mid_prefill_byte_identical(model_and_params, tmp_path):
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path)
    rs = np.random.RandomState(23)
    # multi-chunk prompts (prefill_chunk=8): migration lands mid-prefill
    prompts = [rs.randint(0, 128, (28,)).astype(np.int32) for _ in range(2)]
    budgets = [8, 6]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    router.step()
    mid = None
    for h in router.replicas.values():
        for r in h.inner._active:
            if r.pending is None and 0 < r.consumed < r.prompt.size:
                mid = r.uid
                break
        if mid:
            break
    assert mid is not None, "no request caught mid-prefill"
    assert router.migrate(mid)
    router.run()
    assert router.fleet_stats()["migrated_token_divergence"] == 0
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


def test_drain_empties_replica_zero_dropped_and_compacts(model_and_params, tmp_path):
    """Elastic scale-down: the drain migrates every queued + live request
    off (zero dropped acked tokens), leaves the replica empty and out of
    the ring, and its journal compacted to a bounded segment count."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path)
    prompts = _prompts(seed=29, n=6)
    budgets = [9, 7, 11, 8, 10, 6]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    for _ in range(4):
        router.step()
    victim = next(n for n, h in router.replicas.items() if h.inner.has_work())
    inner = router.replicas[victim].inner
    outstanding = inner.queued_count() + inner.live_count()
    assert outstanding >= 1
    moved = router.drain(victim)
    assert moved == outstanding
    assert not inner.has_work()
    assert inner.stats["migrated_out"] == moved
    assert router.replicas[victim].state == "drained"
    assert victim not in router._ring.nodes()
    # journal growth bounded: the drain's final migration (live count 0 <
    # migrated-out garbage) triggers the compaction — and with nothing
    # left on the replica, nothing remains to replay
    jdir = router.replicas[victim].journal_dir
    assert len(RequestJournal.segments(jdir)) <= 1
    states, _ = RequestJournal.replay(jdir)
    assert not any(not st.done for st in states.values())
    # a fresh submit can no longer land on the drained replica
    extra = router.submit(prompts[0], max_new_tokens=3)
    assert router._where[extra] != victim
    router.run()
    assert router.fleet_stats()["migrated_token_divergence"] == 0
    _assert_oracle(router, cfg, params, prompts, budgets, uids)
    np.testing.assert_array_equal(
        router.take_result(extra), _dense(cfg, params, prompts[0], 3)
    )


def test_migrate_without_target_restores_request(model_and_params, tmp_path):
    """A migration that cannot find a target (single-replica fleet) must
    not strand the request: the state goes back on the source scheduler
    and the stream finishes there byte-identically. A failed drain
    likewise returns the replica to service."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=1, tmp=tmp_path)
    prompts = _prompts(seed=31, n=2)
    budgets = [8, 6]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    for _ in range(3):
        router.step()
    inner = router.replicas["r0"].inner
    live = next(r.uid for r in inner._active if not r.done)
    with pytest.raises(RuntimeError):
        router.migrate(live)
    # the request is back on the source, not lost off every scheduler —
    # and the failed move left no phantom migration accounting
    assert router._where[live] == "r0"
    assert any(
        r.uid == live for r in list(inner._queue) + list(inner._active)
    )
    assert inner.stats["migrated_out"] == 0
    assert inner.stats["migrated_in"] == 0
    # a drain with nowhere to move also fails CLEAN: replica back in service
    with pytest.raises(RuntimeError):
        router.drain("r0")
    assert router.replicas["r0"].state == "active"
    assert "r0" in router._ring.nodes()
    router.run()
    assert router.fleet_stats()["migrated_token_divergence"] == 0
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


def test_adopt_journal_raises_uid_floor_no_collision(model_and_params, tmp_path):
    """Adopted uids come from a previous fleet's stride space: a fresh
    fleet on the same strides must allocate PAST them, or a new submit
    reuses a uid the fleet already tracks and the global maps clobber."""
    cfg, _, params = model_and_params
    old_dir = os.path.join(str(tmp_path), "old-r0")
    old = _fleet(cfg, params, n=1, tmp=tmp_path, names=["old-r0"])
    prompts = _prompts(seed=37, n=3)
    budgets = [8, 7, 6]
    old_uids = [old.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    old.step()  # some progress journaled; then the whole process "dies"
    del old
    fresh = _fleet(cfg, params, n=1, tmp=tmp_path, names=["n0"])  # stride 0 again
    adopted = fresh.adopt_journal(old_dir)
    assert adopted == len(old_uids)
    # the fresh replica's allocator must clear every adopted uid
    new_uid = fresh.submit(prompts[0], max_new_tokens=4)
    assert new_uid not in old_uids
    # a LATER join on a stride the old fleet used is floored too
    jdir = os.path.join(str(tmp_path), "n1")
    h1 = fresh.join(_server(cfg, params, journal_dir=jdir), name="n1", journal_dir=jdir)
    assert h1.inner._next_uid >= h1.uid_base
    fresh.run()
    assert fresh.fleet_stats()["migrated_token_divergence"] == 0
    _assert_oracle(fresh, cfg, params, prompts, budgets, old_uids)
    np.testing.assert_array_equal(
        fresh.take_result(new_uid), _dense(cfg, params, prompts[0], 4)
    )


def test_single_migration_appends_without_full_compaction(model_and_params, tmp_path):
    """One rebalancing move off a busy replica costs an appended
    migrated-out record + sync, NOT a full-state journal rewrite — the
    compaction only fires when migrated-out garbage outweighs live state
    (which a drain's tail always reaches: the ≤1-segment drain guarantee
    is covered by the drain test)."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path)
    prompts = _prompts(seed=41, n=6)
    budgets = [9, 8, 10, 7, 9, 8]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    for _ in range(3):
        router.step()
    src = next(
        n for n, h in router.replicas.items()
        if h.inner.queued_count() + h.inner.live_count() >= 2
    )
    inner = router.replicas[src].inner
    victim = next(r.uid for r in list(inner._active) + list(inner._queue))
    assert router.migrate(victim)
    # garbage (1 migrated-out) <= live remaining: append-only, no rewrite
    assert inner.stats["journal_compactions"] == 0
    # ... but the migrated-out record IS durable: a replay of the source
    # journal no longer claims the request
    states, _ = RequestJournal.replay(router.replicas[src].journal_dir)
    assert victim not in states
    router.run()
    assert router.fleet_stats()["migrated_token_divergence"] == 0
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


def test_migration_to_journal_less_target_keeps_source_claim(
    model_and_params, tmp_path
):
    """The target-journal-FIRST durability contract requires the target
    to HAVE a journal: migrating onto a journal-less replica must leave
    the source journal claiming the request (no migrated-out record), or
    a crash after the move finds the state in neither journal and acked
    tokens are lost. The double-claim this keeps is what adoption
    dedupes."""
    cfg, _, params = model_and_params
    jdir = os.path.join(str(tmp_path), "src")
    handles = [
        ReplicaHandle(name="src", server=_server(cfg, params, journal_dir=jdir),
                      journal_dir=jdir),
        ReplicaHandle(name="bare", server=_server(cfg, params)),  # no journal
    ]
    router = FleetRouter(handles)
    rs = np.random.RandomState(47)
    prompts, budgets, uids = [], [], []
    # keep submitting distinct prompts until one routes to the journaled
    # replica (consistent hashing spreads unseen keys — a handful suffices)
    for _ in range(24):
        p = rs.randint(0, 128, (int(rs.randint(6, 20)),)).astype(np.int32)
        u = router.submit(p, max_new_tokens=7)
        prompts.append(p), budgets.append(7), uids.append(u)
        if router._where.get(u) == "src" and len(uids) >= 3:
            break
    assert any(router._where.get(u) == "src" for u in uids)
    # budgets of 7 cannot finish in 3 steps: the victim is still live
    for _ in range(3):
        router.step()
    inner = router.replicas["src"].inner
    victim = next(
        (r.uid for r in list(inner._active) + list(inner._queue)), None
    )
    assert victim is not None
    acked = list(
        next(
            (r.generated for r in inner._active if r.uid == victim), []
        )
    )
    assert router.migrate(victim, target="bare")
    # no "m" disclaim: the source journal still replays the request —
    # with every acked token — because the target holds it only in memory
    states, _ = RequestJournal.replay(jdir)
    assert victim in states and not states[victim].done
    assert list(states[victim].generated)[: len(acked)] == acked
    # the claim survives a full compaction of the source journal
    inner.compact_journal()
    states, _ = RequestJournal.replay(jdir)
    assert victim in states and not states[victim].done
    router.run()
    assert router.fleet_stats()["migrated_token_divergence"] == 0
    # ...and is disclaimed once the output was delivered: a later replay
    # cannot resurrect the finished request
    states, _ = RequestJournal.replay(jdir)
    assert victim not in states or states[victim].done
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


def test_inbound_recover_preserves_compaction_garbage_counter(
    model_and_params, tmp_path
):
    """``recover()`` on a LIVE migration target re-seeds one request — it
    is NOT a compaction (the writer's retirement boundary is unchanged) —
    so it must not zero the migrated-out garbage counter, or a replica
    that both sends and receives migrations never triggers the rewrite
    and its journal grows without bound."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path)
    prompts = _prompts(seed=53, n=6)
    uids = [router.submit(p, max_new_tokens=6) for p in prompts]
    for _ in range(3):
        router.step()
    a, b = router.replicas["r0"].inner, router.replicas["r1"].inner
    if a.queued_count() + a.live_count() < 2:
        a, b = b, a
    out_uid = next(r.uid for r in list(a._active) + list(a._queue))
    assert router.migrate(out_uid)
    assert a._migrated_since_compact == 1
    # an INBOUND migration (recover on the live server) keeps the count
    in_uid = next(
        (r.uid for r in list(b._active) + list(b._queue)), None
    )
    if in_uid is not None:
        router.migrate(in_uid, target=_name_of(router, a))
        assert a._migrated_since_compact == 1
    router.run()
    assert router.fleet_stats()["migrated_token_divergence"] == 0
    _assert_oracle(router, cfg, params, prompts, [6] * 6, uids)


def _name_of(router, inner):
    return next(n for n, h in router.replicas.items() if h.inner is inner)


# ---------------------------------------------------------------------------
# routing quality + failure detection
# ---------------------------------------------------------------------------
def test_prefix_affinity_beats_random_on_hit_rate(model_and_params):
    """Consistent-hash affinity pins each shared system prompt to one
    replica (its prefix cache pays the prefill once); random spread pays
    the cold miss once per replica — measurably lower hit rate."""
    cfg, _, params = model_and_params

    def run(affinity):
        router = _fleet(
            cfg, params, n=2, names=["a0", "a1"], affinity=affinity
        )
        rs = np.random.RandomState(3)
        sysps = [rs.randint(0, 128, (16,)).astype(np.int32) for _ in range(3)]
        for _wave in range(3):
            ps = [
                np.concatenate(
                    [sysps[i % 3], rs.randint(0, 128, (4,)).astype(np.int32)]
                )
                for i in range(6)
            ]
            router.serve(ps, max_new_tokens=4)
        return router.serve_stats()["prefix"]["prefix_hit_rate"]

    hit_affinity = run(True)
    hit_random = run(False)
    assert hit_affinity > hit_random, (hit_affinity, hit_random)


def test_circuit_breaker_trips_on_flaky_replica(model_and_params, tmp_path):
    """Ordinary exceptions (not chaos kills) trip the per-replica circuit
    breaker after ``breaker_threshold`` consecutive failures; the dead
    replica's streams finish on the survivor byte-identically."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path, breaker_threshold=3)
    prompts = _prompts(seed=31, n=4)
    budgets = [8, 9, 7, 10]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    for _ in range(2):
        router.step()
    victim = next(n for n, h in router.replicas.items() if h.inner.has_work())

    def boom():
        raise RuntimeError("wedged backend")

    router.replicas[victim].server.step = boom
    router.run()
    h = router.replicas[victim]
    assert h.state == "dead"
    assert router.fleet_stats()["replica_kills"] == 1
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


def test_health_probe_circuit_breaker(model_and_params):
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, breaker_threshold=2)
    name = next(iter(router.replicas))
    router.replicas[name].health_fn = lambda srv: False
    assert router.probe()[name] is False
    assert router.replicas[name].state == "active"  # one strike
    router.probe()  # second strike: breaker opens
    assert router.replicas[name].state == "dead"


# ---------------------------------------------------------------------------
# SLA + goodput across a mid-trace kill (loadgen fleet scope)
# ---------------------------------------------------------------------------
def test_sla_and_goodput_across_mid_trace_kill(model_and_params, tmp_path):
    """The acceptance replay: a heavy-tailed two-tenant trace across 3
    SLA-scheduled replicas with a replica killed mid-trace. Every stream
    stays byte-identical to the oracle, no tenant starves, p99 TTFT stays
    bounded, and fleet goodput beats the single-replica baseline on the
    SAME trace (virtual clock: each replica is its own service lane)."""
    cfg, _, params = model_and_params
    tenants = [
        TenantSpec(name="gold", weight=3.0, priority=1, ttft_target_ms=4000),
        TenantSpec(name="free", weight=1.0),
    ]
    trace = make_trace(
        [
            TenantLoad(name="gold", rate=60, prompt_len=(6, 14),
                       max_new_tokens=(3, 7)),
            TenantLoad(name="free", rate=60, prompt_len=(6, 14),
                       max_new_tokens=(3, 7)),
        ],
        horizon_s=1.0,
        vocab_size=CFG["vocab_size"],
        seed=5,
    )
    router = _fleet(cfg, params, n=3, tmp=tmp_path, tenants=tenants)
    rep = replay(
        router,
        trace,
        clock=VirtualClock(step_cost_s=0.02),
        events=[(0.3, lambda srv: srv.kill_replica(next(
            n for n, h in srv.replicas.items() if h.inner.has_work()
        )))],
    )
    fs = router.fleet_stats()
    assert rep["events_fired"] == 1 and fs["replica_kills"] == 1
    assert fs["rerouted"] >= 1, fs  # the kill landed on a busy replica
    assert fs["migrated_token_divergence"] == 0
    assert rep["starved_tenants"] == []
    assert rep["ttft_ms"]["count"] > 0 and np.isfinite(rep["ttft_ms"]["p99"])
    # byte-identical outputs for every finished request, kill included
    for idx, out in rep["outputs"].items():
        if out is None:
            continue
        r = trace[idx]
        np.testing.assert_array_equal(
            out, _dense(cfg, params, r.prompt, r.max_new_tokens)
        )
    # goodput: 3 replicas (one killed mid-trace) still beat 1 replica
    single = _fleet(cfg, params, n=1, tenants=tenants)
    rep1 = replay(single, trace, clock=VirtualClock(step_cost_s=0.02))
    assert rep["goodput_tokens_per_s"] > rep1["goodput_tokens_per_s"], (
        rep["goodput_tokens_per_s"], rep1["goodput_tokens_per_s"]
    )


# ---------------------------------------------------------------------------
# prefill/decode role split
# ---------------------------------------------------------------------------
def test_role_split_migration_at_first_decode(model_and_params):
    """Disaggregation: prefill-role replicas admit, and the step the first
    decode token exists the request hands off to the decode replica (KV
    handoff = migration). Streams stay byte-identical; the prefill
    replica never runs a plain decode dispatch."""
    cfg, _, params = model_and_params
    router = FleetRouter([
        ReplicaHandle(name="pf", server=_server(cfg, params), role="prefill"),
        ReplicaHandle(name="dc", server=_server(cfg, params), role="decode"),
    ])
    rs = np.random.RandomState(37)
    prompts = [rs.randint(0, 128, (int(rs.randint(10, 20)),)).astype(np.int32)
               for _ in range(4)]
    budgets = [6, 9, 4, 8]
    outs = router.serve(prompts, max_new_tokens=budgets)
    for o, p, n in zip(outs, prompts, budgets):
        np.testing.assert_array_equal(o, _dense(cfg, params, p, n))
    fs = router.fleet_stats()
    assert fs["role_migrations"] == 4  # one handoff per request
    pf = router.replicas["pf"].inner.stats
    dc = router.replicas["dc"].inner.stats
    assert pf["decode_steps"] == 0  # the prefill tier never plain-decodes
    assert dc["decode_steps"] > 0
    # each request emitted exactly its first token on the prefill tier
    assert pf["emitted_tokens"] == 4
    assert dc["emitted_tokens"] == sum(budgets) - 4
    assert fs["migrated_token_divergence"] == 0


# ---------------------------------------------------------------------------
# elasticity: resize policy + journal-catch-up join
# ---------------------------------------------------------------------------
def test_resize_policy_watermarks_hysteresis_and_quantization():
    # the valid-count quantization reuses the elastic batch math: 4-slot
    # replicas under a 32-slot fleet budget resize through {1, 2, 4, 8}
    assert valid_fleet_sizes(32, 4) == [1, 2, 4, 8]
    pol = FleetResizePolicy(
        min_replicas=1, max_replicas=8, target_backlog_per_replica=4.0,
        cooldown_steps=5, valid_counts=valid_fleet_sizes(32, 4),
    )
    # heavy backlog: 40 requests over 2 replicas -> wants 10 -> snaps to 8
    assert pol.decide(backlog=40, n_active=2, step=0) == 8
    # inside the cooldown nothing moves, however loud the signal
    assert pol.decide(backlog=40, n_active=4, step=2) == 4
    # idle fleet far past the cooldown shrinks (snapped downward)
    assert pol.decide(backlog=1, n_active=4, step=20) == 1
    # the hysteresis band holds steady
    assert pol.decide(backlog=16, n_active=4, step=40) == 4
    with pytest.raises(ValueError, match="scale_down_at"):
        FleetResizePolicy(scale_up_at=0.2, scale_down_at=0.5)
    with pytest.raises(ValueError, match="min_replicas"):
        FleetResizePolicy(min_replicas=3, max_replicas=2)


def test_autoscale_grows_and_drains(model_and_params):
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=1)
    rs = np.random.RandomState(41)
    uids = [
        router.submit(rs.randint(0, 128, (8,)).astype(np.int32), max_new_tokens=4)
        for _ in range(12)
    ]
    pol = FleetResizePolicy(
        min_replicas=1, max_replicas=4, target_backlog_per_replica=3.0,
        cooldown_steps=0,
    )
    grew = router.autoscale_step(pol, spawn=lambda: _server(cfg, params), step=0)
    assert grew == 3
    assert router.fleet_stats()["n_active"] == 4
    assert router.fleet_stats()["joins"] == 3
    router.run()
    for u in uids:
        assert router.take_result(u) is not None
    shrank = router.autoscale_step(pol, spawn=lambda: _server(cfg, params), step=10)
    assert shrank == -3
    assert router.fleet_stats()["n_active"] == 1


def test_journal_catchup_join_and_adoption(model_and_params, tmp_path):
    """Scale-up by journal catch-up: a dead replica's orphaned journal is
    adopted by a joining replica (the new capacity arrives already
    carrying the dead one's load), byte-identically."""
    cfg, _, params = model_and_params
    router = _fleet(cfg, params, n=2, tmp=tmp_path)
    prompts = _prompts(seed=43, n=4)
    budgets = [10, 8, 9, 11]
    uids = [router.submit(p, max_new_tokens=n) for p, n in zip(prompts, budgets)]
    for _ in range(4):
        router.step()
    victim = next(n for n, h in router.replicas.items() if h.inner.has_work())
    # the replica vanishes without the router re-routing (simulates an
    # operator-level removal): detach its requests from router tracking
    h = router.replicas[victim]
    h.state = "dead"
    router._ring.remove(victim)
    dead_uids = [u for u, n in router._where.items() if n == victim]
    for u in dead_uids:
        del router._where[u]
    # journal-catch-up join: fresh replica + adopt the orphaned journal
    jdir = os.path.join(str(tmp_path), "joiner")
    router.join(
        _server(cfg, params, journal_dir=jdir),
        name="joiner",
        journal_dir=jdir,
        catchup_dir=h.journal_dir,
    )
    assert router.fleet_stats()["adopted"] >= len(dead_uids)
    router.run()
    assert router.fleet_stats()["migrated_token_divergence"] == 0
    _assert_oracle(router, cfg, params, prompts, budgets, uids)


# ---------------------------------------------------------------------------
# merged observability
# ---------------------------------------------------------------------------
def test_fleet_serve_stats_and_observability_merge(model_and_params):
    cfg, _, params = model_and_params
    from deepspeed_tpu.profiling.tracer import (
        MetricsRegistry,
        ObservabilityHub,
        Tracer,
    )

    tracer = Tracer(max_spans=4096)
    metrics = MetricsRegistry()
    router = _fleet(cfg, params, n=2, tracer=tracer, metrics=metrics)
    hub = ObservabilityHub(tracer, metrics)
    router.attach_observability(hub)
    prompts = _prompts(seed=47, n=4)
    router.serve(prompts, max_new_tokens=[5, 6, 4, 7])
    merged = router.serve_stats()
    per = merged["replicas"]
    assert len(per) == 2
    for key in ("finished", "emitted_tokens", "dispatches", "admitted"):
        assert merged[key] == sum(rep[key] for rep in per.values()), key
    assert merged["dispatches_per_token"] == pytest.approx(
        merged["dispatches"] / merged["emitted_tokens"]
    )
    assert merged["tenants"]["default"]["finished"] == 4
    assert merged["tenants"]["default"]["ttft_ms"]["count"] == 4
    assert 0.0 <= merged["prefix"]["prefix_hit_rate"] <= 1.0
    assert merged["fleet"]["n_active"] == 2
    # the hub's merged report carries the fleet source + router spans
    report = hub.report()
    assert report["fleet"]["fleet"]["routed"] == 4
    names = {s["name"] for s in tracer.spans()}
    assert "fleet.step" in names and "fleet.replica_step" in names
    assert "fleet.route" in names


# ---------------------------------------------------------------------------
# the real thing: kill -9 a fleet process, adopt the journals, finish
# ---------------------------------------------------------------------------
REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

_FLEET_CHILD_PRELUDE = """
import os, sys, json
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.environ["DS_TEST_REPO"])
import numpy as np
import jax
import jax.numpy as jnp
from deepspeed_tpu.inference.fleet import FleetRouter, ReplicaHandle
from deepspeed_tpu.inference.journal import RequestJournal
from deepspeed_tpu.inference.scheduler import PagedServer
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.utils import chaos

WORKDIR = os.environ["DS_TEST_DIR"]
cfg = TransformerConfig(
    vocab_size=128, hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
    max_seq_len=64, norm="rmsnorm", position="rope", activation="swiglu",
    use_bias=False, tie_embeddings=False, flash_attention=False, dtype="float32")
model = TransformerLM(cfg)
toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
params = model.init(jax.random.PRNGKey(0), toks)

def server(jdir):
    return PagedServer(cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
                       attn_impl="xla", dtype=jnp.float32, prefix_cache=True,
                       journal=RequestJournal(jdir))

rs = np.random.RandomState(7)
sysp = rs.randint(0, 128, (16,)).astype(np.int32)
prompts = []
for i in range(6):
    tail = rs.randint(0, 128, (int(rs.randint(3, 8)),)).astype(np.int32)
    prompts.append(np.concatenate([sysp, tail]) if i % 2 == 0 else tail)
budgets = [10, 7, 12, 8, 9, 11]
"""

_FLEET_KILL_CHILD = _FLEET_CHILD_PRELUDE + """
dirs = [os.path.join(WORKDIR, f"r{i}") for i in range(3)]
router = FleetRouter([
    ReplicaHandle(name=f"r{i}", server=server(d), journal_dir=d)
    for i, d in enumerate(dirs)
])
for p, n in zip(prompts, budgets):
    router.submit(p, max_new_tokens=n)
# a REAL kill -9 of the whole fleet process at a replica's step arrival
chaos.install(chaos.ChaosSchedule(
    [chaos.ChaosRule("fleet.replica_kill", hit=int(os.environ["DS_TEST_HIT"]),
                     action="exit")]))
router.run()
print("NOCRASH")
"""

_FLEET_RECOVER_CHILD = _FLEET_CHILD_PRELUDE + """
# the restart: FRESH replicas on FRESH journals; every pre-crash journal is
# adopted (journal-catch-up), outstanding requests re-distributed, finished
# results restored — then the fleet runs everything to completion
dirs = [os.path.join(WORKDIR, f"n{i}") for i in range(2)]
router = FleetRouter([
    ReplicaHandle(name=f"n{i}", server=server(d), journal_dir=d)
    for i, d in enumerate(dirs)
])
for i in range(3):
    router.adopt_journal(os.path.join(WORKDIR, f"r{i}"))
router.run()
outs = sorted(out.tolist() for out in router._results.values())
assert router.fleet_stats()["migrated_token_divergence"] == 0
print("RESULTS " + json.dumps(outs))
"""


@pytest.mark.slow
@pytest.mark.parametrize("hit", [3, 7])
def test_fleet_kill9_restart_adopts_journals_byte_identical(
    model_and_params, tmp_path, hit
):
    """The maximum-fidelity case: the whole fleet process dies by a real
    ``os._exit(137)`` at a deterministic replica-step arrival; a fresh
    process adopts every journal and finishes all six streams
    byte-identically to the dense oracle."""
    cfg, _, params = model_and_params
    env = dict(os.environ)
    env.update({
        "DS_TEST_REPO": REPO,
        "DS_TEST_DIR": str(tmp_path),
        "DS_TEST_HIT": str(hit),
    })
    proc = subprocess.run(
        [sys.executable, "-c", _FLEET_KILL_CHILD], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 137, (
        f"kill did not fire (rc={proc.returncode}):\n{proc.stdout[-2000:]}"
        f"\n{proc.stderr[-2000:]}"
    )
    assert "NOCRASH" not in proc.stdout

    proc2 = subprocess.run(
        [sys.executable, "-c", _FLEET_RECOVER_CHILD], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=420,
    )
    assert proc2.returncode == 0, proc2.stdout[-2000:] + proc2.stderr[-2000:]
    line = next(
        l for l in proc2.stdout.splitlines() if l.startswith("RESULTS ")
    )
    outs = json.loads(line[len("RESULTS "):])
    # the oracle, in-process: same prompts, uninterrupted dense decode
    rs = np.random.RandomState(7)
    sysp = rs.randint(0, 128, (16,)).astype(np.int32)
    prompts = []
    for i in range(6):
        tail = rs.randint(0, 128, (int(rs.randint(3, 8)),)).astype(np.int32)
        prompts.append(np.concatenate([sysp, tail]) if i % 2 == 0 else tail)
    budgets = [10, 7, 12, 8, 9, 11]
    want = sorted(
        _dense(cfg, params, p, n).tolist() for p, n in zip(prompts, budgets)
    )
    assert outs == want
