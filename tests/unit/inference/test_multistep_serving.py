"""Multi-step in-program serving windows (ISSUE 11): N decode rounds fused
into ONE dispatch, host gap amortized to 1/N.

Load-bearing checks: with ``paged_kv.multi_step`` armed, steady-state
decode (no scheduling events) dispatches ONE ``build_ragged_multistep``
program per ``horizon`` tokens per row — measured through compile
telemetry as dispatches/token ≤ 1/horizon — while the greedy streams stay
BYTE-IDENTICAL to the single-step ragged path, the bucketed per-shape
oracle, and dense lockstep ``decode.generate``; any scheduling event
(admission, prefill, drafts, pool pressure) breaks the window back to the
single-step path and ``window_break_reasons`` names it. EOS inside a
window, finish exactly at the window edge, admission breaking a window,
preemption + chunk-grid resume, and prefix-cache attach are each pinned
against the oracles. The companion analysis gate lives in
``tests/unit/analysis/test_passes.py::test_green_multistep_window_program_and_compile_gate``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.inference import decode
from deepspeed_tpu.inference.scheduler import PagedServer, compiled_serving_programs
from deepspeed_tpu.inference.spec_decode import Drafter
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry

CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,  # GQA on the serving path
    max_seq_len=64,
    norm="rmsnorm",
    position="rope",
    activation="swiglu",
    use_bias=False,
    tie_embeddings=False,
    flash_attention=False,
    dtype="float32",
)
H = 4  # the armed horizon for every window server in this suite


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def _prompts(n, seed=0, lo=3, hi=20):
    rs = np.random.RandomState(seed)
    return [
        rs.randint(0, CFG["vocab_size"], (int(rs.randint(lo, hi)),)).astype(np.int32)
        for _ in range(n)
    ]


def _dense(cfg, params, prompt, n, eos=None):
    return np.asarray(decode.generate(cfg, params, prompt[None], n, eos_token_id=eos))[0]


def _server(cfg, params, multi_step=True, horizon=H, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("dtype", jnp.float32)
    ms = {"enable": True, "horizon": horizon} if multi_step else None
    return PagedServer(cfg, params, multi_step=ms, **kw)


# --- token exactness: window vs single-step vs bucketed vs dense ------------
def test_window_matches_singlestep_bucketed_and_dense(model_and_params):
    """The core exactness oracle: the same ragged request mix through the
    window path, the single-step ragged path, and the bucketed per-shape
    oracle — byte-identical streams, windows actually engaged, pool
    drained."""
    cfg, _, params = model_and_params
    prompts = _prompts(4, seed=2)
    budgets = [13, 9, 17, 12]
    windowed = _server(cfg, params)
    outs = windowed.serve(prompts, max_new_tokens=budgets)
    single = _server(cfg, params, multi_step=False)
    ragged_oracle = single.serve(prompts, max_new_tokens=budgets)
    bucketed = _server(cfg, params, multi_step=False, ragged=False)
    bucketed_oracle = bucketed.serve(prompts, max_new_tokens=budgets)
    for p, n, a, b, c in zip(prompts, budgets, outs, ragged_oracle, bucketed_oracle):
        np.testing.assert_array_equal(a, _dense(cfg, params, p, n))
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
    st = windowed.serve_stats()
    assert st["window_steps"] >= 2, st
    assert single.stats["window_steps"] == 0
    # the window server paid strictly fewer dispatches for the same tokens
    assert st["dispatches"] < single.stats["dispatches"]
    assert windowed.pool.used_pages() == 0 and windowed.pool.live_tokens() == 0
    windowed.pool.integrity_check()


def test_window_eos_inside(model_and_params):
    """EOS landing mid-window freezes the row in-program: it emits the EOS
    token and nothing after it, byte-identical to sequential decode, and
    the break is attributed to eos."""
    cfg, _, params = model_and_params
    prompts = _prompts(2, seed=7)
    futures = {i: _dense(cfg, params, p, 16) for i, p in enumerate(prompts)}
    # an EOS that fires a couple of windows in for row 0 — NOT on a window
    # edge (position prompt+6 with horizon 4: round 2 of window 2)
    eos = int(futures[0][prompts[0].size + 5])
    server = _server(cfg, params)
    outs = server.serve(prompts, max_new_tokens=16, eos_token_id=eos)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 16, eos=eos))
    st = server.serve_stats()
    assert st["window_steps"] >= 1
    assert st["window_break_reasons"]["eos"] >= 1, st["window_break_reasons"]


def test_window_finish_at_window_edge(model_and_params):
    """Budgets aligned so every row's last token lands exactly on a window
    edge: the fused program emits full windows, nothing falls back to the
    single-step tail, and no break is charged to budget."""
    cfg, _, params = model_and_params
    server = _server(cfg, params)
    prompts = _prompts(2, seed=3, lo=4, hi=7)  # single-chunk prompts
    # first token comes from the finishing prefill chunk; the remaining
    # 3*H tokens are exactly three full windows
    budget = 3 * H + 1
    outs = server.serve(prompts, max_new_tokens=budget)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, budget))
    st = server.serve_stats()
    assert st["window_steps"] == 3, st
    assert st["window_break_reasons"]["budget"] == 0, st["window_break_reasons"]
    assert st["window_break_reasons"]["eos"] == 0


def test_window_admission_breaks(model_and_params):
    """A submission arriving while windows are running breaks the next
    window (its TTFT is never parked behind a fused dispatch): the break
    is attributed to admission, the late request's chunks ride single-step
    dispatches, and every stream stays exact."""
    cfg, _, params = model_and_params
    server = _server(cfg, params)
    prompts = _prompts(6, seed=4)
    # fill every slot so late submissions actually QUEUE
    first = [server.submit(p, max_new_tokens=14) for p in prompts[:4]]
    # run until windows have engaged
    while server.stats["window_steps"] < 1:
        server.step()
    late = [server.submit(p, max_new_tokens=14) for p in prompts[4:]]
    results = server.run()
    for uid, p in zip(first + late, prompts):
        np.testing.assert_array_equal(results[uid], _dense(cfg, params, p, 14))
    br = server.serve_stats()["window_break_reasons"]
    assert br["admission"] >= 1, br  # queued-but-unadmittable broke windows
    assert br["prefill"] >= 1, br  # the late chunks broke windows too


def test_window_preemption_and_chunk_grid_resume(model_and_params):
    """An undersized pool: window reservation (a whole horizon of pages
    per row) hits pool pressure, breaks to the single-step path — which
    preempts — and the recomputed continuations stay byte-identical to
    the window-off oracle and dense."""
    cfg, _, params = model_and_params
    kw = dict(page_size=4, num_pages=14, max_slots=3, prefill_chunk=8)
    prompts = _prompts(4, seed=4, lo=6, hi=14)
    windowed = _server(cfg, params, **kw)
    outs = windowed.serve(prompts, max_new_tokens=12)
    assert windowed.stats["preempted"] >= 1, "pool was sized to force preemption"
    oracle = _server(cfg, params, multi_step=False, **kw).serve(
        prompts, max_new_tokens=12
    )
    for p, a, b in zip(prompts, outs, oracle):
        np.testing.assert_array_equal(a, _dense(cfg, params, p, 12))
        np.testing.assert_array_equal(a, b)
    assert windowed.pool.used_pages() == 0
    windowed.pool.integrity_check()


def test_window_pool_pressure_attributed_to_pool_reason(model_and_params):
    """Reservation pressure with NO queue and no prefill: the window break
    lands on the dedicated "pool" counter — never on "budget" (token
    budgets and page-pool pressure need opposite remediations) — the
    single-step fallback preempts as usual, and streams stay exact."""
    cfg, _, params = model_and_params
    # 2 slots, both admit at once (queue never forms), pool sized so the
    # rows outgrow it mid-decode: 9 allocatable pages × 4 tokens < the
    # two streams' peak demand
    kw = dict(page_size=4, num_pages=10, max_slots=2, prefill_chunk=8)
    prompts = _prompts(2, seed=12, lo=6, hi=10)
    server = _server(cfg, params, **kw)
    outs = server.serve(prompts, max_new_tokens=14)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 14))
    br = server.serve_stats()["window_break_reasons"]
    assert br["pool"] >= 1, br
    assert server.stats["preempted"] >= 1
    server.pool.integrity_check()


def test_window_prefix_cache_attach(model_and_params):
    """Warm prefix attaches ride underneath windows unchanged: the second
    serve of shared-prefix prompts attaches pages, windows still form, and
    streams match sharing-off serving byte for byte."""
    cfg, _, params = model_and_params
    rs = np.random.RandomState(21)
    sys_tokens = rs.randint(0, 128, (19,)).astype(np.int32)  # 2 pages + 3 mid-grid
    prompts = [
        np.concatenate([sys_tokens, rs.randint(0, 128, (3 + i,)).astype(np.int32)])
        for i in range(4)
    ]
    server = _server(cfg, params, prefix_cache=True)
    first = server.serve(prompts[:1], max_new_tokens=9)
    rest = server.serve(prompts[1:], max_new_tokens=9)
    assert server.pool.stats["prefix_hit_pages"] > 0, "prefix cache never engaged"
    assert server.stats["window_steps"] >= 1
    off = _server(cfg, params, multi_step=False, prefix_cache=False)
    oracle = off.serve(prompts, max_new_tokens=9)
    for p, a, b in zip(prompts, first + rest, oracle):
        np.testing.assert_array_equal(a, _dense(cfg, params, p, 9))
        np.testing.assert_array_equal(a, b)
    server.pool.integrity_check()


class FadingDrafter(Drafter):
    """Drafts the precomputed greedy future only while the context is
    short: early rounds speculate (windows must break on 'draft'), later
    rounds propose nothing (windows must form). Exercises the
    window/speculation handoff incl. the one-proposal-per-step contract."""

    def __init__(self, futures, fade_at):
        self.futures = futures
        self.fade_at = fade_at
        self.calls = []  # (uid, context length) per proposal

    def propose(self, uid, context, k):
        self.calls.append((uid, context.size))
        if context.size >= self.fade_at:
            return np.zeros(0, np.int32)
        return self.futures[uid][context.size : context.size + k].astype(np.int32)


def test_window_coexists_with_spec_decode(model_and_params):
    """Speculation and windows share the serve: drafted rounds verify
    through the single-step path (break reason 'draft'), quiet rounds fuse
    into windows — and the streams stay byte-identical to dense. The
    drafter is consulted at most once per scheduler step (the failed
    window probe hands its proposals to the fallback)."""
    cfg, _, params = model_and_params
    prompts = _prompts(2, seed=5, lo=4, hi=7)
    budget = 18
    futures = {i: _dense(cfg, params, p, budget) for i, p in enumerate(prompts)}
    fade_at = max(p.size for p in prompts) + 4
    drafter = FadingDrafter(futures, fade_at)
    server = _server(
        cfg, params, drafter=drafter, spec_decode={"max_draft": 3}
    )
    outs = server.serve(prompts, max_new_tokens=budget)
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(out, futures[i])
    st = server.serve_stats()
    assert st["spec_rounds"] >= 1, "speculation never engaged"
    assert st["window_steps"] >= 1, "windows never formed after the drafts faded"
    assert st["window_break_reasons"]["draft"] >= 1, st["window_break_reasons"]
    # the drafter is asked at most ONCE per request per step: a window
    # probe that breaks on 'draft' hands its proposals to the fallback
    # instead of re-asking — a double-ask would repeat the same
    # (uid, context length) pair, since no token lands in between
    assert len(drafter.calls) == len(set(drafter.calls)), drafter.calls


def test_window_forms_with_near_finished_row_at_seq_cap(model_and_params):
    """A row parked near max_seq_len whose remaining budget fits (but
    whose len + horizon would NOT) must not break windows forever: the
    reservation asks min(horizon, remaining budget) per row — the
    in-program budget freeze bounds the row's writes to its budget."""
    cfg, _, params = model_and_params
    rs = np.random.RandomState(30)
    # 61 + budget 2 = 63 ≤ max_seq_len 64, but 61 + horizon 4 = 65 > 64:
    # an un-clamped reservation can NEVER make this row writable
    long_p = rs.randint(0, 128, (61,)).astype(np.int32)
    short_p = rs.randint(0, 128, (6,)).astype(np.int32)
    server = _server(cfg, params)
    uids = [server.submit(short_p, max_new_tokens=3 * H + 1),
            server.submit(long_p, max_new_tokens=2)]
    # drive past prefill (the short row decodes inside the long row's
    # chunk dispatches there — single-step by design)
    while server._queue or any(r.pending is None for r in server._active):
        server.step()
    assert len(server._active) == 2  # the capped row is still live
    # the very first stable step must FUSE: the capped row's clamped
    # reservation (len + its 1-token budget) fits max_seq_len, so it
    # freezes at its budget inside the window — an un-clamped len + H
    # reservation overflows the cap and would force this step (and the
    # capped row's retirement) through a single-step decode dispatch
    server.step()
    assert server.stats["window_steps"] == 1, server.serve_stats()
    results = server.run()
    np.testing.assert_array_equal(
        results[uids[0]], _dense(cfg, params, short_p, 3 * H + 1)
    )
    np.testing.assert_array_equal(results[uids[1]], _dense(cfg, params, long_p, 2))


# --- the dispatch-amortization gate -----------------------------------------
def test_steady_state_dispatches_per_token_le_one_over_horizon(model_and_params):
    """THE acceptance gate: once the running set is stable (prefill done,
    queue empty), compile telemetry measures dispatches/token ≤ 1/horizon
    — each window is ONE ``paged_multistep_*`` dispatch covering horizon
    rounds — and the serving program set stays ≤ 4."""
    cfg, _, params = model_and_params
    telemetry = CompileTelemetry()
    server = _server(cfg, params, telemetry=telemetry)
    prompts = _prompts(2, seed=5, lo=4, hi=7)
    for p in prompts:
        server.submit(p, max_new_tokens=3 * H + 1)
    # drive to the steady state: everything admitted and past prefill
    while server._queue or any(r.pending is None for r in server._active):
        server.step()
    disp_before = sum(
        r["dispatches"] for n, r in telemetry.stats().items()
        if n.startswith("paged_")
    )
    tok_before = server.stats["emitted_tokens"]
    server.run()
    stats = telemetry.stats()
    disp = sum(
        r["dispatches"] for n, r in stats.items() if n.startswith("paged_")
    ) - disp_before
    toks = server.stats["emitted_tokens"] - tok_before
    assert toks == 2 * 3 * H
    assert disp / toks <= 1.0 / H, (disp, toks)
    # every steady-state dispatch was the fused window program
    assert disp == server.stats["window_steps"]
    assert compiled_serving_programs(stats) <= 4, stats
    assert any(n.startswith("paged_multistep_") for n in stats), stats.keys()


def test_window_retrace_guard_and_program_budget(model_and_params):
    """3 waves of shifting mixes through one telemetry: the window program
    compiles once (warmup aside, no wave adds a compile), total serving
    programs ≤ 4 (narrow + mixed + one window program for the single armed
    horizon), and telemetry dispatch counts reconcile with the scheduler's
    own dispatch counter."""
    cfg, _, params = model_and_params
    telemetry = CompileTelemetry()
    server = _server(cfg, params, telemetry=telemetry)
    waves = [_prompts(2, seed=6), _prompts(4, seed=7), _prompts(2, seed=8)]
    compiles = []
    for wave in waves:
        outs = server.serve(wave, max_new_tokens=11)
        for p, out in zip(wave, outs):
            np.testing.assert_array_equal(out, _dense(cfg, params, p, 11))
        compiles.append(sum(r["compiles"] for r in telemetry.stats().values()))
    stats = telemetry.stats()
    assert compiled_serving_programs(stats) <= 4, stats
    assert compiles[1] == compiles[0] and compiles[2] == compiles[0], compiles
    for name, rec in stats.items():
        assert rec["compiles"] <= 1, f"{name} recompiled: {rec}"
    assert server.stats["window_steps"] >= 1
    total = sum(r["dispatches"] for r in stats.values())
    assert total == server.stats["dispatches"]


def test_windows_add_zero_host_transfers_and_zero_programs_when_traced(
    model_and_params
):
    """Telemetry-free contract, window edition: serving the same trace
    with tracing ON compiles the identical program set (tracing adds zero
    programs), the streams match, and the fetch accounting closes — the
    packed token matrix is the ONE sanctioned fetch per window, so the
    window path's host fetches equal its dispatches exactly (no hidden
    per-token or per-round transfer)."""
    from deepspeed_tpu.profiling.tracer import MetricsRegistry, Tracer

    cfg, _, params = model_and_params
    prompts = _prompts(3, seed=9)
    sets = {}
    outs = {}
    for traced in (False, True):
        telemetry = CompileTelemetry()
        kw = {}
        if traced:
            kw = dict(tracer=Tracer(enabled=True), metrics=MetricsRegistry())
        server = _server(cfg, params, telemetry=telemetry, **kw)
        outs[traced] = server.serve(prompts, max_new_tokens=3 * H + 1)
        sets[traced] = sorted(telemetry.stats().keys())
        assert server.stats["window_steps"] >= 1
    assert sets[True] == sets[False], sets
    for a, b in zip(outs[True], outs[False]):
        np.testing.assert_array_equal(a, b)


# --- stats / config / engine surface ----------------------------------------
def test_window_stats_block(model_and_params):
    """serve_stats() carries the window observability block: window_steps,
    the armed horizon, dispatches_per_token (strictly amortized below the
    single-step path's), and the break-reason counters."""
    cfg, _, params = model_and_params
    server = _server(cfg, params)
    prompts = _prompts(2, seed=10, lo=4, hi=7)
    server.serve(prompts, max_new_tokens=3 * H + 1)
    st = server.serve_stats()
    assert st["window_horizon"] == H
    assert st["window_steps"] >= 1
    assert 0.0 < st["dispatches_per_token"] < 1.0
    assert set(st["window_break_reasons"]) == {
        "admission", "prefill", "draft", "eos", "budget", "pool"
    }
    single = _server(cfg, params, multi_step=False)
    single.serve(prompts, max_new_tokens=3 * H + 1)
    sst = single.serve_stats()
    assert sst["window_horizon"] == 0 and sst["window_steps"] == 0
    assert st["dispatches_per_token"] < sst["dispatches_per_token"]


def test_multistep_config_validation(model_and_params):
    cfg, _, params = model_and_params
    with pytest.raises(ValueError, match="horizon"):
        _server(cfg, params, horizon=1)
    with pytest.raises(ValueError, match="ragged"):
        _server(cfg, params, ragged=False)
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    with pytest.raises(ValueError, match="multi_step"):
        DeepSpeedInferenceConfig(
            paged_kv={"ragged": False, "multi_step": {"enable": True}}
        )
    with pytest.raises(ValueError, match="horizon"):
        DeepSpeedInferenceConfig(
            paged_kv={"multi_step": {"enable": True, "horizon": 1}}
        )
    # horizon validates only when armed (parity with the other sub-blocks)
    DeepSpeedInferenceConfig(paged_kv={"multi_step": {"horizon": 1}})


def test_multistep_knob_through_engine(model_and_params, tmp_path):
    """inference.paged_kv.multi_step routes the engine's serve() through
    windows (byte-identical to the un-windowed engine), serve_stats()
    surfaces the window block, and the flight recorder's dump names the
    armed horizon so postmortems can read the window config."""
    cfg, model, params = model_and_params
    outs = {}
    for enable in (True, False):
        engine = ds.init_inference(
            model,
            dtype="fp32",
            paged_kv={"page_size": 8, "max_slots": 4, "prefill_chunk": 8,
                      "attn_impl": "xla",
                      "multi_step": {"enable": enable, "horizon": H}},
            tracing={"flight_recorder": True,
                     "flight_recorder_dir": str(tmp_path / str(enable))},
        )
        engine.set_params(params)
        engine._ds_config = cfg  # converted-family contract
        prompts = _prompts(3, seed=11)
        outs[enable] = engine.serve(prompts, max_new_tokens=3 * H + 1)
        st = engine.serve_stats()
        if enable:
            assert st["window_steps"] >= 1
            assert any(
                n.startswith("paged_multistep_") for n in engine.compile_stats()
            )
            rec = engine.observability_hub.flight_recorder
            assert rec.context["serve.multi_step"]["horizon"] == H
            import json

            path = rec.dump(reason="test")
            payload = json.loads(open(path).read())
            assert payload["context"]["serve.multi_step"]["horizon"] == H
        else:
            assert st["window_steps"] == 0
            # the context reflects the CURRENT build — a rebuild with
            # windows disabled must not leave a stale armed-horizon claim
            rec = engine.observability_hub.flight_recorder
            assert rec.context["serve.multi_step"]["enable"] is False
        engine.observability_hub.flight_recorder.uninstall()
    for a, b in zip(outs[True], outs[False]):
        np.testing.assert_array_equal(a, b)
