"""Sampling (temperature / top-k / top-p) + cached-rollout speed tests.

Reference analog: the HF LogitsProcessor semantics the reference reaches
through ``deepspeed/inference/engine.py:578`` generate dispatch, and the
hybrid engine's fast cached rollouts (``deepspeed/runtime/hybrid_engine.py:32``).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.sampling import (
    sample_logits,
    top_k_filter,
    top_p_filter,
)

NEG = -1e29  # anything below this counts as filtered


def test_top_k_filter_keeps_k_largest():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
    out = np.asarray(top_k_filter(logits, 2))
    assert (out[0] > NEG).sum() == 2
    assert out[0, 1] == 5.0 and out[0, 4] == 4.0


def test_top_p_filter_nucleus():
    # probs ~ [0.643, 0.237, 0.087, 0.032] → p=0.8 keeps the first two
    logits = jnp.log(jnp.asarray([[0.643, 0.237, 0.087, 0.032]]))
    out = np.asarray(top_p_filter(logits, 0.8))
    assert (out[0] > NEG).sum() == 2
    # the top token survives even when its prob alone exceeds p — or p is 0
    for p in (0.1, 0.0):
        out_tiny = np.asarray(top_p_filter(logits, p))
        assert (out_tiny[0] > NEG).sum() == 1 and out_tiny[0, 0] > NEG


def test_greedy_when_temperature_zero():
    logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 1.0]])
    toks = np.asarray(sample_logits(logits, jax.random.PRNGKey(0), temperature=0.0))
    np.testing.assert_array_equal(toks, [1, 0])


def test_sampling_respects_filters():
    rng = jax.random.PRNGKey(0)
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 4.0]] * 64)
    rngs = jax.random.split(rng, 32)
    for r in rngs:
        toks = np.asarray(
            sample_logits(logits, r, temperature=1.0, top_k=2)
        )
        assert np.isin(toks, [3, 4]).all(), "top-k=2 must only emit the two best"
    for r in rngs:
        toks = np.asarray(
            sample_logits(logits, r, temperature=1.0, top_p=0.05)
        )
        assert (toks == 4).all(), "tiny nucleus degenerates to greedy"


def test_sampling_reproducible_same_key():
    logits = jax.random.normal(jax.random.PRNGKey(3), (4, 97))
    a = sample_logits(logits, jax.random.PRNGKey(7), temperature=0.9, top_k=40, top_p=0.95)
    b = sample_logits(logits, jax.random.PRNGKey(7), temperature=0.9, top_k=40, top_p=0.95)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
class TestCachedGeneration:
    def _model(self, max_seq_len=256):
        from deepspeed_tpu.models import TransformerLM
        from deepspeed_tpu.models.config import TransformerConfig

        cfg = TransformerConfig(
            vocab_size=128,
            hidden_size=32,
            num_layers=2,
            num_heads=4,
            max_seq_len=max_seq_len,
            dtype="float32",
            flash_attention=False,
        )
        model = TransformerLM(cfg)
        toks = jnp.zeros((1, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks)
        return model, cfg, params

    def test_cached_sampled_generation_reproducible(self):
        from deepspeed_tpu.inference.decode import generate

        _, cfg, params = self._model()
        prompts = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 8)), jnp.int32)
        a = generate(cfg, params, prompts, 12, temperature=0.8, top_k=20,
                     top_p=0.9, rng=jax.random.PRNGKey(5))
        b = generate(cfg, params, prompts, 12, temperature=0.8, top_k=20,
                     top_p=0.9, rng=jax.random.PRNGKey(5))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.shape == (2, 20)

    def test_cached_greedy_matches_full_forward_loop(self):
        """The on-device while-loop decode must emit the same greedy tokens
        as the full-forward reference loop (cached decode ≡ full forward)."""
        from deepspeed_tpu.inference.decode import generate
        from deepspeed_tpu.inference.generation import greedy_generate

        model, cfg, params = self._model()
        prompts = jnp.asarray(np.random.RandomState(1).randint(0, 128, (2, 8)), jnp.int32)
        cached = generate(cfg, params, prompts, 10)

        def apply_fn(p, t, rng):
            return model.apply(p, t, train=False)

        full = greedy_generate(apply_fn, params, prompts, 10, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(full))

    def test_eos_early_exit_on_device(self):
        """Rows that hit EOS keep emitting EOS; the loop exits early (the
        returned length ≤ prompt + max_new) without per-token host syncs."""
        from deepspeed_tpu.inference.decode import generate

        _, cfg, params = self._model()
        prompts = jnp.asarray(np.random.RandomState(2).randint(0, 128, (2, 8)), jnp.int32)
        greedy = generate(cfg, params, prompts, 6)
        eos = int(np.asarray(greedy)[0, 9])  # token the model WILL emit at step 2
        out = np.asarray(generate(cfg, params, prompts, 24, eos_token_id=eos))
        row0 = out[0, 8:]
        hit = np.nonzero(row0 == eos)[0]
        assert hit.size, "eos never emitted"
        # everything after the first EOS in row 0 is EOS padding
        assert (row0[hit[0]:] == eos).all()

    def test_hybrid_rollout_uses_cached_decoder_and_is_fast(self, eight_devices):
        """The DS-Chat property: rollouts at long context must come from the
        KV-cached path — ≥5× the full-forward-per-token loop at 2k context."""
        import deepspeed_tpu as ds
        import deepspeed_tpu.parallel.mesh as mesh_mod
        from deepspeed_tpu.inference.generation import greedy_generate
        from deepspeed_tpu.models import TransformerLM
        from deepspeed_tpu.models.config import TransformerConfig

        mesh_mod.reset_topology()
        cfg = TransformerConfig(
            vocab_size=256,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            max_seq_len=2176,
            dtype="float32",
            flash_attention=False,
        )
        engine, *_ = ds.initialize(
            model=TransformerLM(cfg),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "hybrid_engine": {"enabled": True, "max_out_tokens": 32},
            },
        )
        rs = np.random.RandomState(0)
        prompts = rs.randint(0, 256, (1, 2048)).astype(np.int32)
        engine.init_params(jnp.asarray(prompts))
        n_new = 32

        # warm both paths (compile), then time steady-state
        engine.generate(prompts, max_new_tokens=n_new)
        t0 = time.perf_counter()
        out = engine.generate(prompts, max_new_tokens=n_new)
        cached_s = time.perf_counter() - t0
        assert out.shape == (1, 2048 + n_new)

        module = engine.module

        def apply_fn(p, t, rng):
            return module.apply(p, t, train=False)

        cache = {}
        greedy_generate(apply_fn, engine._params, prompts, n_new,
                        jax.random.PRNGKey(0), jit_cache=cache)
        t0 = time.perf_counter()
        full = greedy_generate(apply_fn, engine._params, prompts, n_new,
                               jax.random.PRNGKey(0), jit_cache=cache)
        full_s = time.perf_counter() - t0

        np.testing.assert_array_equal(np.asarray(out), np.asarray(full))
        assert full_s / cached_s >= 5.0, (
            f"cached rollout only {full_s / cached_s:.1f}x faster "
            f"({cached_s:.3f}s vs {full_s:.3f}s)"
        )
