"""MoE expert-parallel inference + ZeRO-Inference tests.

Reference analogs: expert groups in ``deepspeed/inference/engine.py:217,230``
(here: GSPMD expert-axis placement) and ZeRO-Inference
(``deepspeed/runtime/engine.py:1499-1520`` — stage-3 offload without an
optimizer; here: the layer-stream store driving eval programs).
"""

from __future__ import annotations

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.models.moe_transformer import MoETransformerConfig, MoETransformerLM
from deepspeed_tpu.models.transformer import TransformerLM


class TestMoEInference:
    def test_generate_with_expert_axis(self, eight_devices):
        """MoE inference on an expert-parallel mesh: params place over the
        'expert' axis, generate runs the dispatch all-to-alls."""
        mesh_mod.reset_topology()
        from deepspeed_tpu.runtime.config import MeshConfig
        mesh_mod.initialize_topology(MeshConfig(expert=2, data=4))
        model = MoETransformerLM(
            MoETransformerConfig(
                vocab_size=64,
                hidden_size=16,
                num_layers=2,
                num_heads=2,
                num_experts=2,
                max_seq_len=32,
                dtype="float32",
                flash_attention=False,
            )
        )
        engine = ds.init_inference(model, dtype="fp32")
        toks = np.random.RandomState(0).randint(0, 64, (8, 4)).astype(np.int32)
        engine.init_params(toks)
        # expert leaves actually live on the expert axis
        experts = engine._params["layers"]["moe"]["experts"]["w_in"]
        assert "expert" in str(experts.sharding.spec), experts.sharding.spec
        out = np.asarray(engine.generate(toks, max_new_tokens=4))
        assert out.shape == (8, 8)
        np.testing.assert_array_equal(out[:, :4], toks)

    def test_forward_logits(self, eight_devices):
        mesh_mod.reset_topology()
        from deepspeed_tpu.runtime.config import MeshConfig
        mesh_mod.initialize_topology(MeshConfig(expert=2, data=4))
        model = MoETransformerLM(
            MoETransformerConfig(
                vocab_size=64,
                hidden_size=16,
                num_layers=2,
                num_heads=2,
                num_experts=2,
                dtype="float32",
                flash_attention=False,
            )
        )
        engine = ds.init_inference(model, dtype="fp32")
        toks = np.random.RandomState(1).randint(0, 64, (8, 6)).astype(np.int32)
        logits = np.asarray(engine(toks))
        assert logits.shape == (8, 6, 64)
        assert np.isfinite(logits).all()


class TestZeroInference:
    CFG = dict(
        vocab_size=64,
        hidden_size=16,
        num_layers=3,
        num_heads=2,
        max_seq_len=32,
        dtype="float32",
        flash_attention=False,
    )

    def _engine(self):
        mesh_mod.reset_topology()
        model = TransformerLM(TransformerConfig(**self.CFG))
        return ds.init_inference(
            model,
            dtype="fp32",
            zero={"stage": 3, "offload_param": {"device": "cpu"}},
        )

    def test_params_stay_off_chip(self, eight_devices):
        engine = self._engine()
        toks = np.random.RandomState(0).randint(0, 64, (8, 8)).astype(np.int32)
        logits = np.asarray(engine(toks))
        assert logits.shape == (8, 8, 64)
        assert engine._param_stream is not None
        assert engine._params is None  # nothing pinned in HBM
        # no optimizer state was allocated (inference never steps)
        assert all(st.exp_avg is None for st in engine._param_stream._layer_state)

    def test_matches_in_hbm_forward(self, eight_devices):
        engine = self._engine()
        toks = np.random.RandomState(1).randint(0, 64, (8, 8)).astype(np.int32)
        stream_logits = np.asarray(engine(toks))
        host_params = engine._param_stream.gathered_params()

        mesh_mod.reset_topology()
        plain = ds.init_inference(TransformerLM(TransformerConfig(**self.CFG)), dtype="fp32")
        plain.set_params(host_params)
        plain_logits = np.asarray(plain(toks))
        np.testing.assert_allclose(stream_logits, plain_logits, rtol=1e-5, atol=1e-5)

    def test_generate(self, eight_devices):
        engine = self._engine()
        toks = np.random.RandomState(2).randint(0, 64, (8, 4)).astype(np.int32)
        out = np.asarray(engine.generate(toks, max_new_tokens=4))
        assert out.shape == (8, 8)
        np.testing.assert_array_equal(out[:, :4], toks)


class TestWeightQuantInference:
    def test_quant_flag_changes_numerics_within_tolerance(self, eight_devices):
        mesh_mod.reset_topology()
        cfg_m = dict(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            dtype="float32", flash_attention=False,
        )
        toks = np.random.RandomState(0).randint(0, 64, (8, 8)).astype(np.int32)

        plain = ds.init_inference(TransformerLM(TransformerConfig(**cfg_m)), dtype="fp32")
        plain.init_params(toks)
        base = np.asarray(plain(toks))

        mesh_mod.reset_topology()
        quant = ds.init_inference(
            TransformerLM(TransformerConfig(**cfg_m)),
            dtype="fp32",
            quant={"enabled": True, "num_bits": 8, "group_size": 32},
        )
        quant.init_params(toks)
        q_out = np.asarray(quant(toks))
        assert not np.array_equal(q_out, base), "quant flag was silently ignored"
        np.testing.assert_allclose(q_out, base, rtol=0.2, atol=0.5)
