"""Launcher parsing tests (reference: ``tests/unit/launcher/test_run.py``)."""

from __future__ import annotations

import base64
import json

import pytest

from deepspeed_tpu.launcher.launch import decode_world_info, encode_world_info
from deepspeed_tpu.launcher.runner import (
    fetch_hostfile,
    parse_args,
    parse_resource_filter,
)


def _hostfile(tmp_path, text):
    p = tmp_path / "hostfile"
    p.write_text(text)
    return str(p)


class TestHostfile:
    def test_parse(self, tmp_path):
        path = _hostfile(tmp_path, "worker-0 slots=4\nworker-1 slots=8\n")
        pool = fetch_hostfile(path)
        assert pool == {"worker-0": 4, "worker-1": 8}

    def test_comments_and_blanks(self, tmp_path):
        path = _hostfile(tmp_path, "# comment\n\nworker-0 slots=2\n")
        assert fetch_hostfile(path) == {"worker-0": 2}

    def test_missing_file(self):
        assert fetch_hostfile("/nonexistent/hostfile") == {}

    def test_malformed_raises(self, tmp_path):
        path = _hostfile(tmp_path, "worker-0 slots=banana\n")
        with pytest.raises(ValueError):
            fetch_hostfile(path)

    def test_duplicate_raises(self, tmp_path):
        path = _hostfile(tmp_path, "w slots=1\nw slots=2\n")
        with pytest.raises(ValueError):
            fetch_hostfile(path)


class TestResourceFilter:
    POOL = {"worker-0": 4, "worker-1": 4}

    def test_no_filter(self):
        out = parse_resource_filter(self.POOL)
        assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [0, 1, 2, 3]}

    def test_include_host(self):
        out = parse_resource_filter(self.POOL, include_str="worker-0")
        assert out == {"worker-0": [0, 1, 2, 3]}

    def test_include_slots(self):
        out = parse_resource_filter(self.POOL, include_str="worker-1:0,2")
        assert out == {"worker-1": [0, 2]}

    def test_exclude_host(self):
        out = parse_resource_filter(self.POOL, exclude_str="worker-1")
        assert out == {"worker-0": [0, 1, 2, 3]}

    def test_exclude_slots(self):
        out = parse_resource_filter(self.POOL, exclude_str="worker-0:1,3")
        assert out["worker-0"] == [0, 2]

    def test_both_raises(self):
        with pytest.raises(ValueError):
            parse_resource_filter(self.POOL, include_str="worker-0", exclude_str="worker-1")

    def test_unknown_host_raises(self):
        with pytest.raises(ValueError):
            parse_resource_filter(self.POOL, include_str="worker-9")


class TestWorldInfo:
    def test_roundtrip(self):
        info = {"worker-0": [0, 1], "worker-1": [0, 1]}
        enc = encode_world_info(info)
        assert decode_world_info(enc) == info
        # stable b64 json, inspectable by hand
        assert json.loads(base64.urlsafe_b64decode(enc)) == info

    def test_none(self):
        assert decode_world_info("None") == {}


class TestArgs:
    def test_defaults(self):
        args = parse_args(["train.py"])
        assert args.launcher == "pdsh"
        assert args.user_script == "train.py"
        assert args.master_port == 29500

    def test_user_args_passthrough(self):
        args = parse_args(["train.py", "--lr", "0.1", "--deepspeed"])
        assert args.user_args == ["--lr", "0.1", "--deepspeed"]

    def test_include(self):
        args = parse_args(["-i", "worker-0:0", "train.py"])
        assert args.include == "worker-0:0"
