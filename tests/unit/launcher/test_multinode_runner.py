"""Multinode runner command construction (reference:
``tests/unit/launcher/test_multinode_runner.py``)."""

from __future__ import annotations

from copy import deepcopy

import pytest

from deepspeed_tpu.launcher.multinode_runner import (
    MPICHRunner,
    OpenMPIRunner,
    PDSHRunner,
    SlurmRunner,
)
from deepspeed_tpu.launcher.runner import parse_args


@pytest.fixture
def runner_info():
    hosts = {"worker-0": 4, "worker-1": 4}
    world_info = "SGVsbG8gV29ybGQ="
    env = {"PATH": "/usr/bin"}
    args = parse_args(["test_launcher.py", "--launcher_arg", "1"])
    return env, hosts, world_info, args


def test_pdsh_runner(runner_info):
    env, resource_pool, world_info, args = runner_info
    runner = PDSHRunner(args, world_info)
    cmd = runner.get_cmd(env, {"worker-0": [0, 1], "worker-1": [0, 1]})
    assert cmd[0] == "pdsh"
    assert "-w" in cmd
    assert "worker-0,worker-1" in cmd
    assert "deepspeed_tpu.launcher.launch" in cmd
    assert env["PDSH_RCMD_TYPE"] == "ssh"
    assert cmd[-3:] == ["test_launcher.py", "--launcher_arg", "1"]


def test_pdsh_runner_exports(runner_info):
    env, resource_pool, world_info, args = runner_info
    runner = PDSHRunner(args, world_info)
    runner.add_export("JAX_PLATFORMS", "tpu")
    cmd = runner.get_cmd(env, {"worker-0": [0]})
    joined = " ".join(cmd)
    assert "export JAX_PLATFORMS=tpu;" in joined


def test_openmpi_runner(runner_info):
    env, resource_pool, world_info, args = runner_info
    runner = OpenMPIRunner(args, world_info, resource_pool)
    cmd = runner.get_cmd(env, resource_pool)
    assert cmd[0] == "mpirun"
    assert "-n" in cmd
    assert cmd[cmd.index("-n") + 1] == "2"  # one proc per host
    assert "test_launcher.py" in cmd


def test_openmpi_rejects_include(runner_info):
    env, resource_pool, world_info, _ = runner_info
    args = parse_args(["-i", "worker-0", "test_launcher.py"])
    runner = OpenMPIRunner(args, world_info, resource_pool)
    with pytest.raises(ValueError):
        runner.validate_args()


def test_mpich_runner(runner_info):
    env, resource_pool, world_info, args = runner_info
    runner = MPICHRunner(args, world_info, resource_pool)
    cmd = runner.get_cmd(env, resource_pool)
    assert cmd[0] == "mpirun"
    assert "-ppn" in cmd
    assert cmd[cmd.index("-ppn") + 1] == "1"


def test_slurm_runner(runner_info):
    env, resource_pool, world_info, args = runner_info
    runner = SlurmRunner(args, world_info, resource_pool)
    cmd = runner.get_cmd(env, resource_pool)
    assert cmd[0] == "srun"
    assert "--ntasks-per-node=1" in cmd
