"""CLI argument helpers (reference: tests/unit/launcher/test_ds_arguments.py):
add_config_arguments wires --deepspeed/--deepspeed_config plus the hidden
legacy --deepscale aliases onto a user parser."""

import argparse

import deepspeed_tpu as ds


def _parser():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_epochs", type=int)
    return ds.add_config_arguments(parser)


def test_no_ds_args():
    args = _parser().parse_args(["--num_epochs", "2"])
    assert args.num_epochs == 2
    assert args.deepspeed is False
    assert args.deepspeed_config is None


def test_core_deepspeed_arguments():
    args = _parser().parse_args(
        ["--num_epochs", "2", "--deepspeed", "--deepspeed_config", "foo.json"]
    )
    assert args.deepspeed is True
    assert args.deepspeed_config == "foo.json"


def test_deepspeed_flag_alone():
    args = _parser().parse_args(["--deepspeed"])
    assert args.deepspeed is True
    assert args.deepspeed_config is None


def test_legacy_deepscale_aliases_exist():
    args = _parser().parse_args(["--deepscale", "--deepscale_config", "bar.json"])
    assert args.deepscale is True
    assert args.deepscale_config == "bar.json"


def test_returns_same_parser():
    parser = argparse.ArgumentParser()
    assert ds.add_config_arguments(parser) is parser
