"""bin/ CLI smoke tests (reference: bin/ds_report env report, bin/ds_bench
collective sweep, bin/ds_elastic batch explorer): each tool runs on the CPU
mesh and prints its contract."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def _run(args, timeout=240):
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    return subprocess.run(
        [sys.executable] + args,
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout,
    )


def test_ds_report_prints_environment():
    r = _run([os.path.join(REPO, "bin", "ds_report")])
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout.lower()
    assert "jax" in out
    assert "op" in out or "builder" in out or "native" in out


def test_ds_elastic_explores_batch_sizes(tmp_path):
    import json

    cfg = tmp_path / "elastic.json"
    cfg.write_text(json.dumps({
        "train_micro_batch_size_per_gpu": 1,
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 64,
            "micro_batch_sizes": [2, 4],
            "min_gpus": 1, "max_gpus": 8,
            "min_time": 0, "version": 0.1,
        },
    }))
    r = _run([os.path.join(REPO, "bin", "ds_elastic"), "-c", str(cfg), "-w", "4"])
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout.lower()
    assert "batch size" in out and "micro batch" in out, r.stdout


def test_ds_bench_runs_collective_sweep():
    r = _run([os.path.join(REPO, "bin", "ds_bench"), "--sizes-mb", "1", "--trials", "2"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all_reduce" in r.stdout.lower() or "allreduce" in r.stdout.lower() or "bytes" in r.stdout.lower()
