"""Mesh/topology tests (reference: tests/unit/runtime/pipe/test_topology.py)."""

import pytest

from deepspeed_tpu.parallel.mesh import AXIS_ORDER, build_mesh, get_topology, initialize_topology
from deepspeed_tpu.runtime.config import MeshConfig


def test_default_topology_all_data(eight_devices):
    topo = initialize_topology()
    assert topo.get_data_parallel_world_size() == 8
    assert topo.mesh.axis_names == AXIS_ORDER


def test_mixed_axes(eight_devices):
    topo = initialize_topology(MeshConfig(model=2, sequence=2))
    assert topo.get_model_parallel_world_size() == 2
    assert topo.get_sequence_parallel_world_size() == 2
    assert topo.get_data_parallel_world_size() == 2
    assert topo.axis_size("model") == 2


def test_expert_regroups_data(eight_devices):
    topo = initialize_topology(MeshConfig(expert=4))
    assert topo.get_expert_parallel_world_size() == 4
    assert topo.get_data_parallel_world_size() == 8  # data(2) x expert(4)
    assert topo.get_expert_data_parallel_world_size() == 2
    assert "expert" in topo.data_parallel_axes


def test_seq_in_dp_axes(eight_devices):
    topo = initialize_topology(MeshConfig(sequence=2))
    assert "sequence" in topo.data_parallel_axes
    assert topo.get_sequence_data_parallel_world_size() == 8


def test_singleton(eight_devices):
    t1 = get_topology()
    assert get_topology() is t1
