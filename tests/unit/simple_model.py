"""Test model fixtures (reference: ``tests/unit/simple_model.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel:
    """MLP regression model (reference SimpleModel :18)."""

    def __init__(self, hidden_dim: int = 16, nlayers: int = 2):
        self.hidden_dim = hidden_dim
        self.nlayers = nlayers

    def init(self, rng, batch):
        params = {}
        for i in range(self.nlayers):
            rng, sub = jax.random.split(rng)
            params[f"w{i}"] = jax.random.normal(sub, (self.hidden_dim, self.hidden_dim)) * 0.1
        return params

    def apply(self, params, batch, rngs=None, train=True):
        x, y = batch
        h = x
        for i in range(self.nlayers):
            h = h @ params[f"w{i}"]
            if i < self.nlayers - 1:
                h = jnp.tanh(h)
        return jnp.mean((h - y) ** 2)


def random_dataloader(model_dim: int = 16, total_samples: int = 64, batch_size: int = 8, seed: int = 0):
    rs = np.random.RandomState(seed)
    x = rs.randn(total_samples, model_dim).astype(np.float32)
    y = rs.randn(total_samples, model_dim).astype(np.float32)
    for i in range(0, total_samples, batch_size):
        yield (x[i : i + batch_size], y[i : i + batch_size])


def learnable_dataloader(model_dim: int = 16, total_samples: int = 64, batch_size: int = 8, seed: int = 0):
    """Deterministic regression stream with a GUARANTEED loss gradient:
    every step yields the same (x, y) batch, with y a fixed contraction of
    x — a target the MLP can move toward from its small-init state. A
    working optimizer therefore decreases the loss on every early step;
    "did the run learn" becomes a property of the optimizer, not of which
    random targets the step happened to draw (random_dataloader's fresh
    noise per step made 5-step loss-decrease asserts flake under jax-rng
    changes: the "did not learn in 5 steps" class in fast_tests.sh)."""
    rs = np.random.RandomState(seed)
    x = rs.randn(batch_size, model_dim).astype(np.float32)
    y = (0.5 * x).astype(np.float32)
    for _ in range(0, total_samples, batch_size):
        yield (x, y)


def rel_loss_decrease(losses) -> float:
    """Relative loss decrease over a run — the de-flaked learning criterion
    (scale-free, so it holds across dtypes and quantized variants)."""
    first = float(losses[0])
    return (first - float(losses[-1])) / max(abs(first), 1e-12)


def sequence_dataloader(vocab: int = 128, seq: int = 32, total: int = 32, batch: int = 8, seed: int = 0):
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, vocab, (total, seq + 1)).astype(np.int32)
    for i in range(0, total, batch):
        chunk = toks[i : i + batch]
        yield {"input_ids": chunk[:, :-1], "labels": chunk[:, 1:]}


# --- comm-free training-loop utilities -------------------------------------
# Shared by the fused-grad-accum parity and compile-telemetry tests: drive
# full optimizer steps on the virtual CPU mesh with no collectives beyond
# the engine's own GSPMD-emitted ones, deterministically enough that two
# engines built from the same config can be compared leaf-for-leaf.


def step_batch(model_dim: int = 16, batch_size: int = 8, seed: int = 0):
    """One deterministic FULL-step (x, y) batch for SimpleModel parity runs
    (slice or pass to ``train_batch(batch=...)``)."""
    rs = np.random.RandomState(seed)
    x = rs.randn(batch_size, model_dim).astype(np.float32)
    y = rs.randn(batch_size, model_dim).astype(np.float32)
    return (x, y)


def train_steps_micro(engine, batch, steps: int):
    """Drive ``steps`` optimizer steps through the per-microbatch
    forward/backward/step protocol, slicing ``batch`` into gas microbatches
    each step. Returns per-step mean losses as host floats."""
    gas = engine.gradient_accumulation_steps()
    micro = engine._split_step_batch(batch, gas)
    losses = []
    for _ in range(steps):
        vals = []
        for b in micro:
            loss = engine.forward(b)
            engine.backward(loss)
            engine.step()
            vals.append(float(jax.device_get(loss)))
        losses.append(sum(vals) / len(vals))
    return losses


def train_steps_batch(engine, batch, steps: int):
    """Drive ``steps`` optimizer steps through ``train_batch`` (the fused
    single-dispatch path when ``compile.fuse_grad_accum`` is on). Returns
    per-step mean losses as host floats."""
    return [float(engine.train_batch(batch=batch)) for _ in range(steps)]


def master_snapshot(engine):
    """Host copy of the fp32 master tree for cross-engine parity asserts."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), engine.get_master_params()
    )
