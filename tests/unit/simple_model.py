"""Test model fixtures (reference: ``tests/unit/simple_model.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel:
    """MLP regression model (reference SimpleModel :18)."""

    def __init__(self, hidden_dim: int = 16, nlayers: int = 2):
        self.hidden_dim = hidden_dim
        self.nlayers = nlayers

    def init(self, rng, batch):
        params = {}
        for i in range(self.nlayers):
            rng, sub = jax.random.split(rng)
            params[f"w{i}"] = jax.random.normal(sub, (self.hidden_dim, self.hidden_dim)) * 0.1
        return params

    def apply(self, params, batch, rngs=None, train=True):
        x, y = batch
        h = x
        for i in range(self.nlayers):
            h = h @ params[f"w{i}"]
            if i < self.nlayers - 1:
                h = jnp.tanh(h)
        return jnp.mean((h - y) ** 2)


def random_dataloader(model_dim: int = 16, total_samples: int = 64, batch_size: int = 8, seed: int = 0):
    rs = np.random.RandomState(seed)
    x = rs.randn(total_samples, model_dim).astype(np.float32)
    y = rs.randn(total_samples, model_dim).astype(np.float32)
    for i in range(0, total_samples, batch_size):
        yield (x[i : i + batch_size], y[i : i + batch_size])


def sequence_dataloader(vocab: int = 128, seq: int = 32, total: int = 32, batch: int = 8, seed: int = 0):
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, vocab, (total, seq + 1)).astype(np.int32)
    for i in range(0, total, batch):
        chunk = toks[i : i + batch]
        yield {"input_ids": chunk[:, :-1], "labels": chunk[:, 1:]}
