"""End-to-end convergence sanity checks (nightly).

Counterpart of the reference's ``tests/model/`` suite
(``tests/model/run_sanity_check.py``: BingBertSquad / Megatron GPT-2 trained
to a loss target): the tiny llama family is trained ~100 steps on a fixed
synthetic corpus under {ZeRO-3, pipeline, MoE}, asserting (a) the final loss
beats a recorded threshold and (b) dp1 and the sharded mesh land on the same
curve.

Each scenario runs in its OWN subprocess with a device count sized to its
mesh (the harness box can be a single core; an 8-virtual-device mesh there
spends its time in XLA's in-process collective rendezvous, not math — and a
dp2 ZeRO-3 run exercises the same sharded-master/gather paths). The corpus
is a deterministic next-token rule (an affine map over the vocab), which a
2-layer decoder learns quickly.

Run with: ``pytest -m nightly tests/model/``
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

# nightly AND slow: the ini's `addopts = -m "not nightly and not slow"` is
# OVERRIDDEN by any explicit -m on the command line, and the tier-1 command
# runs `-m 'not slow'` — which used to pull these ~100-step subprocess
# convergence legs into tier-1 and stall it past its timeout (the standing
# PR-9/-10/-11 note in CHANGES.md). Double-marking keeps them out of every
# non-nightly selection; run them with `pytest -m nightly tests/model/`.
pytestmark = [pytest.mark.nightly, pytest.mark.slow]

_HERE = os.path.abspath(__file__)
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(_HERE)))

VOCAB = 257  # prime: exercises non-divisible partition dims too
SEQ = 64
STEPS = int(os.environ.get("DS_CONV_STEPS", "100"))


def _run_scenario(name: str, n_devices: int, timeout_s: int = 1500) -> dict:
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, _HERE, name],
        env=env,
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=timeout_s,
    )
    assert proc.returncode == 0, f"{name} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    last = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(last)


class TestDenseConvergence:
    def test_zero3_dp2(self):
        rec = _run_scenario("zero3_dp2", 2)
        assert rec["final"] < 1.0, rec
        assert rec["final"] < rec["first"] / 4, rec

    def test_sharded_matches_single_device(self):
        """Same model/data/seeds at dp1 and dp2/zero3 (fp32): the sharding
        must not change the math beyond accumulation-order noise."""
        a = _run_scenario("zero3_dp2", 2)
        b = _run_scenario("dense_dp1", 1)
        assert b["final"] < 1.0, b
        assert abs(a["final"] - b["final"]) < 0.3, (a, b)


class TestPipelineConvergence:
    def test_pipe2(self):
        rec = _run_scenario("pipe2", 2)
        assert rec["final"] < 1.2, rec
        assert rec["final"] < rec["first"] / 4, rec


class TestMoEConvergence:
    def test_moe_ep2(self):
        # the MoE step (gate + capacity einsums + all_to_all) is the
        # slowest scenario on a small host; give it more wall clock
        rec = _run_scenario("moe_ep2", 2, timeout_s=3000)
        assert rec["final"] < 1.5, rec
        assert rec["final"] < rec["first"] / 3, rec


# ---------------------------------------------------------------------------
# child scenarios (run as `python test_convergence.py <name>` with the env
# set by _run_scenario; no pytest/conftest in this path)


def _corpus(rng, batch):
    import numpy as np

    start = rng.randint(0, VOCAB, (batch, 1))
    seqs = [start]
    for _ in range(SEQ):
        seqs.append((7 * seqs[-1] + 3) % VOCAB)
    toks = np.concatenate(seqs, axis=1).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


def _train_engine(engine, batch_size, seed=0):
    import jax
    import numpy as np

    rng = np.random.RandomState(seed)
    first = None
    for step in range(STEPS):
        batch = _corpus(rng, batch_size)
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        if step == 0:
            first = float(jax.device_get(loss))
    return {"first": first, "final": float(jax.device_get(loss))}


def _scenario_zero3_dp2():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, llama_config

    cfg = llama_config("tiny", num_layers=2, max_seq_len=SEQ, vocab_size=VOCAB)
    engine, *_ = ds.initialize(
        model=TransformerLM(cfg),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "adam", "params": {"lr": 3e-3}},
            "zero_optimization": {"stage": 3},
            "gradient_clipping": 1.0,
            "mesh": {"data": 2},
            "steps_per_print": 10_000,
        },
        dist_init_required=False,
    )
    return _train_engine(engine, engine.train_batch_size())


def _scenario_dense_dp1():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, llama_config

    cfg = llama_config("tiny", num_layers=2, max_seq_len=SEQ, vocab_size=VOCAB)
    engine, *_ = ds.initialize(
        model=TransformerLM(cfg),
        config={
            "train_micro_batch_size_per_gpu": 16,
            "optimizer": {"type": "adam", "params": {"lr": 3e-3}},
            "zero_optimization": {"stage": 0},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
        dist_init_required=False,
    )
    return _train_engine(engine, engine.train_batch_size())


def _scenario_pipe2():
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, llama_config
    from deepspeed_tpu.models.transformer import cross_entropy_loss
    from deepspeed_tpu.pipe import LayerSpec, PipelineModule
    import numpy as np

    cfg = llama_config("tiny", num_layers=2, max_seq_len=SEQ, vocab_size=VOCAB)

    class _Embed:
        def init(self, rng, x):  # noqa: ARG002
            return {"tokens": jax.random.normal(rng, (cfg.vocab_size, cfg.hidden_size)) * 0.02}

        def apply(self, p, toks, train=True):  # noqa: ARG002
            return p["tokens"][toks]

    class _Block:
        def init(self, rng, x):  # noqa: ARG002
            m = TransformerLM(cfg)
            full = m.init(rng, None)
            return jax.tree_util.tree_map(lambda a: a[0], full["layers"])

        def apply(self, p, x, train=True):
            import jax.numpy as jnp

            m = TransformerLM(cfg)
            T = x.shape[1]
            positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], x.shape[:2])
            out, _ = m._layer(x, p, positions, None, train)
            return out

    class _Head:
        def init(self, rng, x):  # noqa: ARG002
            return {"w": jax.random.normal(rng, (cfg.hidden_size, cfg.vocab_size)) * 0.02}

        def apply(self, p, x, train=True):  # noqa: ARG002
            return x @ p["w"].astype(x.dtype)

    pm = PipelineModule(
        [LayerSpec(_Embed), LayerSpec(_Block), LayerSpec(_Block), LayerSpec(_Head)],
        loss_fn=cross_entropy_loss,
    )
    engine, *_ = ds.initialize(
        model=pm,
        config={
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "adam", "params": {"lr": 3e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"pipe": 2},
            "steps_per_print": 10_000,
        },
        dist_init_required=False,
    )
    rng = np.random.RandomState(0)
    first = None
    for step in range(STEPS):
        b = _corpus(rng, engine.train_batch_size())
        loss = engine.train_batch(batch=(b["input_ids"], b["labels"]))
        if step == 0:
            first = float(loss)
    return {"first": first, "final": float(loss)}


def _scenario_moe_ep2():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import MoETransformerLM, moe_llama_config

    cfg = moe_llama_config(
        "tiny", num_layers=2, max_seq_len=SEQ, vocab_size=VOCAB, num_experts=2
    )
    engine, *_ = ds.initialize(
        model=MoETransformerLM(cfg),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "adam", "params": {"lr": 3e-3}},
            "zero_optimization": {"stage": 2},
            "gradient_clipping": 1.0,
            "mesh": {"expert": 2},
            "steps_per_print": 10_000,
        },
        dist_init_required=False,
    )
    return _train_engine(engine, engine.train_batch_size())


_SCENARIOS = {
    "zero3_dp2": _scenario_zero3_dp2,
    "dense_dp1": _scenario_dense_dp1,
    "pipe2": _scenario_pipe2,
    "moe_ep2": _scenario_moe_ep2,
}


if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    rec = _SCENARIOS[sys.argv[1]]()
    print(json.dumps(rec))
