"""Hybrid-engine rollout throughput: cached decode vs full-forward (nightly).

The reference's hybrid engine exists to make RLHF rollouts fast via
kernel-injected cached inference (``deepspeed/runtime/hybrid_engine.py:32``);
round 3's rollout here re-ran a full-sequence forward per emitted token.
This test pins the fix: at a few-hundred-token context the KV-cached decode
loop must beat the full-forward-per-token loop by a wide margin (the gap
only widens with context — at the DS-Chat 2k context the per-token cost
ratio is ~context/1).
"""

import time

import jax
import numpy as np
import pytest

import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.inference.decode import generate as kv_generate
from deepspeed_tpu.inference.generation import greedy_generate
from deepspeed_tpu.models import TransformerLM, llama_config

# nightly AND slow: an explicit `-m 'not slow'` (the tier-1 command)
# overrides the ini addopts' nightly exclusion — see test_convergence.py
pytestmark = [pytest.mark.nightly, pytest.mark.slow]

CTX, NEW = 256, 12


def test_cached_rollout_beats_full_forward():
    mesh_mod.reset_topology()
    cfg = llama_config("tiny", num_layers=2, max_seq_len=CTX + NEW, vocab_size=512)
    model = TransformerLM(cfg)
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 512, (2, CTX)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)

    def apply_fn(p, toks, rng):  # noqa: ARG001
        return model.apply(p, toks, train=False)

    def run(fn):
        out = fn()  # compile
        jax.device_get(np.asarray(out[0, -1]))
        t0 = time.perf_counter()
        out = fn()
        jax.device_get(np.asarray(out[0, -1]))
        return time.perf_counter() - t0, np.asarray(out)

    rng = jax.random.PRNGKey(1)
    full_cache = {}  # shared across warmup + timed run: the timed call
    # must hit the compiled step, not re-trace it
    t_full, out_full = run(
        lambda: greedy_generate(apply_fn, params, prompt, NEW, rng, jit_cache=full_cache)
    )
    t_kv, out_kv = run(lambda: kv_generate(cfg, params, prompt, NEW))

    # identical greedy tokens, much faster
    np.testing.assert_array_equal(out_kv[:, : out_full.shape[1]], out_full)
    assert t_full / t_kv >= 3.0, f"cached rollout only {t_full / t_kv:.1f}x faster"
