"""16-virtual-device multichip dryrun (nightly).

Axis sizes of 2 can hide divisibility/padding bugs; the driver's own dryrun
runs at its configured device count, and this pins the larger meshes
(dp16 ZeRO-3, dp4×tp2×sp2, pp4×dp4, ep4×dp4) as standing coverage.
``dryrun_multichip`` re-execs itself with the right XLA flags, so this
works from inside the 8-device suite process."""

import os
import sys

import pytest

# nightly AND slow: an explicit `-m 'not slow'` (the tier-1 command)
# overrides the ini addopts' nightly exclusion — see test_convergence.py
pytestmark = [pytest.mark.nightly, pytest.mark.slow]


def test_dryrun_multichip_16():
    """Bounded: a collective-rendezvous hang on the virtual mesh must fail
    the test, not wedge the nightly job — so run the re-exec form in our
    own subprocess with a timeout instead of the unbounded built-in one."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__; __graft_entry__.dryrun_multichip(16)",
        ],
        env=env,
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=2400,
    )
    assert proc.returncode == 0, f"dryrun_16 failed:\n{proc.stdout[-1500:]}\n{proc.stderr[-1500:]}"
    assert "phase 3 ok" in proc.stdout
