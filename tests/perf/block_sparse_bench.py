"""Microbench: Pallas block-sparse attention vs dense flash at long seq.

Run on a real TPU (reference analog: the Triton block-sparse kernels'
long-sequence win). Expected: the sparse kernel beats dense once the live
fraction is small — at 8k with a sliding-window config the layout keeps
<20% of blocks.

    python tests/perf/block_sparse_bench.py [seq_len]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.pallas_block_sparse import (
    build_block_tables,
    pallas_block_sparse_attention,
)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BSLongformerSparsityConfig,
)
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def main(T: int = 8192):
    B, NH, D = 1, 8, 64
    BLOCK = 64
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, NH, T, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, NH, T, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, NH, T, D), jnp.bfloat16)

    cfg = BSLongformerSparsityConfig(num_heads=NH, block=BLOCK)
    layout = cfg.make_layout(T)[:1]
    row_idx, row_cnt, _, _ = build_block_tables(layout[0])
    nb = T // BLOCK
    live_frac = float(row_cnt.sum()) / (nb * nb)

    sparse = jax.jit(
        lambda q, k, v: pallas_block_sparse_attention(
            q, k, v, layout, BLOCK, causal=True
        )
    )
    # flash kernel expects [B, T, N, D]
    to_btnd = lambda x: x.transpose(0, 2, 1, 3)
    dense = jax.jit(lambda q, k, v: flash_attention(to_btnd(q), to_btnd(k), to_btnd(v), causal=True))

    def timeit(fn, reps=10):
        out = fn(q, k, v)
        jax.device_get(np.asarray(out).ravel()[:1])
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(q, k, v)
        jax.device_get(np.asarray(out).ravel()[:1])
        return (time.perf_counter() - t0) / reps

    ts = timeit(sparse)
    td = timeit(dense)
    print(
        f"seq={T} block={BLOCK} live_blocks={live_frac:.1%} | "
        f"sparse {ts * 1e3:.2f} ms vs dense flash {td * 1e3:.2f} ms "
        f"({td / ts:.2f}x)"
    )


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8192)
