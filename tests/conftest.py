"""Test harness: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's distributed-in-one-box strategy (tests/unit/common.py
``DistributedTest``): multi-chip semantics are exercised on one host. Here a
single process drives 8 XLA cpu devices through the same GSPMD code paths the
TPU pod uses (the sitecustomize force-registers the tunneled TPU backend
unless PALLAS_AXON_POOL_IPS is empty, hence the env dance).
"""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_topology():
    """Each test builds its own mesh; reset the singleton between tests."""
    import deepspeed_tpu.parallel.mesh as mesh_mod

    mesh_mod.reset_topology()
    yield
    mesh_mod.reset_topology()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
