"""Test harness: force an 8-device virtual CPU mesh.

Mirrors the reference's distributed-in-one-box strategy (tests/unit/common.py
``DistributedTest``): multi-chip semantics are exercised on one host. Here a
single process drives 8 XLA cpu devices through the same GSPMD code paths the
TPU pod uses.

The site customization (PYTHONPATH=/root/.axon_site) imports jax and
registers the tunneled TPU backend at interpreter startup — before this file
runs — so env vars alone are too late. We force the platform through
jax.config (effective until the first backend use, which pytest hasn't done
yet) and XLA_FLAGS for the cpu client's device count (the cpu client is
created lazily, so this is still in time).
"""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_sessionstart(session):  # noqa: ARG001
    devs = jax.devices()
    assert devs[0].platform == "cpu", (
        f"test suite must run on the virtual CPU mesh, got {devs[0].platform}; "
        "the axon backend was initialized before conftest could force cpu"
    )


@pytest.fixture(autouse=True)
def _fresh_topology():
    """Each test builds its own mesh; reset the singleton between tests."""
    import deepspeed_tpu.parallel.mesh as mesh_mod

    mesh_mod.reset_topology()
    yield
    mesh_mod.reset_topology()


@pytest.fixture(autouse=True)
def _clear_jax_caches():
    """Drop compiled executables after EVERY test. Accumulated
    executables/live buffers degrade the 8-device CPU mesh pathologically
    (observed 2026-07-31: test_spatial runs 43s fresh but sat >45 min at
    full CPU when reached through the suite; a module-scoped clear moved
    the wedge into the next large module instead of removing it). The
    recompilation cost is a few seconds per test; the wedge it prevents is
    unbounded."""
    yield
    jax.clear_caches()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
