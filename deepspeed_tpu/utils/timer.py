"""Wall-clock timers.

Counterpart of ``deepspeed/utils/timer.py`` (``SynchronizedWallClockTimer``,
``ThroughputTimer``). "Synchronized" here means blocking on JAX async dispatch
before reading the clock (the CUDA-event analog).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


from deepspeed_tpu.utils.sync import device_sync as _sync


class SynchronizedWallClockTimer:
    """Named host timers. ``tracer`` (``profiling/tracer.py``) routes every
    completed start/stop interval into the unified timeline as a span, so
    the wall-clock breakdown and the trace are one dataset.

    HOT-PATH HAZARD (fixed): ``Timer.stop`` used to default ``sync=True`` —
    a full device sync (drain of the async dispatch queue) on every stop,
    which on a tunneled TPU backend serializes host and device and can
    dominate the step time. The default is now ``sync=False``; pass
    ``sync=True`` explicitly only OUTSIDE the step loop (window boundaries,
    benches — ``ThroughputTimer`` below is the sanctioned synced timer)."""

    class Timer:
        def __init__(self, name: str, tracer=None):
            self.name = name
            self.tracer = tracer
            self.started = False
            self.start_time = 0.0
            self.elapsed_ = 0.0
            self.record = []

        def start(self, sync: bool = False):
            if sync:
                _sync()
            self.start_time = time.perf_counter()
            self.started = True

        def stop(self, sync: bool = False, record: bool = False):
            if not self.started:
                return
            if sync:
                _sync()
            now = time.perf_counter()
            self.elapsed_ += now - self.start_time
            self.started = False
            if record:
                self.record.append(self.elapsed_)
            if self.tracer is not None:
                self.tracer.add_span(f"timer.{self.name}", self.start_time, now)

        def reset(self):
            self.elapsed_ = 0.0
            self.started = False

        def elapsed(self, reset: bool = True) -> float:
            out = self.elapsed_
            if reset:
                self.reset()
            return out

        def mean(self) -> float:
            return sum(self.record) / len(self.record) if self.record else 0.0

    def __init__(self, tracer=None):
        self.timers: Dict[str, SynchronizedWallClockTimer.Timer] = {}
        self.tracer = tracer

    def __call__(self, name: str) -> "SynchronizedWallClockTimer.Timer":
        if name not in self.timers:
            self.timers[name] = self.Timer(name, tracer=self.tracer)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True, memory_breakdown=None, ranks=None):  # noqa: ARG002
        from deepspeed_tpu.utils.logging import log_dist

        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {elapsed:.2f}")
        log_dist("time (ms) | " + " | ".join(parts), ranks=ranks or [0])

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        return {n: self.timers[n].mean() * 1000.0 / normalizer for n in names if n in self.timers}


class NoopTimer:
    class Timer:
        def start(self, *a, **k):
            pass

        def stop(self, *a, **k):
            pass

        def reset(self):
            pass

        def elapsed(self, *a, **k):
            return 0.0

    def __call__(self, name):  # noqa: ARG002
        return self.Timer()

    def log(self, *a, **k):
        pass


class ThroughputTimer:
    """Samples/sec reporting (reference ``ThroughputTimer``).

    The reference synchronizes the accelerator around EVERY step to time it
    (cheap on a local CUDA stream). Here a sync drains the async dispatch
    queue — on TPU (worse: on a tunneled backend) that serializes host and
    device and can dominate the step time. So this timer measures whole
    *logging windows* instead: it syncs once per ``steps_per_output`` steps,
    divides wall-clock by the window's sample count, and leaves the hot loop
    fully async. Steady-state numbers are identical; only sub-window
    per-step resolution is given up.
    """

    def __init__(self, batch_size: int, start_step: int = 2, steps_per_output: int = 50, monitor_memory: bool = False, logging_fn=None):
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.steps_per_output = max(steps_per_output, 1)
        self.monitor_memory = monitor_memory
        self.logging = logging_fn
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.started = False
        self.initialized = False
        self._window_open = False
        self._window_start_time = 0.0
        self._window_start_step = 0
        self._measured_steps = 0
        self._last_window_rate = 0.0

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def abort_window(self):
        """Discard a half-open measurement window (e.g. the engine switches
        to eval mid-window) so its wall-clock never deflates the rate."""
        self._window_open = False

    def start(self):
        self.started = True
        if not self._window_open and self.global_step_count >= self.start_step:
            # open a measurement window on a drained queue: host work between
            # windows (checkpoint saves, eval loops) is not counted
            _sync()
            self._window_start_time = time.perf_counter()
            self._window_start_step = self.global_step_count
            self._window_open = True
            self.initialized = True

    def stop(self, global_step: bool = False, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if not (self._window_open and global_step):
            return
        window_steps = self.global_step_count - self._window_start_step
        if window_steps < self.steps_per_output and self.global_step_count % self.steps_per_output != 0:
            return
        _sync()
        now = time.perf_counter()
        duration = now - self._window_start_time
        self.total_elapsed_time += duration
        self._measured_steps += window_steps
        if duration > 0:
            self._last_window_rate = self.batch_size * window_steps / duration
        if report_speed and self.logging:
            self.logging(
                f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                f"global_step={self.global_step_count}, RunningAvgSamplesPerSec={self.avg_samples_per_sec():.2f}, "
                f"CurrSamplesPerSec={self._last_window_rate:.2f}"
            )
        self._window_open = False

    def avg_samples_per_sec(self) -> float:
        if self.total_elapsed_time > 0 and self._measured_steps > 0:
            return self.batch_size * self._measured_steps / self.total_elapsed_time
        return 0.0
