"""Communication logging.

Counterpart of the reference's ``deepspeed/utils/comms_logging.py``
(``CommsLogger`` with per-op records and ``get_bw`` utilization calc). Records
are kept per (op_name, msg_size); ``log_summary`` prints the aggregate table.
"""

from __future__ import annotations

import math
from typing import Dict, List

from deepspeed_tpu.utils.logging import log_dist


def get_caller_func(frame: int = 3) -> str:
    import sys

    return sys._getframe(frame).f_code.co_name


def calc_bw_log(comm_op: str, size: int, duration: float, n_links: int = 1) -> tuple:
    """Return (msg_size, algbw GB/s, busbw GB/s) for a collective.

    Bus-bandwidth factors follow the standard NCCL-style accounting: allreduce
    moves 2(n-1)/n of the data per link, all_gather/reduce_scatter (n-1)/n.
    """
    duration = max(duration, 1e-9)
    n = max(n_links, 1)
    if comm_op in ("all_reduce", "allreduce", "inference_all_reduce"):
        tput = 2 * size / duration
        busbw = (size / duration) * (2 * (n - 1) / n)
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter", "reduce_scatter_tensor"):
        size *= n
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n)
    else:
        tput = size / duration
        busbw = tput
    return size, tput / 1e9, busbw / 1e9


class CommsLogger:
    def __init__(self, verbose: bool = False, debug: bool = False, prof_ops: List[str] = None):
        self.verbose = verbose
        self.debug = debug
        self.prof_ops = prof_ops or []
        self.comms_dict: Dict[str, Dict[int, List[float]]] = {}
        self.enabled = False

    def configure(self, comms_config) -> None:
        self.enabled = getattr(comms_config, "comms_logger_enabled", False)
        if self.enabled:
            cfg = comms_config.comms_logger
            self.verbose = cfg.verbose
            self.debug = cfg.debug
            self.prof_ops = cfg.prof_ops
            self.prof_all = cfg.prof_all
        else:
            self.prof_all = False

    def start_profiling_comms(self):
        self.prof_all = True

    def stop_profiling_comms(self):
        self.prof_all = False

    def append(self, raw_name: str, record_name: str, latency: float, msg_size: int) -> None:
        rec = self.comms_dict.setdefault(record_name, {})
        sizes = rec.setdefault(msg_size, [0, 0.0, []])
        sizes[0] += 1
        sizes[1] += latency
        sizes[2].append(latency)
        if self.verbose:
            log_dist(f"comm op: {record_name} | time (ms): {latency:.2f} | msg size: {msg_size}", ranks=[0])

    def log_all(self, print_log: bool = True, show_straggler: bool = False) -> Dict:  # noqa: ARG002
        lines = [f"{'Comm. Op':<20}{'Message Size':>15}{'Count':>10}{'Total Latency(ms)':>20}{'Avg Latency(ms)':>18}"]
        for record_name, sizes in sorted(self.comms_dict.items()):
            lines.append(record_name)
            for size, (count, total, _samples) in sorted(sizes.items()):
                avg = total / count if count else 0.0
                lines.append(f"{'':<20}{_fmt_size(size):>15}{count:>10}{total:>20.2f}{avg:>18.2f}")
        if print_log:
            log_dist("\n".join(lines), ranks=[0])
        return self.comms_dict


def _fmt_size(num: int) -> str:
    if num <= 0:
        return "0 B"
    units = ["B", "KB", "MB", "GB", "TB"]
    p = min(int(math.log(num, 1024)), len(units) - 1)
    return f"{num / 1024 ** p:.2f} {units[p]}"
