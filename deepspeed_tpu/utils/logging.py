"""Rank-aware logging.

TPU-native counterpart of the reference's ``deepspeed/utils/logging.py``:
a module-level ``logger`` plus ``log_dist`` that filters by process index.
On TPU there is one process per host (not per device), so "rank" here is
``jax.process_index()``.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class _LoggerFactory:
    @staticmethod
    def create_logger(name: str = "DeepSpeedTPU", level: int = logging.INFO) -> logging.Logger:
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d:%(funcName)s] %(message)s"
        )
        lg = logging.getLogger(name)
        lg.setLevel(level)
        lg.propagate = False
        if not lg.handlers:
            handler = logging.StreamHandler(stream=sys.stdout)
            handler.setFormatter(formatter)
            lg.addHandler(handler)
        return lg


logger = _LoggerFactory.create_logger(
    level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(), logging.INFO)
)


@functools.lru_cache(None)
def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks=None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the listed process ranks (``[-1]`` or None = all)."""
    my_rank = _process_index()
    if ranks is None or len(ranks) == 0 or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        print(message, flush=True)


def warning_once(message: str) -> None:
    _warn_once_impl(message)


@functools.lru_cache(None)
def _warn_once_impl(message: str) -> None:
    logger.warning(message)


def get_current_level() -> int:
    return logger.getEffectiveLevel()


def should_log_le(max_log_level_str: str) -> bool:
    """True if the logger's level is <= the given level name (i.e. it would emit it)."""
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    level = LOG_LEVELS.get(max_log_level_str.lower())
    if level is None:
        raise ValueError(f"unknown log level: {max_log_level_str}")
    return get_current_level() <= level
