"""Process-group accessor API (reference: ``deepspeed/utils/groups.py``
:51-528 — ``initialize(ep_size, mpu)`` plus the ``_get_*_parallel_group``
family).

TPU-native design: the reference materializes torch.distributed process
groups; here every "group" is a VIEW over an axis of the global device mesh
(``parallel/mesh.py``). The returned handles carry ``.size``/``.ranks`` —
the duck-type the comm facade's ``get_world_size(group=...)`` /
``get_all_ranks_from_group`` probe — and ``.axis`` for sharding-aware
callers. Collectives over a group are expressed by sharding over its axis;
no group construction or rendezvous happens here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from deepspeed_tpu.parallel.mesh import MeshConfig, get_topology, initialize_topology


@dataclass(frozen=True)
class AxisGroup:
    """A mesh-axis view with the comm-facade group duck-type."""

    axis: Tuple[str, ...]
    size: int

    @property
    def ranks(self):
        return list(range(self.size))

    def __len__(self) -> int:
        return self.size


def _axis_group(*axes: str) -> AxisGroup:
    topo = get_topology()
    size = 1
    for a in axes:
        size *= topo.axis_size(a)
    return AxisGroup(axis=axes, size=size)


def initialize(ep_size: int = 1, mpu=None) -> None:  # noqa: ARG001
    """Establish the expert axis (reference groups.py:51 — creates expert +
    expert-data groups INSIDE the existing parallel layout). The expert
    axis is carved out of the data axis; model/pipe/sequence/data_outer
    axes are preserved. An existing expert axis is validated instead.

    The resulting topology is marked groups-established so a later
    ``ds.initialize`` with no explicit mesh adopts it (the training engine
    otherwise rebuilds its own derived mesh)."""
    if ep_size <= 1:
        return
    topo = get_topology()
    if topo.axis_size("expert") == ep_size:
        topo.user_established = True
        return
    if topo.axis_size("expert") != 1:
        raise ValueError(
            f"expert axis already sized {topo.axis_size('expert')}; "
            f"cannot re-initialize to ep_size={ep_size}"
        )
    old = topo.config
    if old.data % ep_size != 0:
        raise ValueError(
            f"ep_size={ep_size} does not divide the data axis ({old.data}); "
            "expert groups are carved from data parallelism"
        )
    new_topo = initialize_topology(
        MeshConfig(
            pipe=old.pipe,
            data_outer=old.data_outer,
            data=old.data // ep_size,
            expert=ep_size,
            sequence=old.sequence,
            model=old.model,
        )
    )
    new_topo.user_established = True


# --- accessors (reference groups.py:282-528) -------------------------------
def _get_data_parallel_group() -> AxisGroup:
    """Dense-param DP group: data_outer x data x expert — EP groups are
    carved INSIDE data parallelism (reference groups.py; matches
    Topology.get_data_parallel_world_size)."""
    return _axis_group("data_outer", "data", "expert")


def _get_model_parallel_group() -> AxisGroup:
    return _axis_group("model")


def _get_expert_parallel_group(group_name: Optional[str] = None) -> AxisGroup:  # noqa: ARG001
    return _axis_group("expert")


def _get_expert_data_parallel_group(group_name: Optional[str] = None) -> AxisGroup:  # noqa: ARG001
    """DP ranks holding the same expert shard (reference expert-data
    groups, groups.py:113): the inner data axis — matches
    Topology.get_expert_data_parallel_world_size."""
    return _axis_group("data")


def _get_sequence_parallel_group() -> AxisGroup:
    return _axis_group("sequence")


def _get_sequence_data_parallel_group() -> AxisGroup:
    return _axis_group("sequence", "data_outer", "data", "expert")


def _get_max_expert_size_name() -> str:
    return f"ep_size_{_axis_group('expert').size}"


# public world-size / rank helpers (reference :373-465). Rank within a mesh
# axis is a per-device notion under SPMD; the process-level rank is 0 in
# single-controller runs, so these report axis SIZES and rank 0 like the
# reference does on rank 0.
def get_data_parallel_world_size() -> int:
    return _get_data_parallel_group().size


def get_model_parallel_world_size() -> int:
    return _get_model_parallel_group().size


def get_expert_parallel_world_size(group_name: Optional[str] = None) -> int:  # noqa: ARG001
    return _get_expert_parallel_group().size


def get_expert_data_parallel_world_size(group_name: Optional[str] = None) -> int:  # noqa: ARG001
    return _get_expert_data_parallel_group().size


def get_sequence_parallel_world_size() -> int:
    return _get_sequence_parallel_group().size


def get_data_parallel_rank() -> int:
    from deepspeed_tpu.comm import comm as dist

    return dist.get_rank() % max(1, get_data_parallel_world_size())


def get_model_parallel_rank() -> int:
    return 0


def get_expert_parallel_rank(group_name: Optional[str] = None) -> int:  # noqa: ARG001
    return 0
