"""Trace-replay load generation for the paged serving stack.

Aggregate tokens/s on a synchronized batch says little about production
serving — there, traffic is bursty (heavy-tailed inter-arrivals), unequal
(per-tenant rates and contracts), and redundant (shared system prompts).
This harness makes that workload reproducible:

* ``make_trace`` builds a DETERMINISTIC request trace from per-tenant
  ``TenantLoad`` specs: Pareto inter-arrival times (unit-mean, tail index
  ``pareto_alpha`` — smaller = burstier), a shared-prefix mixture (each
  tenant owns ``n_prefixes`` system prompts picked with zipf-ish
  popularity, prepended to a random suffix), and per-request token
  budgets. Same seed → byte-identical trace.
* ``replay`` feeds the trace into a server (``PagedServer`` or
  ``MultiTenantServer``) arrival-by-arrival while driving its step loop,
  then reports the percentiles that matter for serving SLAs: p50/p99
  TTFT and TPOT (aggregate + per tenant), **goodput under overload**
  (tokens/s from finished requests that met their tenant's TTFT target —
  no target means every finished request counts), per-tenant goodput vs
  budget shares with a ``starved_tenants`` verdict, rejection counts, and
  the pool's prefix hit rate.

Time can be real (wall-clock replay, the bench/smoke mode) or virtual
(``VirtualClock``: each server step costs a fixed dt and idle gaps jump
instantly) — virtual replay is fully deterministic and is what the unit
tests pin down.

Fleet scope: ``replay`` drives anything with the server surface —
including a ``FleetRouter`` (``inference/fleet.py``), whose ``clock``
setter installs the virtual clock on every replica — and ``events``
injects timed mid-trace actions (kill a replica, drain one, join a fresh
one) at deterministic trace instants, which is how the fleet bench and
tests measure p99 TTFT across a replica kill.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class TenantLoad:
    """One tenant's offered load.

    ``rate`` is the mean arrival rate (requests per second of trace time);
    inter-arrivals are unit-mean Pareto with tail index ``pareto_alpha``
    (must be > 1 for a finite mean; values near 1 give extreme bursts).
    Prompts are ``prefix + suffix``: with probability
    ``shared_prefix_prob`` one of the tenant's ``n_prefixes`` system
    prompts (zipf-ish popularity — rank r drawn ∝ 1/(r+1)) of
    ``prefix_len`` tokens is prepended to a fresh random suffix of
    uniform length in ``prompt_len``."""

    name: str = "default"
    rate: float = 4.0
    pareto_alpha: float = 1.5
    prompt_len: Tuple[int, int] = (8, 24)
    max_new_tokens: Tuple[int, int] = (4, 12)
    shared_prefix_prob: float = 0.8
    n_prefixes: int = 2
    prefix_len: int = 16
    n_requests: Optional[int] = None  # cap per tenant (horizon still applies)


@dataclass
class TraceRequest:
    """One scheduled arrival (``at`` seconds from trace start)."""

    at: float
    tenant: str
    prompt: np.ndarray
    max_new_tokens: int
    prefix_id: int = -1  # index of the shared system prompt, -1 = none
    index: int = field(default=-1)  # position in the merged trace


def _pareto_gap(rs: np.random.RandomState, alpha: float) -> float:
    """Unit-mean Pareto (Lomax + 1) sample: heavy upper tail, so a few
    gaps are huge and most are small — bursts."""
    a = max(float(alpha), 1.05)
    return (a - 1.0) / a * (1.0 + float(rs.pareto(a)))


def make_trace(
    tenants: Sequence[TenantLoad],
    horizon_s: float,
    vocab_size: int,
    seed: int = 0,
) -> List[TraceRequest]:
    """Deterministic heavy-tailed trace, merged over tenants and sorted by
    arrival time. All randomness flows from ``seed``."""
    rs = np.random.RandomState(seed)
    out: List[TraceRequest] = []
    for tl in tenants:
        prefixes = [
            rs.randint(0, vocab_size, (int(tl.prefix_len),)).astype(np.int32)
            for _ in range(int(tl.n_prefixes))
        ]
        if tl.n_prefixes:
            pop = 1.0 / np.arange(1, tl.n_prefixes + 1, dtype=np.float64)
            pop /= pop.sum()
        t, count = 0.0, 0
        mean_gap = 1.0 / max(float(tl.rate), 1e-9)
        while True:
            t += mean_gap * _pareto_gap(rs, tl.pareto_alpha)
            if t >= horizon_s or (
                tl.n_requests is not None and count >= tl.n_requests
            ):
                break
            lo, hi = tl.prompt_len
            suffix = rs.randint(0, vocab_size, (int(rs.randint(lo, hi + 1)),))
            pid = -1
            if tl.n_prefixes and rs.rand() < tl.shared_prefix_prob:
                pid = int(rs.choice(tl.n_prefixes, p=pop))
                prompt = np.concatenate([prefixes[pid], suffix.astype(np.int32)])
            else:
                prompt = suffix.astype(np.int32)
            blo, bhi = tl.max_new_tokens
            out.append(
                TraceRequest(
                    at=t,
                    tenant=tl.name,
                    prompt=prompt,
                    max_new_tokens=int(rs.randint(blo, bhi + 1)),
                    prefix_id=pid,
                )
            )
            count += 1
    out.sort(key=lambda r: (r.at, r.tenant))
    for i, r in enumerate(out):
        r.index = i
    return out


class VirtualClock:
    """Deterministic replay clock: ``clock()`` reads the current virtual
    time; the replay loop charges ``step_cost_s`` per server step via
    ``tick()`` and jumps idle gaps with ``tick(dt)``. Hand the SAME
    instance to the server (``PagedServer(clock=...)``) so its TTFT/TPOT
    stamps live on the virtual axis."""

    def __init__(self, step_cost_s: float = 0.01):
        self.now = 0.0
        self.step_cost_s = float(step_cost_s)

    def __call__(self) -> float:
        return self.now

    def tick(self, dt: Optional[float] = None) -> None:
        self.now += self.step_cost_s if dt is None else max(float(dt), 0.0)


def replay(
    server,
    trace: Sequence[TraceRequest],
    clock: Optional[VirtualClock] = None,
    eos_token_id: Optional[int] = None,
    max_steps: int = 1_000_000,
    starvation_tolerance: float = 0.10,
    keep_outputs: bool = True,
    events: Optional[Sequence[Tuple[float, Callable]]] = None,
) -> Dict:
    """Replay ``trace`` into ``server`` and report SLA percentiles,
    per-tenant goodput vs budget shares, and prefix hit rate.

    ``server`` is a ``PagedServer`` or ``MultiTenantServer`` — or a
    ``FleetRouter`` over several of them (rejections — ``submit``
    returning None — are counted, not raised). With ``clock=None`` the
    replay runs on the wall clock (arrivals in real time, idle gaps
    slept); pass a ``VirtualClock`` (also installed on the server) for
    deterministic virtual-time replay.

    ``events`` is a list of ``(at_seconds, fn)`` timed actions fired once
    when replay time passes ``at_seconds``, each called with the server —
    the fleet-scope failure injections (kill a replica mid-trace, drain
    one, join a fresh one) that make "p99 TTFT under replica kill" a
    reproducible measurement. Events landing after the replay finishes
    never fire; the report counts the fired ones."""
    wall = clock is None
    if wall:
        t0 = time.perf_counter()

        def now_fn() -> float:
            return time.perf_counter() - t0

    else:
        now_fn = clock
        # the server's TTFT/TPOT stamps must live on the same virtual axis
        inner = getattr(server, "server", server)  # MultiTenantServer front
        inner.clock = clock

    offered: Dict[str, int] = {}
    rejected: Dict[str, int] = {}
    uid_by_index: Dict[int, int] = {}
    pending_events = sorted(events or [], key=lambda e: e[0])
    events_fired = 0
    i = 0
    steps = 0
    trace = list(trace)
    while i < len(trace) or server.has_work():
        now = now_fn()
        while pending_events and pending_events[0][0] <= now:
            _, fire = pending_events.pop(0)
            fire(server)
            events_fired += 1
        while i < len(trace) and trace[i].at <= now:
            r = trace[i]
            offered[r.tenant] = offered.get(r.tenant, 0) + 1
            try:
                uid = server.submit(
                    r.prompt,
                    max_new_tokens=r.max_new_tokens,
                    eos_token_id=eos_token_id,
                    tenant=r.tenant,
                )
            except ValueError:  # oversized for the pool: shed, don't crash
                uid = None
            if uid is None:
                rejected[r.tenant] = rejected.get(r.tenant, 0) + 1
            else:
                uid_by_index[r.index] = uid
            i += 1
        if server.has_work():
            if not wall:
                # charge the step's cost BEFORE it runs so tokens emitted by
                # this step are stamped after the time they took — a request
                # served on the step right after arrival gets TTFT >= one
                # step cost, never 0
                clock.tick()
            server.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(f"replay exceeded max_steps={max_steps}")
        elif i < len(trace):
            gap = trace[i].at - now
            if wall:
                time.sleep(min(max(gap, 0.0), 0.005))
            else:
                clock.tick(gap)
    duration = max(now_fn(), 1e-9)

    stats = server.serve_stats()
    tenant_stats = stats.get("tenants", {})
    # goodput: tokens from finished requests meeting their tenant's TTFT
    # target (no target — or no MultiTenantServer specs — counts them all)
    specs = getattr(server, "tenants", {})
    good_tokens: Dict[str, int] = {}
    for tenant, ttft_ms, _tpot_ms, n_tokens in server.finished_log():
        spec = specs.get(tenant)
        target = getattr(spec, "ttft_target_ms", None) if spec else None
        if target is None or ttft_ms <= target:
            good_tokens[tenant] = good_tokens.get(tenant, 0) + n_tokens
    total_good = sum(good_tokens.values())

    weights = {
        name: getattr(spec, "weight", 1.0) for name, spec in specs.items()
    } or {name: 1.0 for name in offered}
    demanding = [name for name in weights if offered.get(name, 0) > 0]
    demand_weight = sum(weights[n] for n in demanding) or 1.0
    # per-tenant demand in tokens (offered budgets): a tenant that offers
    # LESS than its budget share is not starved by not reaching it — the
    # entitlement is min(budget share, demand share)
    demand_tokens: Dict[str, int] = {}
    for r in trace:
        demand_tokens[r.tenant] = demand_tokens.get(r.tenant, 0) + r.max_new_tokens
    total_demand = sum(demand_tokens.values()) or 1

    tenants_report: Dict[str, Dict] = {}
    starved: List[str] = []
    for name in sorted(set(offered) | set(weights)):
        tokens = tenant_stats.get(name, {}).get("tokens", 0)
        good = good_tokens.get(name, 0)
        budget_share = (
            weights.get(name, 1.0) / demand_weight if name in demanding else 0.0
        )
        demand_share = demand_tokens.get(name, 0) / total_demand
        goodput_share = good / total_good if total_good else 0.0
        entitled = min(budget_share, demand_share)
        is_starved = (
            name in demanding
            and entitled > 0
            and goodput_share + starvation_tolerance < entitled
        )
        if is_starved:
            starved.append(name)
        tenants_report[name] = {
            "offered": offered.get(name, 0),
            "rejected": rejected.get(name, 0),
            "finished": tenant_stats.get(name, {}).get("finished", 0),
            "tokens": tokens,
            "good_tokens": good,
            "goodput_tokens_per_s": good / duration,
            "goodput_share": goodput_share,
            "budget_share": budget_share,
            "demand_share": demand_share,
            "starved": is_starved,
            "ttft_ms": tenant_stats.get(name, {}).get("ttft_ms", {"count": 0}),
            "tpot_ms": tenant_stats.get(name, {}).get("tpot_ms", {"count": 0}),
        }

    report = {
        "duration_s": duration,
        "steps": steps,
        "events_fired": events_fired,
        "n_requests": len(trace),
        "n_rejected": sum(rejected.values()),
        "ttft_ms": stats.get("ttft_ms", {"count": 0}),
        "tpot_ms": stats.get("tpot_ms", {"count": 0}),
        "goodput_tokens_per_s": total_good / duration,
        "prefix": stats.get("prefix", {}),
        "prefix_hit_rate": stats.get("prefix", {}).get("prefix_hit_rate", 0.0),
        "tenants": tenants_report,
        "starved_tenants": starved,
    }
    if keep_outputs:
        report["outputs"] = {
            idx: server.result(uid) for idx, uid in uid_by_index.items()
        }
    return report
