"""Version-compat shims for jax APIs the codebase relies on.

``shard_map`` moved twice across the jax releases this repo must run on:
``jax.experimental.shard_map.shard_map`` (≤0.4.x, replication check kwarg
``check_rep``) → ``jax.shard_map`` (≥0.5, kwarg renamed ``check_vma``).
Call sites import ``shard_map`` from here and always use the NEW spelling
(``check_vma``); this module translates for older jax. Keeping the shim in
one place means a future jax bump deletes this file instead of re-touching
every collective.
"""

from __future__ import annotations

import jax


def mesh_fingerprint(mesh) -> tuple:
    """Hashable identity of a Mesh — axis names, shape, and the flat
    device ids. The ONE definition shared by every cache that must not
    serve an executable (or an out_shardings contract) built for one mesh
    to arrays living on another: the paged-program cache key
    (``inference/tp.py:TPServing.cache_key``) and the pool's CoW copier
    cache (``inference/kv_pool.py``)."""
    return (
        tuple(mesh.axis_names),
        tuple(int(s) for s in mesh.devices.shape),
        tuple(d.id for d in mesh.devices.flat),
    )


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, axis_names=None, **kwargs):
    """``jax.shard_map`` with the modern signature on any supported jax.

    ``axis_names`` (new API: the mesh axes mapped manually) translates to
    the old API's complementary ``auto`` set (the axes left to GSPMD)."""
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
