"""Device synchronization barrier (the CUDA-event/stream-sync analog).

JAX dispatch is async; blocking on a trivial computation drains the default
device's queue. Single source of truth used by timers, accelerator streams,
and accelerator.synchronize.
"""

from __future__ import annotations


def device_sync() -> None:
    try:
        import jax

        # device_get round-trips through the runtime; on tunneled backends
        # block_until_ready alone can return before execution finishes.
        jax.device_get(jax.device_put(0.0) + 0)
    except Exception:
        pass
