"""Device synchronization barrier (the CUDA-event/stream-sync analog).

JAX dispatch is async; blocking on a trivial computation drains the default
device's queue. Single source of truth used by timers, accelerator streams,
and accelerator.synchronize.
"""

from __future__ import annotations


def device_sync() -> None:
    try:
        import jax

        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:
        pass
