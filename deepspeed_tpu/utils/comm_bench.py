"""Collective benchmark sweep (``ds_bench`` CLI).

Counterpart of the reference's ``bin/ds_bench`` → comm benchmark: times the
core collectives (all_reduce / all_gather / reduce_scatter / all_to_all)
over the live device mesh across a size sweep and prints achieved bus
bandwidth (same algbw/busbw accounting as
``deepspeed/utils/comms_logging.py get_bw``).
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from deepspeed_tpu.utils.jax_compat import shard_map


def _bw_gb(op: str, size_bytes: int, seconds: float, n: int) -> float:
    """Bus bandwidth in GB/s (ring-algorithm accounting, comms_logging.get_bw)."""
    if seconds == 0:
        return 0.0
    algbw = size_bytes / seconds
    if op in ("all_reduce",):
        busbw = algbw * (2 * (n - 1) / n)
    elif op in ("all_gather", "reduce_scatter", "all_to_all"):
        busbw = algbw * ((n - 1) / n)
    else:
        busbw = algbw
    return busbw / 1e9


def run_sweep(sizes_mb, trials: int = 5, warmups: int = 2):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("x",))
    results = []

    ops = {
        "all_reduce": lambda x: jax.lax.psum(x, "x"),
        "all_gather": lambda x: jax.lax.all_gather(x, "x"),
        "reduce_scatter": lambda x: jax.lax.psum_scatter(x, "x", tiled=True),
        "all_to_all": lambda x: jax.lax.all_to_all(
            x.reshape(n, -1), "x", split_axis=0, concat_axis=0
        ),
    }
    for size_mb in sizes_mb:
        elems = int(size_mb * 1e6 / 4)
        elems = max(elems - elems % (n * n), n * n)
        for name, op in ops.items():
            fn = jax.jit(
                shard_map(
                    op,
                    mesh=mesh,
                    in_specs=P("x"),
                    out_specs=P("x") if name != "all_reduce" else P(None),
                    check_vma=False,
                )
            )
            x = jax.device_put(
                jnp.ones((elems,), jnp.float32), NamedSharding(mesh, P("x"))
            )
            for _ in range(warmups):
                fn(x).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(trials):
                out = fn(x)
            out.block_until_ready()
            dt = (time.perf_counter() - t0) / trials
            results.append(
                {
                    "op": name,
                    "size_mb": size_mb,
                    "time_ms": dt * 1e3,
                    "busbw_gb_s": _bw_gb(name, elems * 4, dt, n),
                }
            )
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="deepspeed_tpu collective benchmark")
    parser.add_argument("--sizes-mb", type=float, nargs="+", default=[1, 16, 64])
    parser.add_argument("--trials", type=int, default=5)
    args = parser.parse_args(argv)
    results = run_sweep(args.sizes_mb, trials=args.trials)
    print(f"{'op':16s} {'size(MB)':>9s} {'time(ms)':>10s} {'busbw(GB/s)':>12s}")
    for r in results:
        print(
            f"{r['op']:16s} {r['size_mb']:9.1f} {r['time_ms']:10.3f} {r['busbw_gb_s']:12.2f}"
        )
    return 0
