"""Collective benchmark sweep (``ds_bench`` CLI).

Counterpart of the reference's ``bin/ds_bench`` → comm benchmark: times the
core collectives (all_reduce / all_gather / reduce_scatter / all_to_all)
over the live device mesh across a size sweep and prints achieved bus
bandwidth (same algbw/busbw accounting as
``deepspeed/utils/comms_logging.py get_bw``).
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from deepspeed_tpu.utils.jax_compat import shard_map


def _bw_gb(op: str, size_bytes: int, seconds: float, n: int) -> float:
    """Bus bandwidth in GB/s (ring-algorithm accounting, comms_logging.get_bw)."""
    if seconds == 0:
        return 0.0
    algbw = size_bytes / seconds
    if op in ("all_reduce",):
        busbw = algbw * (2 * (n - 1) / n)
    elif op in ("all_gather", "reduce_scatter", "all_to_all"):
        busbw = algbw * ((n - 1) / n)
    else:
        busbw = algbw
    return busbw / 1e9


def run_sweep(sizes_mb, trials: int = 5, warmups: int = 2):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("x",))
    results = []

    ops = {
        "all_reduce": lambda x: jax.lax.psum(x, "x"),
        "all_gather": lambda x: jax.lax.all_gather(x, "x"),
        "reduce_scatter": lambda x: jax.lax.psum_scatter(x, "x", tiled=True),
        "all_to_all": lambda x: jax.lax.all_to_all(
            x.reshape(n, -1), "x", split_axis=0, concat_axis=0
        ),
    }
    for size_mb in sizes_mb:
        elems = int(size_mb * 1e6 / 4)
        elems = max(elems - elems % (n * n), n * n)
        for name, op in ops.items():
            fn = jax.jit(
                shard_map(
                    op,
                    mesh=mesh,
                    in_specs=P("x"),
                    out_specs=P("x") if name != "all_reduce" else P(None),
                    check_vma=False,
                )
            )
            x = jax.device_put(
                jnp.ones((elems,), jnp.float32), NamedSharding(mesh, P("x"))
            )
            for _ in range(warmups):
                fn(x).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(trials):
                out = fn(x)
            out.block_until_ready()
            dt = (time.perf_counter() - t0) / trials
            results.append(
                {
                    "op": name,
                    "size_mb": size_mb,
                    "time_ms": dt * 1e3,
                    "busbw_gb_s": _bw_gb(name, elems * 4, dt, n),
                }
            )
    return results


def run_overlap_bench(size_mb: float = 16, compute_dim: int = 1024,
                      compute_iters: int = 8, trials: int = 5, warmups: int = 2):
    """Comm/compute overlap microbenchmark (ISSUE 5): wall time of a
    compute-only program (a scan of local matmuls — the stand-in for a
    layer's MXU work), a collective-only program (one all-gather, the
    stand-in for the next layer's ZeRO-3 param fetch), and one program
    containing BOTH with no data dependency between them — the shape the
    pipelined layer scan creates, which the scheduler is free to overlap.

    ``overlap_fraction`` is how much of the smaller leg disappeared into
    the larger one: (t_compute + t_collective - t_both) / min(t_compute,
    t_collective), clipped to [0, 1]. 1.0 = the cheaper leg is fully
    hidden; 0.0 = the runtime serialized them (what the ``overlap``
    analysis pass flags statically). This is the reproducible backing for
    PERF.md's hidden-vs-exposed claims: the same three programs, timed.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("x",))
    elems = max(int(size_mb * 1e6 / 4) // n * n, n)

    x = jax.device_put(jnp.ones((elems,), jnp.float32), NamedSharding(mesh, P("x")))
    w = jax.device_put(
        jnp.eye(compute_dim, dtype=jnp.float32) * 0.999,
        NamedSharding(mesh, P(None, None)),
    )

    def compute_leg(w):
        def body(c, _):
            return c @ w, None
        out, _ = jax.lax.scan(body, w, None, length=compute_iters)
        return out

    def collective_leg(x):
        return shard_map(
            lambda t: jax.lax.all_gather(t, "x", tiled=True),
            mesh=mesh, in_specs=P("x"), out_specs=P(None), check_vma=False,
        )(x)

    programs = {
        "compute_only": (jax.jit(compute_leg), (w,)),
        "collective_only": (jax.jit(collective_leg), (x,)),
        # no data dependency between the legs: the overlapped shape
        "overlapped": (jax.jit(lambda w, x: (compute_leg(w), collective_leg(x))), (w, x)),
    }
    times = {}
    for name, (fn, args) in programs.items():
        for _ in range(warmups):
            jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _ in range(trials):
            out = fn(*args)
        jax.block_until_ready(out)
        times[name] = (time.perf_counter() - t0) / trials
    t_c, t_x, t_b = times["compute_only"], times["collective_only"], times["overlapped"]
    saved = t_c + t_x - t_b
    frac = max(0.0, min(1.0, saved / max(min(t_c, t_x), 1e-12)))
    return {
        "devices": n,
        "size_mb": size_mb,
        "compute_only_ms": t_c * 1e3,
        "collective_only_ms": t_x * 1e3,
        "overlapped_ms": t_b * 1e3,
        "overlap_fraction": frac,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="deepspeed_tpu collective benchmark")
    parser.add_argument("--sizes-mb", type=float, nargs="+", default=[1, 16, 64])
    parser.add_argument("--trials", type=int, default=5)
    parser.add_argument(
        "--overlap", action="store_true",
        help="comm/compute overlap mode: compute-only vs collective-only vs "
        "one overlapped program (ISSUE 5 microbenchmark)",
    )
    parser.add_argument("--compute-iters", type=int, default=8)
    args = parser.parse_args(argv)
    if args.overlap:
        for size_mb in args.sizes_mb:
            r = run_overlap_bench(size_mb, compute_iters=args.compute_iters,
                                  trials=args.trials)
            print(
                f"devices={r['devices']} size={r['size_mb']:.1f}MB "
                f"compute={r['compute_only_ms']:.2f}ms "
                f"collective={r['collective_only_ms']:.2f}ms "
                f"overlapped={r['overlapped_ms']:.2f}ms "
                f"overlap_fraction={r['overlap_fraction']:.2f}"
            )
        return 0
    results = run_sweep(args.sizes_mb, trials=args.trials)
    print(f"{'op':16s} {'size(MB)':>9s} {'time(ms)':>10s} {'busbw(GB/s)':>12s}")
    for r in results:
        print(
            f"{r['op']:16s} {r['size_mb']:9.1f} {r['time_ms']:10.3f} {r['busbw_gb_s']:12.2f}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
