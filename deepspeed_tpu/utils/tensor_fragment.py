"""Tensor-fragment access API.

Counterpart of ``deepspeed/utils/tensor_fragment.py``: regardless of how
ZeRO sharded the state, users can read/write the full fp32 master weight,
optimizer state, and gradient of any named parameter. The reference maps
flat-partition fragments back per rank (``safe_get_full_fp32_param`` :92);
here the shardings are declarative, so "full view" is a gather
(``device_get`` of the global array) and "set" is a resharded ``device_put``.

Addressing: parameters are named by their pytree path, ``/``-joined
(e.g. ``"layers/wq"``); ``engine.parameter_names()`` lists them.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _flatten_with_paths(tree) -> Dict[str, Any]:
    """Path → leaf in jax ``tree_flatten`` order (dict keys SORTED — this
    must match ``tree_leaves`` so positional indexing into per-leaf state
    like ``HostOffloadAdam._shards`` stays aligned)."""
    out: Dict[str, Any] = {}

    def walk(prefix, t):
        if t is None:
            return  # tree_flatten drops None subtrees; stay aligned
        if isinstance(t, dict):
            for k in sorted(t.keys()):
                walk(f"{prefix}/{k}" if prefix else str(k), t[k])
        elif isinstance(t, (list, tuple)):
            for i, v in enumerate(t):
                walk(f"{prefix}/{i}" if prefix else str(i), v)
        else:
            out[prefix] = t

    walk("", tree)
    return out


def _set_in_tree(tree, path: str, value) -> bool:
    keys = path.split("/")
    node = tree
    for k in keys[:-1]:
        node = node[int(k)] if isinstance(node, (list, tuple)) else node[k]
    last = keys[-1]
    if isinstance(node, tuple):
        return False  # immutable container: caller reports failure
    if isinstance(node, list):
        node[int(last)] = value
    else:
        node[last] = value
    return True


def parameter_names(engine) -> List[str]:
    """All addressable parameter paths."""
    return list(_flatten_with_paths(engine.get_params()).keys())


def safe_get_full_fp32_param(engine, name: str) -> Optional[np.ndarray]:
    """Full fp32 master weight of ``name`` (reference :92)."""
    master = engine.get_master_params()
    if master is None:
        return None
    flat = _flatten_with_paths(master)
    if name not in flat:
        return None
    return np.asarray(jax.device_get(flat[name]), dtype=np.float32)


def safe_set_full_fp32_param(engine, name: str, value) -> bool:
    """Overwrite the master weight (and the live compute param) of ``name``
    (reference ``safe_set_full_fp32_param``)."""
    value = np.asarray(value, dtype=np.float32)
    if engine._host_offload is not None:
        leaves_paths = list(_flatten_with_paths(engine.get_params()).keys())
        if name not in leaves_paths:
            return False
        li = leaves_paths.index(name)
        ho = engine._host_offload
        for sh in ho._shards[li]:
            sh.master[:] = value[sh.index].reshape(-1)
        _refresh_param_from_master(engine, name, value)
        return True
    master = engine._master
    if master is None:
        return False
    flat = _flatten_with_paths(master)
    if name not in flat:
        return False
    old = flat[name]
    new = jax.device_put(jnp.asarray(value, jnp.float32), old.sharding)
    if not _set_in_tree(master, name, new):
        return False
    _refresh_param_from_master(engine, name, value)
    return True


def _refresh_param_from_master(engine, name: str, value: np.ndarray) -> None:
    params = engine._params
    flat = _flatten_with_paths(params)
    if name in flat:
        old = flat[name]
        new = jax.device_put(jnp.asarray(value).astype(old.dtype), old.sharding)
        _set_in_tree(params, name, new)


def safe_get_full_grad(engine, name: str) -> Optional[np.ndarray]:
    """Full (accumulated) gradient of ``name`` (reference
    ``safe_get_full_grad``). Note grads are scaled by loss-scale × gas until
    the step consumes them. Under the engine's fused step there is no live
    accumulator; the grad is recomputed from the last micro-batch."""
    grads = engine.get_last_grads() if hasattr(engine, "get_last_grads") else engine._grad_acc
    if grads is None:
        return None
    flat = _flatten_with_paths(grads)
    if name not in flat:
        return None
    return np.asarray(jax.device_get(flat[name]), dtype=np.float32)


_STATE_ALIASES = {
    "exp_avg": ("exp_avg", "m", "mu"),
    "exp_avg_sq": ("exp_avg_sq", "v", "nu"),
}


def _resolve_state_key(state_key: str) -> Optional[str]:
    """Canonical host-offload state name for torch-style aliases; None when
    the key names no Adam state (mirrors the non-offload alias lookup)."""
    for canonical, aliases in _STATE_ALIASES.items():
        if state_key == canonical or state_key in aliases:
            return canonical
    return None


def safe_get_full_optimizer_state(engine, name: str, state_key: str) -> Optional[np.ndarray]:
    """Full optimizer state tensor for ``name`` (reference
    ``safe_get_full_optimizer_state``): ``state_key`` in
    {exp_avg, exp_avg_sq} (torch names; mapped onto the functional state)."""
    if engine._host_offload is not None:
        key = _resolve_state_key(state_key)
        if key is None:
            return None
        ho = engine._host_offload
        paths = list(_flatten_with_paths(engine.get_params()).keys())
        if name not in paths:
            return None
        li = paths.index(name)
        sd = ho.state_dict()
        recs = sd["leaves"][li]
        full = np.zeros(ho._shapes[li], np.float32)
        for sh, rec in zip(ho._shards[li], recs):
            from deepspeed_tpu.runtime.zero.offload_states import _index_shape

            full[sh.index] = np.asarray(rec[key]).reshape(_index_shape(sh.index, ho._shapes[li]))
        return full
    opt_state = engine._opt_state
    if opt_state is None:
        return None
    aliases = _STATE_ALIASES.get(state_key, (state_key,))
    for field in getattr(opt_state, "_fields", []):
        if field in aliases or state_key == field:
            tree = getattr(opt_state, field)
            flat = _flatten_with_paths(tree)
            if name in flat:
                return np.asarray(jax.device_get(flat[name]), dtype=np.float32)
    return None


def safe_set_full_optimizer_state(engine, name: str, state_key: str, value) -> bool:
    value = np.asarray(value, dtype=np.float32)
    if engine._host_offload is not None:
        key = _resolve_state_key(state_key)
        if key is None:
            return False
        ho = engine._host_offload
        paths = list(_flatten_with_paths(engine.get_params()).keys())
        if name not in paths:
            return False
        li = paths.index(name)
        if ho.swapper is not None:
            for sh in ho._shards[li]:
                m = np.empty_like(sh.master)
                v = np.empty_like(sh.master)
                ho.swapper.fetch_param(sh.param_id, {"exp_avg": m, "exp_avg_sq": v})
                tgt = {"exp_avg": m, "exp_avg_sq": v}
                tgt[key][:] = value[sh.index].reshape(-1)
                ho.swapper.swap_out_param(sh.param_id, tgt)
        else:
            for sh in ho._shards[li]:
                arr = sh.exp_avg if key == "exp_avg" else sh.exp_avg_sq
                arr[:] = value[sh.index].reshape(-1)
        return True
    opt_state = engine._opt_state
    if opt_state is None:
        return False
    aliases = _STATE_ALIASES.get(state_key, (state_key,))
    for field in getattr(opt_state, "_fields", []):
        if field in aliases or state_key == field:
            tree = getattr(opt_state, field)
            flat = _flatten_with_paths(tree)
            if name not in flat:
                return False
            old = flat[name]
            return _set_in_tree(
                tree, name, jax.device_put(jnp.asarray(value, jnp.float32), old.sharding)
            )
    return False
