"""Deterministic fault-injection harness for crash-restart testing.

Production TPU jobs die at arbitrary instants — slices are preempted, hosts
OOM, disks fill mid-write. The fault-tolerance guarantees this repo makes
(``latest`` never resolves to a torn checkpoint; ``auto_resume`` losses are
bit-identical; serving streams resume byte-identically from the journal) are
only guarantees if a kill at EVERY dangerous instant is actually exercised.
This module names those instants as **injection points** and arms them with
a seeded, fully deterministic schedule, so the crash-restart test matrix is
reproducible down to the byte.

Injection points (the canonical set — sites call ``chaos.point(NAME, ...)``):

* ``ckpt.pre_commit``      — checkpoint fully staged, rename not yet issued
* ``ckpt.mid_commit``      — re-save of an existing tag: the old checkpoint
  is moved aside and the new one not yet renamed in (the only instant the
  tag has no directory; recovery restores the moved-aside copy)
* ``ckpt.mid_array_write`` — between the array payload and the metadata
  write inside the staging dir (a half-written snapshot)
* ``ckpt.post_commit``     — directory renamed into place, ``latest`` marker
  not yet updated
* ``serve.mid_step``       — inside the serving scheduler step, after the
  device dispatch/emits but before the journal flush
* ``train.mid_window``     — inside a multi-step TRAINING window
  (``compile.multi_step``): the fused N-step program was dispatched and
  the engine adopted the donated state, but the window's per-step losses
  have not been drained and none of its steps committed to the counters —
  a kill here must resume bit-identically from the last committed
  checkpoint (windows never straddle a checkpoint interval, so that
  checkpoint sits at or before the window's first step)
* ``train.mid_step``       — a single optimizer step: the step program was
  dispatched and the engine adopted the donated state, but none of the
  host bookkeeping (counters, lr schedule, interval checkpoint) committed;
  a kill here must resume bit-identically from the last committed
  checkpoint — exercised on the expert-sharded MoE config, whose param
  tree spans two mesh axes
* ``journal.append``       — right after a journal record batch reaches the
  OS (the classic torn-tail instant; pair with the ``truncate`` action)
* ``fleet.replica_kill``   — at the top of one replica's turn inside the
  fleet router's step loop (``inference/fleet.py``): the replica is its
  own failure domain, so a ``raise`` here is ONE replica dying while the
  router and the rest of the fleet survive (the router catches the kill
  and re-routes the dead replica's live requests from its journal); the
  ``exit`` action still kills the whole process — the ``-m slow``
  restart-and-adopt case
* ``fleet.mid_migration``  — inside a live request migration, after the
  state left the source replica's memory but before the target durably
  re-seeded it (the double-claim/no-claim window the target-journal-first
  ordering and router-side dedup exist for)
* ``fleet.mid_drain``      — between two migrations of an elastic drain:
  the draining replica dies half-emptied and the remainder must re-route
  from its journal with zero acked tokens dropped

Actions:

* ``raise``    — raise :class:`ChaosKilled` (a ``BaseException`` subclass, so
  ordinary ``except Exception`` recovery code cannot swallow it — exactly
  like a real SIGKILL, nothing downstream of the point runs). In a
  background writer thread this kills the thread silently, leaving torn
  files behind — the in-process simulation of dying mid-write.
* ``exit``     — ``os._exit(137)``: a REAL abrupt death (no atexit, no
  flushing). For the subprocess-driven slow matrix.
* ``truncate`` — chop ``nbytes`` off the end of ``ctx["path"]`` (a torn
  append), then die via ``raise``.
* ``corrupt``  — overwrite the last ``nbytes`` of ``ctx["path"]`` with
  deterministic garbage (bitrot / partial overwrite), then die via
  ``raise``.

Usage::

    from deepspeed_tpu.utils import chaos
    chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("ckpt.pre_commit")]))
    try:
        engine.save_checkpoint(d)      # dies at the armed instant
    except chaos.ChaosKilled:
        pass
    finally:
        chaos.uninstall()
    # ... build a fresh engine and auto_resume: the guarantees must hold.

The default state is DISARMED: ``chaos.point`` is a single ``is None`` check,
so production code paths pay nothing.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

# The canonical injection points. Sites may add new ones; tests iterate this
# list to build the crash matrix, so keep it in sync with the call sites.
POINTS = (
    "ckpt.pre_commit",
    "ckpt.mid_commit",  # re-save window: old checkpoint moved aside, new not yet in place
    "ckpt.mid_array_write",
    "ckpt.post_commit",
    "serve.mid_step",
    "serve.mid_window",  # inside a multi-step window's host phase: the whole
    # window's tokens are buffered in the journal, none yet acked
    "train.mid_window",  # training window dispatched + state adopted, loss
    # drain not yet run and no step of the window committed to the counters
    "train.mid_step",  # a single optimizer step: the step program dispatched
    # and the donated state adopted, but the counters / lr schedule / interval
    # checkpoint not yet committed — resume must replay from the last
    # committed checkpoint bit-identically (the MoE expert-sharded state
    # rides the same contract as the dense tree)
    "train.mid_offload_stream",  # ZeRO-Infinity streamed step, mid-bucket:
    # some host offload buffers updated, others not, the step uncommitted —
    # resume must rebuild the host state from the last checkpoint, never
    # trust the torn buffers

    "journal.append",
    "fleet.replica_kill",  # one replica's turn in the fleet step loop: raise =
    # that replica dies (router survives + re-routes), exit = whole process
    "fleet.mid_migration",  # state off the source, not yet durable on the target
    "fleet.mid_drain",  # a draining replica dies between two migrations
)

_ACTIONS = ("raise", "exit", "truncate", "corrupt")


class ChaosKilled(BaseException):
    """The simulated kill. Deliberately NOT an ``Exception``: recovery/retry
    code that catches ``Exception`` must not be able to 'survive' a kill —
    nothing after the injection point may run, same as SIGKILL."""


@dataclass
class ChaosRule:
    """Fire ``action`` on the ``hit``-th arrival at ``point`` (1-based)."""

    point: str
    hit: int = 1
    action: str = "raise"
    nbytes: int = 16  # tail bytes for truncate/corrupt
    fired: bool = field(default=False, init=False)

    def __post_init__(self):
        if self.action not in _ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r} (have {_ACTIONS})")
        if self.hit < 1:
            raise ValueError(f"hit is 1-based, got {self.hit}")


class ChaosSchedule:
    """An armed set of rules plus per-point arrival counters. Deterministic:
    the n-th arrival at a point always sees the same verdict."""

    def __init__(self, rules: Sequence[ChaosRule]):
        self.rules = list(rules)
        self.counts: Dict[str, int] = {}
        self.fired_log: List[str] = []  # "<point>#<hit>:<action>" per firing

    def fire(self, point: str, **ctx) -> None:
        n = self.counts.get(point, 0) + 1
        self.counts[point] = n
        for rule in self.rules:
            if rule.fired or rule.point != point or rule.hit != n:
                continue
            rule.fired = True
            self.fired_log.append(f"{point}#{n}:{rule.action}")
            self._act(rule, ctx)

    def _act(self, rule: ChaosRule, ctx: Dict) -> None:
        # pre-death hooks (the flight recorder): run BEFORE the action so a
        # postmortem dump exists even for the real os._exit, which skips
        # every atexit/finally downstream. Hook failures never save the
        # process — the kill proceeds regardless.
        for hook in list(_KILL_HOOKS):
            try:
                hook(rule.point, rule.action)
            except Exception:
                pass
        if rule.action == "exit":
            os._exit(137)  # the real thing: no atexit, no flushing
        if rule.action in ("truncate", "corrupt"):
            # file surgery applies only to file-backed points (journal
            # segments); on a directory-backed point (checkpoint staging)
            # the action degrades to the plain kill — it must never raise
            # an ordinary, swallowable IsADirectoryError instead
            path = ctx.get("path")
            if path and os.path.isfile(path):
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    if rule.action == "truncate":
                        f.truncate(max(0, size - rule.nbytes))
                    else:
                        n = min(rule.nbytes, size)
                        f.seek(size - n)
                        # deterministic garbage: position-keyed, not random
                        f.write(bytes((0xA5 ^ (i & 0xFF)) for i in range(n)))
        raise ChaosKilled(f"chaos: killed at {rule.point} (hit {rule.hit})")


_SCHEDULE: Optional[ChaosSchedule] = None

# Pre-death hooks: callables ``(point, action) -> None`` run right before a
# rule's action executes (before the ChaosKilled raise AND before the real
# os._exit). The flight recorder (profiling/tracer.py) registers here so
# every injected kill leaves a postmortem file naming the armed point.
_KILL_HOOKS: List = []


def add_kill_hook(fn) -> None:
    if fn not in _KILL_HOOKS:
        _KILL_HOOKS.append(fn)


def remove_kill_hook(fn) -> None:
    if fn in _KILL_HOOKS:
        _KILL_HOOKS.remove(fn)


def install(schedule: ChaosSchedule) -> ChaosSchedule:
    """Arm a schedule (replacing any armed one) and return it."""
    global _SCHEDULE
    _SCHEDULE = schedule
    return schedule


def uninstall() -> None:
    global _SCHEDULE
    _SCHEDULE = None


def active() -> Optional[ChaosSchedule]:
    return _SCHEDULE


def point(name: str, **ctx) -> None:
    """An injection site. Free when disarmed (one None check)."""
    if _SCHEDULE is not None:
        _SCHEDULE.fire(name, **ctx)


def seeded_schedule(
    seed: int,
    points: Sequence[str] = POINTS,
    n_faults: int = 1,
    max_hit: int = 3,
    actions: Sequence[str] = ("raise",),
) -> ChaosSchedule:
    """A reproducible schedule: ``seed`` fully determines which points fire,
    on which arrival, with which action — the matrix tests sweep seeds
    instead of hand-writing every combination."""
    import numpy as np

    rs = np.random.RandomState(seed)
    rules = [
        ChaosRule(
            point=points[int(rs.randint(len(points)))],
            hit=int(rs.randint(1, max_hit + 1)),
            action=actions[int(rs.randint(len(actions)))],
        )
        for _ in range(n_faults)
    ]
    return ChaosSchedule(rules)
