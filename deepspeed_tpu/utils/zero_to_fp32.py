"""Offline ZeRO-checkpoint consolidation.

Counterpart of the reference's ``deepspeed/utils/zero_to_fp32.py``
(``_get_fp32_state_dict_from_zero_checkpoint`` :194): turn a sharded
deepspeed_tpu checkpoint directory into a single consolidated fp32 state
file loadable without the engine (framework-free: a flat dict of numpy
arrays, saved as ``.npz``).

CLI (the reference's usage)::

    python -m deepspeed_tpu.utils.zero_to_fp32 <checkpoint_dir> <output_file> [--tag TAG]
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths


def _flatten(tree) -> Dict[str, np.ndarray]:
    """Path → fp32 leaf, in jax tree_flatten order (shared traversal with
    the fragment API so positional pairing with per-leaf state is safe)."""
    return {
        k: np.asarray(v, dtype=np.float32)
        for k, v in _flatten_with_paths(tree).items()
        if v is not None
    }


def get_fp32_state_dict_from_zero_checkpoint(
    checkpoint_dir: str, tag: Optional[str] = None
) -> Dict[str, np.ndarray]:
    """Full fp32 weights from a sharded checkpoint (reference :194). Prefers
    the fp32 master (exact optimizer view); falls back to the module
    weights upcast to fp32."""
    from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
        OrbaxCheckpointEngine,
    )

    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if os.path.isfile(latest):
            with open(latest) as f:
                tag = f.read().strip()
    path = os.path.join(checkpoint_dir, tag) if tag else checkpoint_dir
    state = OrbaxCheckpointEngine().load(path)

    master = state.get("master")
    if master is None:
        opt = state.get("optimizer")
        if isinstance(opt, dict) and "host_offload" in opt:
            # offload checkpoints keep the master inside the host-state dict;
            # reassemble each leaf from its shard records
            module_flat = _flatten(state["module"])
            names = list(module_flat.keys())
            out: Dict[str, np.ndarray] = {}
            for name, per in zip(names, opt["host_offload"]["leaves"]):
                full = np.zeros(module_flat[name].shape, np.float32)
                for rec in per:
                    sl = tuple(slice(a, b) for a, b in rec["index"])
                    full[sl] = np.asarray(rec["master"], np.float32).reshape(full[sl].shape)
                out[name] = full
            return out
        master = state.get("module")
    return _flatten(master)


def convert_zero_checkpoint_to_fp32_state_dict(
    checkpoint_dir: str, output_file: str, tag: Optional[str] = None
) -> None:
    """(reference ``convert_zero_checkpoint_to_fp32_state_dict``)"""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file, **sd)
    total = sum(v.size for v in sd.values())
    print(f"saved {len(sd)} tensors ({total:,} fp32 params) to {output_file}")


def load_state_dict_from_zero_checkpoint(model_params: Any, checkpoint_dir: str, tag=None):
    """Overwrite a param pytree's leaves with consolidated fp32 weights
    (reference ``load_state_dict_from_zero_checkpoint``)."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)

    def rebuild(tree, prefix=""):
        if isinstance(tree, dict):
            return {k: rebuild(v, f"{prefix}/{k}" if prefix else str(k)) for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            vals = [rebuild(v, f"{prefix}/{i}" if prefix else str(i)) for i, v in enumerate(tree)]
            return type(tree)(vals)
        return sd.get(prefix, tree)

    return rebuild(model_params)


def main():
    parser = argparse.ArgumentParser(description="consolidate a ZeRO checkpoint to fp32")
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("--tag", default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_file, args.tag)


if __name__ == "__main__":
    main()
