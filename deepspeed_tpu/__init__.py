"""deepspeed_tpu — a TPU-native training/inference framework with the
capability surface of DeepSpeed (reference: microsoft/DeepSpeed v0.10.2),
re-designed for JAX/XLA/Pallas/pjit.

Top-level API mirrors the reference's ``deepspeed/__init__.py``:
``initialize`` (:64), ``init_inference`` (:269), ``add_config_arguments``
(:246), ``init_distributed`` (:38), plus the ``zero``/``comm``/``ops``
namespaces.
"""

from __future__ import annotations

import argparse
from typing import Any, Callable, Optional, Tuple

__version__ = "0.1.0"
__git_hash__ = None
__git_branch__ = None

from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu import comm as comm
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments
from deepspeed_tpu.utils.logging import log_dist, logger

dist = comm

HAS_TRITON = False  # parity probe (deepspeed/__init__.py:15); TPU uses Pallas


def initialize(
    args=None,
    model=None,
    optimizer=None,
    model_parameters=None,
    training_data=None,
    lr_scheduler=None,
    mpu=None,
    dist_init_required: Optional[bool] = None,
    collate_fn: Optional[Callable] = None,
    config: Any = None,
    config_params: Any = None,
    loss_fn: Optional[Callable] = None,
) -> Tuple[Any, Any, Any, Any]:
    """Build the training engine (reference ``deepspeed.initialize``
    ``deepspeed/__init__.py:64``).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    Selects ``PipelineEngine`` for a ``PipelineModule`` and the hybrid engine
    when ``hybrid_engine.enabled``, else ``DeepSpeedEngine``
    (reference :158-196).
    """
    log_dist(f"deepspeed_tpu info: version={__version__}", ranks=[0])

    # a live zero.Init context must not wrap engine construction; PAUSE it
    # and restore on the way out (reference __init__.py:128
    # shutdown_init_context + restore_init_context before returning)
    _init_depth = zero.shutdown_init_context()
    try:
        return _initialize_paused(
            args, model, optimizer, model_parameters, training_data,
            lr_scheduler, mpu, dist_init_required, collate_fn, config,
            config_params, loss_fn,
        )
    finally:
        zero.restore_init_context(_init_depth)


def _initialize_paused(
    args, model, optimizer, model_parameters, training_data, lr_scheduler,
    mpu, dist_init_required, collate_fn, config, config_params, loss_fn,
):
    if model is None:
        raise AssertionError("deepspeed.initialize requires a model")

    if config is None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") and args.deepspeed_config is not None:
        config = args.deepspeed_config
    if config is None:
        config = {}

    if dist_init_required is None or dist_init_required:
        init_distributed(dist_backend=get_accelerator().communication_backend_name())

    ds_config = DeepSpeedConfig(config, mpu)

    from deepspeed_tpu.pipe import PipelineModule

    if hasattr(ds_config, "hybrid_engine") and ds_config.hybrid_engine.enabled:
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

        engine = DeepSpeedHybridEngine(
            args=args,
            model=model,
            optimizer=optimizer,
            model_parameters=model_parameters,
            training_data=training_data,
            lr_scheduler=lr_scheduler,
            mpu=mpu,
            dist_init_required=dist_init_required,
            collate_fn=collate_fn,
            config=config,
            config_class=ds_config,
            loss_fn=loss_fn,
        )
    elif isinstance(model, PipelineModule):
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

        engine = PipelineEngine(
            args=args,
            model=model,
            optimizer=optimizer,
            model_parameters=model_parameters,
            training_data=training_data,
            lr_scheduler=lr_scheduler,
            mpu=model.mpu() if hasattr(model, "mpu") else mpu,
            dist_init_required=dist_init_required,
            collate_fn=collate_fn,
            config=config,
            config_class=ds_config,
            loss_fn=loss_fn,
        )
    else:
        from deepspeed_tpu.runtime.engine import DeepSpeedEngine

        engine = DeepSpeedEngine(
            args=args,
            model=model,
            optimizer=optimizer,
            model_parameters=model_parameters,
            training_data=training_data,
            lr_scheduler=lr_scheduler,
            mpu=mpu,
            dist_init_required=dist_init_required,
            collate_fn=collate_fn,
            config=config,
            config_class=ds_config,
            loss_fn=loss_fn,
        )

    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def add_config_arguments(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """Add ``--deepspeed`` / ``--deepspeed_config`` CLI args (reference :205-243)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument(
        "--deepspeed",
        default=False,
        action="store_true",
        help="Enable DeepSpeed (helper flag to easily toggle).",
    )
    group.add_argument("--deepspeed_config", default=None, type=str, help="DeepSpeed json config file.")
    group.add_argument(
        "--deepscale",
        default=False,
        action="store_true",
        help=argparse.SUPPRESS,
    )
    group.add_argument("--deepscale_config", default=None, type=str, help=argparse.SUPPRESS)
    return parser


def default_inference_config():
    """Default inference config dict (reference :262)."""
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    return DeepSpeedInferenceConfig().model_dump()


def init_inference(model, config=None, **kwargs):
    """Build an inference engine (reference :269)."""
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine

    log_dist(f"deepspeed_tpu inference info: version={__version__}", ranks=[0])
    if config is None:
        config = {}
    if isinstance(config, DeepSpeedInferenceConfig):
        ds_inference_config = config
    else:
        config_dict = dict(config)
        config_dict.update(kwargs)
        ds_inference_config = DeepSpeedInferenceConfig(**config_dict)
    return InferenceEngine(model, config=ds_inference_config)


# namespaces mirroring the reference exports
from deepspeed_tpu import ops  # noqa: E402
from deepspeed_tpu import zero  # noqa: E402
from deepspeed_tpu.runtime import lr_schedules  # noqa: E402
from deepspeed_tpu.pipe import PipelineModule  # noqa: E402
from deepspeed_tpu.runtime.module import DSModule  # noqa: E402
from deepspeed_tpu.ops.transformer.transformer import (  # noqa: E402
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)


class OnDevice:
    """Construction-placement context (reference ``deepspeed/__init__.py:37``
    ``OnDevice``: meta-device model building). Functional init makes this a
    placement hint: inside the context, ``jax.default_device`` points at the
    requested device ('meta' maps to abstract shapes — build with
    ``jax.eval_shape`` for a true zero-memory init)."""

    def __init__(self, dtype=None, device: str = "", enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._ctx = None

    def __enter__(self):
        if not self.enabled or self.device in ("", "meta"):
            return self
        import jax

        kind = self.device.split(":")[0]
        devs = [d for d in jax.devices() if kind in (d.platform, str(d))]
        if devs:
            self._ctx = jax.default_device(devs[0])
            self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
            self._ctx = None
        return False
