"""zero namespace (reference: ``deepspeed/runtime/zero/__init__.py`` re-exports).

``zero.Init`` and ``GatheredParameters`` exist in the reference because eager
PyTorch must physically partition/gather tensors around construction and use
(``partition_parameters.py:709,1938``). Under GSPMD the partitioner owns data
movement, so both are cheap context managers that carry intent:

* ``Init`` — records that models built inside should be initialized directly
  into sharded buffers (the engine already does this for every model via
  jitted init with sharded out-shardings, so the context is a no-op marker
  kept for API compatibility).
* ``GatheredParameters`` — yields fully-replicated host views of requested
  params for user-side surgery, writing modifications back on exit when
  ``modifier_rank`` is set.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax

from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig, ZeroStageEnum
from deepspeed_tpu.runtime.zero.offload_config import (
    DeepSpeedZeroOffloadOptimizerConfig,
    DeepSpeedZeroOffloadParamConfig,
    OffloadDeviceEnum,
)
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner, estimate_zero_memory
from deepspeed_tpu.runtime.zero.tiling import TiledLinear, TiledLinearReturnBias

_init_ctx_depth = 0


class Init(contextlib.AbstractContextManager):
    """API-parity context (reference zero.Init, partition_parameters.py:709).

    Nesting-safe like the reference (tests/unit/runtime/zero/
    test_zero_nesting_init.py): a depth counter, so exiting an inner
    context leaves the outer one active."""

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True,
                 remote_device=None, pin_memory=False, config_dict_or_path=None,
                 config=None, enabled=True, dtype=None, mpu=None):  # noqa: ARG002
        self.enabled = enabled

    def __enter__(self):
        global _init_ctx_depth
        if self.enabled:
            _init_ctx_depth += 1
        return self

    def __exit__(self, *exc):
        global _init_ctx_depth
        if self.enabled and _init_ctx_depth > 0:
            _init_ctx_depth -= 1
        return False


def is_init_context_active() -> bool:
    return _init_ctx_depth > 0


def shutdown_init_context() -> int:
    """Pause the context (reference partition_parameters.py:541 — called by
    ``deepspeed.initialize`` so engine construction isn't nested inside a
    live Init context). Returns the prior depth for ``restore_init_context``."""
    global _init_ctx_depth
    prior = _init_ctx_depth
    _init_ctx_depth = 0
    return prior


def restore_init_context(depth: int) -> None:
    """Resume a paused context (reference ``Init._enable_class`` re-patch on
    restore): ``initialize()`` pauses around engine construction, then code
    after it inside the same ``with zero.Init():`` block sees an active
    context again."""
    global _init_ctx_depth
    _init_ctx_depth = depth


class GatheredParameters(contextlib.AbstractContextManager):
    """Yield replicated views of sharded params (reference :1938).

    ``params`` is a pytree of jax.Arrays (possibly sharded) — or ``None``
    with ``engine`` set, meaning the engine's full param tree. On enter,
    leaves are fully gathered to host numpy arrays; on exit with
    ``modifier_rank`` set, mutations are written back automatically:

    * ``engine=...`` — the engine re-adopts the (whole) tree via
      ``engine.set_params`` (master + compute store refreshed, the
      reference's transparent re-partition on exit);
    * ``write_back=...`` — custom callback escape hatch for partial trees.

    Passing a partial tree with ``modifier_rank`` and no write-back path
    raises: the mutation would otherwise be silently dropped.
    """

    def __init__(self, params: Any = None, modifier_rank: Optional[int] = None, fwd_module=None, enabled: bool = True, write_back=None, engine=None):  # noqa: ARG002
        self.engine = engine
        if params is None:
            if engine is None:
                raise ValueError("GatheredParameters needs params or engine")
            params = engine.get_params()
            self._is_full_tree = True
        else:
            # compare against the engine's treedef, NOT get_params(): the
            # offload path's gathered_params materializes the full model
            # host-side, far too expensive for a structure check
            self._is_full_tree = engine is not None and (
                jax.tree_util.tree_structure(params) == engine.get_param_treedef()
            )
        self.params = params
        self.modifier_rank = modifier_rank
        self.enabled = enabled
        self.write_back = write_back
        self.gathered = None
        if (
            enabled
            and modifier_rank is not None
            and write_back is None
            and not self._is_full_tree
        ):
            raise ValueError(
                "GatheredParameters(modifier_rank=...) on a partial tree has "
                "no write-back path: pass the engine's full param tree (or "
                "engine=..., or a write_back callback) so mutations stick"
            )

    def __enter__(self):
        if not self.enabled:
            return self.params
        import numpy as np

        # np.array copy: device_get hands back read-only views
        self.gathered = jax.tree_util.tree_map(
            lambda p: np.array(jax.device_get(p)), self.params
        )
        return self.gathered

    def __exit__(self, *exc):
        if not (self.enabled and self.modifier_rank is not None):
            return False
        if self.write_back is not None:
            self.write_back(self.gathered)
        elif self.engine is not None:
            self.engine.set_params(self.gathered)
        return False


__all__ = [
    "Init",
    "GatheredParameters",
    "DeepSpeedZeroConfig",
    "ZeroStageEnum",
    "ZeroPartitioner",
    "estimate_zero_memory",
    "OffloadDeviceEnum",
    "DeepSpeedZeroOffloadParamConfig",
    "DeepSpeedZeroOffloadOptimizerConfig",
    "shutdown_init_context",
    "TiledLinear",
    "TiledLinearReturnBias",
]
