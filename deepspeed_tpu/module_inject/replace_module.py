"""Module replacement entry points.

Counterpart of the reference's ``replace_transformer_layer``
(``deepspeed/module_inject/replace_module.py:181``): instead of swapping
``nn.Module`` instances for kernel-injected ones in place, the TPU path
converts the whole model — HF config + state dict → the fused TPU decoder
(``TransformerLM``) with converted weights, AutoTP PartitionSpecs, and the
KV-cache decode programs (``inference/decode.py``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerLM
from deepspeed_tpu.module_inject.auto_tp import AutoTP
from deepspeed_tpu.module_inject.containers import DSPolicy, policy_for
from deepspeed_tpu.utils.logging import log_dist


def _hf_state_dict_to_numpy(model) -> Dict[str, np.ndarray]:
    """Flat numpy state dict from a torch model / state dict / numpy dict."""
    if hasattr(model, "state_dict"):
        sd = model.state_dict()
    else:
        sd = model
    out = {}
    for k, v in sd.items():
        if hasattr(v, "detach"):
            v = v.detach().cpu().float().numpy()
        out[k] = np.asarray(v)
    return out


def _strip_known_prefixes(sd: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """HF *ForCausalLM wrappers prefix the base model (transformer./model.);
    policies expect specific prefixes — normalize gpt2's 'transformer.'."""
    if any(k.startswith("transformer.h.") for k in sd):
        return {k[len("transformer.") :] if k.startswith("transformer.") else k: v for k, v in sd.items()}
    return sd


def replace_transformer_layer(
    orig_layer_impl=None,  # noqa: ARG001 - reference signature parity
    model=None,
    checkpoint_dict=None,  # noqa: ARG001 - sharded ckpt loading via engine
    config=None,
    model_config=None,
    dtype: Optional[str] = None,
) -> Tuple[TransformerLM, Optional[Dict[str, Any]]]:
    """Convert an HF model (or its config) to the injected TPU decoder.

    Returns ``(ds_model, params)`` — params is None when only a config was
    given (weights then come from a checkpoint or fresh init).
    """
    hf_config = model_config
    if hf_config is None and model is not None and hasattr(model, "config"):
        hf_config = model.config
    if hf_config is None:
        raise ValueError("replace_transformer_layer needs model or model_config")
    model_type = getattr(hf_config, "model_type", None) or type(hf_config).__name__
    policy = policy_for(model_type)
    if hasattr(policy, "build_moe_config"):
        from deepspeed_tpu.models.moe_transformer import MoETransformerLM

        ds_config = policy.build_moe_config(hf_config)
        model_cls = MoETransformerLM
    else:
        ds_config = policy.build_config(hf_config)
        model_cls = TransformerLM
    if dtype is not None:
        ds_config.dtype = dtype
    ds_model = model_cls(ds_config)
    log_dist(
        f"module_inject: {model_type} → TransformerLM "
        f"(L={ds_config.num_layers}, H={ds_config.hidden_size}, "
        f"heads={ds_config.num_heads}/{ds_config.num_kv_heads})",
        ranks=[0],
    )
    params = None
    if model is not None and not isinstance(model, type):
        sd = _strip_known_prefixes(_hf_state_dict_to_numpy(model))
        params = policy.convert_weights(sd, ds_config)
    return ds_model, params


def generic_injection(model, dtype=None, enable_cuda_graph=False):  # noqa: ARG001
    """Diffusers-style generic injection (reference replace_module.py:86).

    The reference walks a diffusers pipeline's UNet/VAE and swaps attention
    blocks for DS kernels; the TPU counterpart wraps the spatial model
    families (``models/unet.py``) in an ``InferenceEngine`` so their
    ``tp_partition_rules`` sharding specs are applied and the forward is
    jitted (XLA supplies the fused bias-add the reference hand-writes in
    ``csrc/spatial/csrc/opt_bias_add.cu``). Non-spatial modules pass through
    unchanged, mirroring the reference's policy-miss behavior."""
    from deepspeed_tpu.models.unet import AutoencoderKL, UNet2DConditionModel

    if isinstance(model, (UNet2DConditionModel, AutoencoderKL)):
        import deepspeed_tpu as ds

        s = str(dtype) if dtype is not None else "fp32"
        if "bfloat16" in s or s == "bf16":
            dt = "bf16"
        elif "float16" in s or s in ("fp16", "half"):
            dt = "fp16"
        elif "int8" in s:
            dt = "int8"
        else:
            dt = "fp32"
        return ds.init_inference(model, dtype=dt)
    return model


def tp_shard_specs(params_shapes: Any, mp_axis: str = "model") -> Any:
    """AutoTP over an arbitrary param tree (reference AutoTP entry)."""
    return AutoTP(mp_axis=mp_axis).partition_specs(params_shapes)
