"""Kernel injection / AutoTP (reference: ``deepspeed/module_inject/``)."""

from deepspeed_tpu.module_inject.auto_tp import (
    AutoTP,
    Classification,
    ReplaceWithTensorSlicing,
    classify_param,
    spec_for_param,
)
from deepspeed_tpu.module_inject.containers import DSPolicy, policy_for, replace_policies
from deepspeed_tpu.module_inject.replace_module import (
    generic_injection,
    replace_transformer_layer,
    tp_shard_specs,
)
