"""AutoTP — automatic tensor-parallel sharding from a parameter walk.

TPU-native counterpart of the reference's ``AutoTP``
(``deepspeed/module_inject/auto_tp.py:170``) and
``ReplaceWithTensorSlicing`` (:19): the reference parses the module graph to
find which Linears feed an all-reduce and physically slices their weights
per rank; here the same walk runs over the *parameter pytree* and emits
GSPMD ``PartitionSpec``s over the ``model`` mesh axis — the XLA partitioner
then inserts exactly the all-reduces the reference's ``LinearAllreduce``
performs by hand.

Classification (the reference's policy, module_inject/layers.py:15,32):
* column-parallel (shard OUTPUT features): q/k/v/gate/up/fc-in projections —
  any matmul whose output feeds a nonlinearity or head-split;
* row-parallel (shard INPUT features): attention-out and fc-out projections —
  their outputs sum across ranks (the all-reduce point);
* replicated: norms, biases of row-parallel layers, embeddings (or
  vocab-sharded when requested).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

# name-pattern tables (matched against the last path component, lowercase)
COLUMN_PATTERNS = [
    r"w?q(_proj|_lin)?$", r"w?k(_proj|_lin)?$", r"w?v(_proj|_lin)?$",
    r"(w_)?qkv(_proj)?$", r"query(_key_value)?$", r"key$", r"value$",
    r"(w_)?gate(_proj)?$", r"(w_)?up(_proj)?$", r"w_in$", r"fc1$", r"c_fc$",
    r"wi(_\d+)?$", r"dense_h_to_4h$", r"intermediate$",
]
ROW_PATTERNS = [
    r"w?o(_proj|ut_proj)?$", r"out(_proj)?$", r"w_out$", r"fc2$", r"c_proj$",
    r"wo$", r"dense_4h_to_h$", r"attn_out$", r"dense$", r"o_proj$", r"down_proj$",
]
VOCAB_PATTERNS = [r"tokens$", r"wte$", r"embed_tokens$", r"word_embeddings$", r"lm_head$"]
NORM_PATTERNS = [r".*norm.*", r"ln_\w+$", r".*layernorm.*"]


def _matches(name: str, patterns: List[str]) -> bool:
    return any(re.fullmatch(p, name) for p in patterns)


class Classification:
    COLUMN = "column"
    ROW = "row"
    VOCAB = "vocab"
    REPLICATE = "replicate"


def classify_param(path: str) -> str:
    """Classify one parameter by its tree path (reference AutoTP
    ``tp_parser`` semantics via names instead of graph ops)."""
    name = path.split("/")[-1].lower()
    if _matches(name, NORM_PATTERNS):
        return Classification.REPLICATE
    if _matches(name, COLUMN_PATTERNS):
        return Classification.COLUMN
    if _matches(name, ROW_PATTERNS):
        return Classification.ROW
    if _matches(name, VOCAB_PATTERNS):
        return Classification.VOCAB
    return Classification.REPLICATE


def spec_for_param(path: str, shape: Tuple[int, ...], mp_axis: str = "model") -> P:
    """PartitionSpec for one leaf. Stacked [L, in, out] layer weights keep
    the leading scan dim unsharded (the flagship model layout)."""
    kind = classify_param(path)
    nd = len(shape)
    if nd == 0 or kind == Classification.REPLICATE:
        return P(*([None] * nd))
    stacked = nd == 3
    if kind == Classification.COLUMN:
        # shard output features (last dim); 1-D bias of a column layer
        # shards its only dim
        if nd == 1:
            return P(mp_axis)
        return P(None, None, mp_axis) if stacked else P(None, mp_axis)
    if kind == Classification.ROW:
        # shard input features (second-to-last dim); row biases replicate
        # (they are added after the all-reduce)
        if nd == 1:
            return P(None)
        return P(None, mp_axis, None) if stacked else P(mp_axis, None)
    if kind == Classification.VOCAB:
        if nd == 1:
            return P(None)
        name = path.split("/")[-1].lower()
        if name == "lm_head":
            return P(None, mp_axis)  # output-vocab sharded
        return P(mp_axis, None)  # input-vocab sharded embedding
    return P(*([None] * nd))


class AutoTP:
    """Emit a PartitionSpec tree for an arbitrary param pytree
    (reference AutoTP class, auto_tp.py:170)."""

    def __init__(self, mp_axis: str = "model", overrides: Optional[Dict[str, P]] = None):
        self.mp_axis = mp_axis
        self.overrides = overrides or {}

    def partition_specs(self, params_shapes: Any) -> Any:
        def walk(prefix: str, tree):
            if isinstance(tree, dict):
                return {k: walk(f"{prefix}/{k}", v) for k, v in tree.items()}
            if isinstance(tree, (list, tuple)):
                out = [walk(f"{prefix}/{i}", v) for i, v in enumerate(tree)]
                return type(tree)(out)
            shape = tuple(getattr(tree, "shape", np.shape(tree)))
            for pat, spec in self.overrides.items():
                if re.fullmatch(pat, prefix):
                    return spec
            return spec_for_param(prefix, shape, self.mp_axis)

        return walk("", params_shapes)

    def validate(self, params_shapes: Any, specs: Any, mp_size: int) -> List[str]:
        """Report leaves whose sharded dim is not divisible by mp_size
        (the reference errors at slice time; we surface it up front)."""
        problems: List[str] = []

        def walk(prefix, tree, spec):
            if isinstance(tree, dict):
                for k in tree:
                    walk(f"{prefix}/{k}", tree[k], spec[k])
                return
            shape = tuple(getattr(tree, "shape", np.shape(tree)))
            for dim, entry in zip(shape, tuple(spec) + (None,) * len(shape)):
                if entry is not None and dim % mp_size != 0:
                    problems.append(f"{prefix}: dim {dim} not divisible by mp={mp_size}")

        walk("", params_shapes, specs)
        return problems


class ReplaceWithTensorSlicing:
    """Physically slice a host weight for one model-parallel rank —
    used by the sharded checkpoint loader when weights arrive as full host
    arrays (reference module_inject/auto_tp.py:19)."""

    def __init__(self, mp_rank: int = 0, mp_size: int = 1, mp_axis: str = "model"):
        self.mp_rank = mp_rank
        self.mp_size = mp_size
        self.mp_axis = mp_axis

    def shard(self, path: str, weight: np.ndarray) -> np.ndarray:
        spec = spec_for_param(path, weight.shape, self.mp_axis)
        for axis, entry in enumerate(spec):
            if entry == self.mp_axis:
                dim = weight.shape[axis]
                assert dim % self.mp_size == 0, f"{path}: {dim} % {self.mp_size} != 0"
                size = dim // self.mp_size
                sl = [slice(None)] * weight.ndim
                sl[axis] = slice(self.mp_rank * size, (self.mp_rank + 1) * size)
                return weight[tuple(sl)]
        return weight
