"""LoRA adapters for RLHF rollouts (DS-Chat).

Counterpart of the reference's ``HybridEngineContainer`` LoRA feature
(``deepspeed/module_inject/containers/features/hybrid_engine.py:50-80``:
``set_lora_params`` / ``fuse_lora`` / ``unfuse_lora``, driven by
``DeepSpeedHybridEngine.fuse_lora_weight`` at
``deepspeed/runtime/hybrid_engine.py:141``). The reference fuses by mutating
``param.data += scaling * left.T @ right.T`` before a rollout and subtracting
after — an approximate restore in half precision.

TPU-native design: LoRA state is a pytree mirroring the targeted weight
leaves. Fusing is a *pure function* producing a new param tree (one einsum
per stacked layer weight, batched over the layer dim — MXU-friendly), and
unfusing on the hybrid engine is EXACT: the compute-dtype store is recast
from the untouched fp32 master instead of subtracting the delta back in
bf16.

Layout: model weights here are stacked over layers — ``params["layers"][k]``
is ``[L, in, out]`` — so a LoRA pair is ``right [L, in, r]`` and ``left
[L, r, out]`` and the delta is ``einsum('lir,lro->lio')``. Plain 2-D leaves
(no leading layer dim) get the unbatched pair.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

# default targets: the attention projections (the DS-Chat / LoRA-paper
# default) — callers widen to MLP weights via LoRAConfig.target_keys
DEFAULT_TARGET_KEYS = ("wq", "wk", "wv", "wo")


@dataclass
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    target_keys: Tuple[str, ...] = DEFAULT_TARGET_KEYS

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def _is_matrix(leaf) -> bool:
    return hasattr(leaf, "ndim") and leaf.ndim in (2, 3)


def init_lora_params(params: Dict[str, Any], config: LoRAConfig, rng) -> Dict[str, Any]:
    """LoRA state for every targeted weight: ``right`` ~ N(0, 1/r) (the
    down-projection), ``left`` = 0 (so the adapter starts as identity —
    standard LoRA init). Returns ``{"layers": {key: {"right", "left"}}}``
    mirroring the model tree's targeted leaves."""
    layers = params.get("layers", {})
    out: Dict[str, Any] = {"layers": {}}
    r = config.rank
    for key in config.target_keys:
        if key not in layers or not _is_matrix(layers[key]):
            continue
        w = layers[key]
        rng, sub = jax.random.split(rng)
        if w.ndim == 3:  # stacked [L, in, out]
            L, d_in, d_out = w.shape
            right = jax.random.normal(sub, (L, d_in, r), jnp.float32) / jnp.sqrt(r)
            left = jnp.zeros((L, r, d_out), jnp.float32)
        else:
            d_in, d_out = w.shape
            right = jax.random.normal(sub, (d_in, r), jnp.float32) / jnp.sqrt(r)
            left = jnp.zeros((r, d_out), jnp.float32)
        out["layers"][key] = {"right": right, "left": left}
    if not out["layers"]:
        raise ValueError(
            f"no LoRA targets matched: target_keys={config.target_keys}, "
            f"layer weights={[k for k, v in layers.items() if _is_matrix(v)]}"
        )
    return out


def lora_delta(pair: Dict[str, Any], scaling: float, dtype=None):
    """``scaling * right @ left`` (batched over the stacked layer dim)."""
    right, left = pair["right"], pair["left"]
    if right.ndim == 3:
        delta = jnp.einsum("lir,lro->lio", right, left)
    else:
        delta = right @ left
    delta = scaling * delta
    return delta.astype(dtype) if dtype is not None else delta


def fuse_lora_tree(params: Dict[str, Any], lora: Dict[str, Any], scaling: float) -> Dict[str, Any]:
    """New param tree with every targeted weight replaced by
    ``W + scaling * right @ left`` (reference ``fuse_lora``,
    hybrid_engine.py feature :63). Pure — the input tree is untouched."""
    new_layers = dict(params["layers"])
    for key, pair in lora["layers"].items():
        w = new_layers[key]
        new_layers[key] = (
            w.astype(jnp.float32) + lora_delta(pair, scaling)
        ).astype(w.dtype)
    out = dict(params)
    out["layers"] = new_layers
    return out


def unfuse_lora_tree(params: Dict[str, Any], lora: Dict[str, Any], scaling: float) -> Dict[str, Any]:
    """Inverse of ``fuse_lora_tree`` (reference ``unfuse_lora`` :72). NOTE:
    in half precision this is an approximate restore (same as the
    reference's ``param.data -=``); the hybrid engine restores exactly by
    recasting from the fp32 master instead."""
    neg = {
        "layers": {
            k: {"right": p["right"], "left": -p["left"]}
            for k, p in lora["layers"].items()
        }
    }
    return fuse_lora_tree(params, neg, scaling)


def maybe_get_lora(lora: Optional[Dict[str, Any]], key: str) -> List[Any]:
    """Reference-shaped probe (``maybe_get_lora``): ``[right, left]`` when
    ``key`` has an adapter, else ``[]`` (scaling lives on LoRAConfig /
    the engine, not per-pair)."""
    if lora is None or key not in lora.get("layers", {}):
        return []
    pair = lora["layers"][key]
    return [pair["right"], pair["left"]]
