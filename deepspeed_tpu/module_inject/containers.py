"""Per-architecture injection policies.

Counterpart of the reference's policy/container layer
(``deepspeed/module_inject/containers/`` + ``replace_policy.py``): a policy
knows how to map one HuggingFace architecture onto the fused TPU decoder
(``models/transformer.py TransformerLM`` — the analog of
``DeepSpeedTransformerInference``): config translation + weight-layout
conversion (attention/mlp extraction, the reference's
``TransformerPolicy.attention()/mlp()`` contract).

Weights arrive as a flat HF state dict of numpy arrays (from torch or
safetensors); ``convert_weights`` re-lays them into the stacked [L, ...]
param tree, transposing torch's [out, in] Linear convention to the [in, out]
matmul layout the TPU model uses.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.models.config import TransformerConfig


def _t(w: np.ndarray) -> np.ndarray:
    """torch Linear [out, in] → matmul [in, out]."""
    return np.ascontiguousarray(np.asarray(w).T)


def _stack(arrs: List[np.ndarray]) -> np.ndarray:
    return np.stack([np.asarray(a) for a in arrs], axis=0)


class DSPolicy:
    """Base policy (reference module_inject/policy.py:224 DSPolicy)."""

    model_types: List[str] = []

    @classmethod
    def matches(cls, model_type: str) -> bool:
        return model_type.lower() in cls.model_types

    def build_config(self, hf_config) -> TransformerConfig:
        raise NotImplementedError

    def convert_weights(self, sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict[str, Any]:
        raise NotImplementedError


class GPT2Policy(DSPolicy):
    """gpt2 (reference containers/gpt2.py): learned positions, gelu, fused
    c_attn qkv, Conv1D weights already [in, out]."""

    model_types = ["gpt2"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.n_embd,
            num_layers=c.n_layer,
            num_heads=c.n_head,
            max_seq_len=c.n_positions,
            norm="layernorm",
            position="learned",
            activation="gelu",
            use_bias=True,
            tie_embeddings=True,
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L, H = cfg.num_layers, cfg.hidden_size
        # HF GPT-2 Conv1D stores [in, out] already — no transpose
        qkv = [np.asarray(sd[f"h.{i}.attn.c_attn.weight"]) for i in range(L)]
        qkv_b = [np.asarray(sd[f"h.{i}.attn.c_attn.bias"]) for i in range(L)]
        layer = {
            "attn_norm_scale": _stack([sd[f"h.{i}.ln_1.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"h.{i}.ln_1.bias"] for i in range(L)]),
            "wq": _stack([w[:, :H] for w in qkv]),
            "wk": _stack([w[:, H : 2 * H] for w in qkv]),
            "wv": _stack([w[:, 2 * H :] for w in qkv]),
            "bq": _stack([b[:H] for b in qkv_b]),
            "bk": _stack([b[H : 2 * H] for b in qkv_b]),
            "bv": _stack([b[2 * H :] for b in qkv_b]),
            "wo": _stack([sd[f"h.{i}.attn.c_proj.weight"] for i in range(L)]),
            "bo": _stack([sd[f"h.{i}.attn.c_proj.bias"] for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"h.{i}.ln_2.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"h.{i}.ln_2.bias"] for i in range(L)]),
            "w_in": _stack([sd[f"h.{i}.mlp.c_fc.weight"] for i in range(L)]),
            "b_in": _stack([sd[f"h.{i}.mlp.c_fc.bias"] for i in range(L)]),
            "w_out": _stack([sd[f"h.{i}.mlp.c_proj.weight"] for i in range(L)]),
            "b_out": _stack([sd[f"h.{i}.mlp.c_proj.bias"] for i in range(L)]),
        }
        return {
            "embed": {"tokens": np.asarray(sd["wte.weight"]), "pos": np.asarray(sd["wpe.weight"])},
            "layers": layer,
            "final_norm_scale": np.asarray(sd["ln_f.weight"]),
            "final_norm_bias": np.asarray(sd["ln_f.bias"]),
        }


class LlamaPolicy(DSPolicy):
    """llama/llama2 + mistral (reference containers/llama.py): RMSNorm,
    RoPE, SwiGLU, GQA, untied head."""

    model_types = ["llama", "mistral"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=c.intermediate_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            num_kv_heads=getattr(c, "num_key_value_heads", c.num_attention_heads),
            max_seq_len=getattr(c, "max_position_embeddings", 4096),
            norm="rmsnorm",
            norm_eps=getattr(c, "rms_norm_eps", 1e-5),
            position="rope",
            rope_theta=getattr(c, "rope_theta", 10000.0),
            activation="swiglu",
            use_bias=False,
            tie_embeddings=getattr(c, "tie_word_embeddings", False),
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L = cfg.num_layers

        def lw(i, name):
            return _t(sd[f"model.layers.{i}.{name}.weight"])

        layer = {
            "attn_norm_scale": _stack(
                [sd[f"model.layers.{i}.input_layernorm.weight"] for i in range(L)]
            ),
            "wq": _stack([lw(i, "self_attn.q_proj") for i in range(L)]),
            "wk": _stack([lw(i, "self_attn.k_proj") for i in range(L)]),
            "wv": _stack([lw(i, "self_attn.v_proj") for i in range(L)]),
            "wo": _stack([lw(i, "self_attn.o_proj") for i in range(L)]),
            "mlp_norm_scale": _stack(
                [sd[f"model.layers.{i}.post_attention_layernorm.weight"] for i in range(L)]
            ),
            "w_gate": _stack([lw(i, "mlp.gate_proj") for i in range(L)]),
            "w_up": _stack([lw(i, "mlp.up_proj") for i in range(L)]),
            "w_out": _stack([lw(i, "mlp.down_proj") for i in range(L)]),
        }
        params = {
            "embed": {"tokens": np.asarray(sd["model.embed_tokens.weight"])},
            "layers": layer,
            "final_norm_scale": np.asarray(sd["model.norm.weight"]),
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = _t(sd["lm_head.weight"])
        return params


class OPTPolicy(DSPolicy):
    """opt (reference containers/opt.py): learned positions (offset 2 handled
    by caller), relu, layernorm, tied head."""

    model_types = ["opt"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=c.ffn_dim,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            norm="layernorm",
            position="learned",
            activation="relu",
            use_bias=True,
            tie_embeddings=True,
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L = cfg.num_layers
        pre = "model.decoder."

        def lw(i, name):
            return _t(sd[f"{pre}layers.{i}.{name}.weight"])

        def lb(i, name):
            return np.asarray(sd[f"{pre}layers.{i}.{name}.bias"])

        layer = {
            "attn_norm_scale": _stack([sd[f"{pre}layers.{i}.self_attn_layer_norm.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{pre}layers.{i}.self_attn_layer_norm.bias"] for i in range(L)]),
            "wq": _stack([lw(i, "self_attn.q_proj") for i in range(L)]),
            "wk": _stack([lw(i, "self_attn.k_proj") for i in range(L)]),
            "wv": _stack([lw(i, "self_attn.v_proj") for i in range(L)]),
            "bq": _stack([lb(i, "self_attn.q_proj") for i in range(L)]),
            "bk": _stack([lb(i, "self_attn.k_proj") for i in range(L)]),
            "bv": _stack([lb(i, "self_attn.v_proj") for i in range(L)]),
            "wo": _stack([lw(i, "self_attn.out_proj") for i in range(L)]),
            "bo": _stack([lb(i, "self_attn.out_proj") for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"{pre}layers.{i}.final_layer_norm.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{pre}layers.{i}.final_layer_norm.bias"] for i in range(L)]),
            "w_in": _stack([lw(i, "fc1") for i in range(L)]),
            "b_in": _stack([lb(i, "fc1") for i in range(L)]),
            "w_out": _stack([lw(i, "fc2") for i in range(L)]),
            "b_out": _stack([lb(i, "fc2") for i in range(L)]),
        }
        # OPT's positional table has a +2 offset; rows 2: align to position 0
        pos = np.asarray(sd[f"{pre}embed_positions.weight"])[2:]
        return {
            "embed": {"tokens": np.asarray(sd[f"{pre}embed_tokens.weight"]), "pos": pos},
            "layers": layer,
            "final_norm_scale": np.asarray(sd[f"{pre}final_layer_norm.weight"]),
            "final_norm_bias": np.asarray(sd[f"{pre}final_layer_norm.bias"]),
        }


class GPTNeoXPolicy(DSPolicy):
    """gpt_neox (reference containers/gptneox.py): rope, gelu, fused qkv."""

    model_types = ["gpt_neox", "gptneox"]

    def build_config(self, c) -> TransformerConfig:
        head_dim = c.hidden_size // c.num_attention_heads
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=c.intermediate_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            norm="layernorm",
            position="rope",
            rope_theta=getattr(c, "rotary_emb_base", 10000.0),
            # NeoX rotates rotary_pct of each head (0.25 on Pythia/NeoX-20B)
            rope_dim=int(getattr(c, "rotary_pct", 1.0) * head_dim),
            activation="gelu",
            use_bias=True,
            tie_embeddings=False,
            # HF default use_parallel_residual=True: x + attn(ln1 x) + mlp(ln2 x)
            parallel_residual=bool(getattr(c, "use_parallel_residual", True)),
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L, H = cfg.num_layers, cfg.hidden_size
        NH, D = cfg.num_heads, cfg.head_dim
        pre = "gpt_neox."
        wqs, wks, wvs, bqs, bks, bvs = [], [], [], [], [], []
        for i in range(L):
            # neox fuses qkv interleaved per head: [NH, 3, D, H]
            w = np.asarray(sd[f"{pre}layers.{i}.attention.query_key_value.weight"])
            b = np.asarray(sd[f"{pre}layers.{i}.attention.query_key_value.bias"])
            w = w.reshape(NH, 3, D, H)
            b = b.reshape(NH, 3, D)
            wqs.append(np.ascontiguousarray(w[:, 0].reshape(NH * D, H).T))
            wks.append(np.ascontiguousarray(w[:, 1].reshape(NH * D, H).T))
            wvs.append(np.ascontiguousarray(w[:, 2].reshape(NH * D, H).T))
            bqs.append(b[:, 0].reshape(-1))
            bks.append(b[:, 1].reshape(-1))
            bvs.append(b[:, 2].reshape(-1))
        layer = {
            "attn_norm_scale": _stack([sd[f"{pre}layers.{i}.input_layernorm.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{pre}layers.{i}.input_layernorm.bias"] for i in range(L)]),
            "wq": _stack(wqs),
            "wk": _stack(wks),
            "wv": _stack(wvs),
            "bq": _stack(bqs),
            "bk": _stack(bks),
            "bv": _stack(bvs),
            "wo": _stack([_t(sd[f"{pre}layers.{i}.attention.dense.weight"]) for i in range(L)]),
            "bo": _stack([sd[f"{pre}layers.{i}.attention.dense.bias"] for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"{pre}layers.{i}.post_attention_layernorm.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{pre}layers.{i}.post_attention_layernorm.bias"] for i in range(L)]),
            "w_in": _stack([_t(sd[f"{pre}layers.{i}.mlp.dense_h_to_4h.weight"]) for i in range(L)]),
            "b_in": _stack([sd[f"{pre}layers.{i}.mlp.dense_h_to_4h.bias"] for i in range(L)]),
            "w_out": _stack([_t(sd[f"{pre}layers.{i}.mlp.dense_4h_to_h.weight"]) for i in range(L)]),
            "b_out": _stack([sd[f"{pre}layers.{i}.mlp.dense_4h_to_h.bias"] for i in range(L)]),
        }
        return {
            "embed": {"tokens": np.asarray(sd[f"{pre}embed_in.weight"])},
            "layers": layer,
            "final_norm_scale": np.asarray(sd[f"{pre}final_layer_norm.weight"]),
            "final_norm_bias": np.asarray(sd[f"{pre}final_layer_norm.bias"]),
            "lm_head": _t(sd["embed_out.weight"]),
        }


class BloomPolicy(DSPolicy):
    """bloom (reference containers/bloom.py): alibi positions, gelu."""

    model_types = ["bloom"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            num_layers=c.n_layer,
            num_heads=c.n_head,
            max_seq_len=2048,
            norm="layernorm",
            position="alibi",
            activation="gelu",
            use_bias=True,
            tie_embeddings=True,
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L, H = cfg.num_layers, cfg.hidden_size
        NH, D = cfg.num_heads, cfg.head_dim
        pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        wqs, wks, wvs, bqs, bks, bvs = [], [], [], [], [], []
        for i in range(L):
            w = np.asarray(sd[f"{pre}h.{i}.self_attention.query_key_value.weight"])
            b = np.asarray(sd[f"{pre}h.{i}.self_attention.query_key_value.bias"])
            w = w.reshape(NH, 3, D, H)
            b = b.reshape(NH, 3, D)
            wqs.append(np.ascontiguousarray(w[:, 0].reshape(NH * D, H).T))
            wks.append(np.ascontiguousarray(w[:, 1].reshape(NH * D, H).T))
            wvs.append(np.ascontiguousarray(w[:, 2].reshape(NH * D, H).T))
            bqs.append(b[:, 0].reshape(-1))
            bks.append(b[:, 1].reshape(-1))
            bvs.append(b[:, 2].reshape(-1))
        layer = {
            "attn_norm_scale": _stack([sd[f"{pre}h.{i}.input_layernorm.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{pre}h.{i}.input_layernorm.bias"] for i in range(L)]),
            "wq": _stack(wqs), "wk": _stack(wks), "wv": _stack(wvs),
            "bq": _stack(bqs), "bk": _stack(bks), "bv": _stack(bvs),
            "wo": _stack([_t(sd[f"{pre}h.{i}.self_attention.dense.weight"]) for i in range(L)]),
            "bo": _stack([sd[f"{pre}h.{i}.self_attention.dense.bias"] for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"{pre}h.{i}.post_attention_layernorm.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{pre}h.{i}.post_attention_layernorm.bias"] for i in range(L)]),
            "w_in": _stack([_t(sd[f"{pre}h.{i}.mlp.dense_h_to_4h.weight"]) for i in range(L)]),
            "b_in": _stack([sd[f"{pre}h.{i}.mlp.dense_h_to_4h.bias"] for i in range(L)]),
            "w_out": _stack([_t(sd[f"{pre}h.{i}.mlp.dense_4h_to_h.weight"]) for i in range(L)]),
            "b_out": _stack([sd[f"{pre}h.{i}.mlp.dense_4h_to_h.bias"] for i in range(L)]),
        }
        return {
            "embed": {"tokens": np.asarray(sd[f"{pre}word_embeddings.weight"])},
            "layers": layer,
            "final_norm_scale": np.asarray(sd[f"{pre}ln_f.weight"]),
            "final_norm_bias": np.asarray(sd[f"{pre}ln_f.bias"]),
        }


class GPTJPolicy(DSPolicy):
    """gptj (reference containers/gptj.py): parallel attention+mlp off a
    SHARED ln_1, partial rotary over ``rotary_dim`` dims, untied head with
    bias. HF GPT-J's interleaved (rotate-every-two) rotary is absorbed at
    conversion: the rotary span of wq/wk is permuted even-then-odd so the
    family's rotate-half kernel computes identical scores."""

    model_types = ["gptj"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.n_embd,
            num_layers=c.n_layer,
            num_heads=c.n_head,
            max_seq_len=c.n_positions,
            norm="layernorm",
            position="rope",
            rope_dim=int(getattr(c, "rotary_dim", None) or (c.n_embd // c.n_head)),
            activation="gelu",
            use_bias=True,
            qkv_bias=False,
            tie_embeddings=False,
            parallel_residual=True,
            shared_parallel_norm=True,
            lm_head_bias=True,
        )

    @staticmethod
    def _rotary_perm(cfg) -> np.ndarray:
        """Per-head feature order turning HF's interleaved rotary layout
        into the family's rotate-half layout (evens then odds within the
        rotary span; the tail passes through)."""
        D, rot = cfg.head_dim, int(cfg.rope_dim or cfg.head_dim)
        order = np.concatenate(
            [np.arange(0, rot, 2), np.arange(1, rot, 2), np.arange(rot, D)]
        )
        return order

    def _permute_qk(self, w, cfg) -> np.ndarray:
        """[H, NH*D] column permutation within each head's feature block."""
        NH, D = cfg.num_heads, cfg.head_dim
        order = self._rotary_perm(cfg)
        cols = w.reshape(w.shape[0], NH, D)[:, :, order]
        return np.ascontiguousarray(cols.reshape(w.shape[0], NH * D))

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L = cfg.num_layers
        # the loader may have normalized the ForCausalLM 'transformer.' prefix
        pre = "transformer." if any(k.startswith("transformer.h.") for k in sd) else ""
        layer = {
            "attn_norm_scale": _stack([sd[f"{pre}h.{i}.ln_1.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{pre}h.{i}.ln_1.bias"] for i in range(L)]),
            "wq": _stack([self._permute_qk(_t(sd[f"{pre}h.{i}.attn.q_proj.weight"]), cfg) for i in range(L)]),
            "wk": _stack([self._permute_qk(_t(sd[f"{pre}h.{i}.attn.k_proj.weight"]), cfg) for i in range(L)]),
            "wv": _stack([_t(sd[f"{pre}h.{i}.attn.v_proj.weight"]) for i in range(L)]),
            "wo": _stack([_t(sd[f"{pre}h.{i}.attn.out_proj.weight"]) for i in range(L)]),
            "bo": _stack([np.zeros(cfg.hidden_size, np.float32) for _ in range(L)]),
            # parallel residual reads ONE shared ln_1 (shared_parallel_norm);
            # the mlp_norm slots stay populated for tree-shape stability but
            # are ignored by the layer
            "mlp_norm_scale": _stack([sd[f"{pre}h.{i}.ln_1.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{pre}h.{i}.ln_1.bias"] for i in range(L)]),
            "w_in": _stack([_t(sd[f"{pre}h.{i}.mlp.fc_in.weight"]) for i in range(L)]),
            "b_in": _stack([sd[f"{pre}h.{i}.mlp.fc_in.bias"] for i in range(L)]),
            "w_out": _stack([_t(sd[f"{pre}h.{i}.mlp.fc_out.weight"]) for i in range(L)]),
            "b_out": _stack([sd[f"{pre}h.{i}.mlp.fc_out.bias"] for i in range(L)]),
        }
        out = {
            "embed": {"tokens": np.asarray(sd[f"{pre}wte.weight"])},
            "layers": layer,
            "final_norm_scale": np.asarray(sd[f"{pre}ln_f.weight"]),
            "final_norm_bias": np.asarray(sd[f"{pre}ln_f.bias"]),
            "lm_head": _t(sd["lm_head.weight"]),
        }
        out["lm_head_bias"] = np.asarray(
            sd.get("lm_head.bias", np.zeros(cfg.vocab_size, np.float32))
        )
        return out


class BertPolicy(DSPolicy):
    """bert (reference containers/bert.py): post-LN encoder, bidirectional
    attention, learned positions + embedding LayerNorm, gelu. The
    single-segment token_type row 0 is folded into the position table, so
    inference without segment ids matches HF exactly."""

    model_types = ["bert"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=c.intermediate_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            causal=False,
            prenorm=False,
            embed_norm=True,
            norm="layernorm",
            norm_eps=getattr(c, "layer_norm_eps", 1e-12),
            position="learned",
            activation="gelu",
            use_bias=True,
            tie_embeddings=True,
        )

    _prefix = "bert."

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L = cfg.num_layers
        pre = self._prefix if any(k.startswith(self._prefix) for k in sd) else ""
        emb = f"{pre}embeddings."
        enc = f"{pre}encoder.layer."

        def lw(i, name):
            return _t(sd[f"{enc}{i}.{name}.weight"])

        def lb(i, name):
            return np.asarray(sd[f"{enc}{i}.{name}.bias"])

        layer = {
            # post-LN: attn_norm follows attention+residual, mlp_norm the FFN
            "attn_norm_scale": _stack([sd[f"{enc}{i}.attention.output.LayerNorm.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{enc}{i}.attention.output.LayerNorm.bias"] for i in range(L)]),
            "wq": _stack([lw(i, "attention.self.query") for i in range(L)]),
            "wk": _stack([lw(i, "attention.self.key") for i in range(L)]),
            "wv": _stack([lw(i, "attention.self.value") for i in range(L)]),
            "bq": _stack([lb(i, "attention.self.query") for i in range(L)]),
            "bk": _stack([lb(i, "attention.self.key") for i in range(L)]),
            "bv": _stack([lb(i, "attention.self.value") for i in range(L)]),
            "wo": _stack([lw(i, "attention.output.dense") for i in range(L)]),
            "bo": _stack([lb(i, "attention.output.dense") for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"{enc}{i}.output.LayerNorm.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{enc}{i}.output.LayerNorm.bias"] for i in range(L)]),
            "w_in": _stack([lw(i, "intermediate.dense") for i in range(L)]),
            "b_in": _stack([lb(i, "intermediate.dense") for i in range(L)]),
            "w_out": _stack([lw(i, "output.dense") for i in range(L)]),
            "b_out": _stack([lb(i, "output.dense") for i in range(L)]),
        }
        pos = np.asarray(sd[f"{emb}position_embeddings.weight"])
        tt_key = f"{emb}token_type_embeddings.weight"
        if tt_key in sd:
            pos = pos + np.asarray(sd[tt_key])[0][None, :]
        return {
            "embed": {
                "tokens": np.asarray(sd[f"{emb}word_embeddings.weight"]),
                "pos": pos,
                "norm_scale": np.asarray(sd[f"{emb}LayerNorm.weight"]),
                "norm_bias": np.asarray(sd[f"{emb}LayerNorm.bias"]),
            },
            "layers": layer,
        }


class DistilBertPolicy(DSPolicy):
    """distil_bert (reference containers/distil_bert.py): BERT family without
    token types; HF distilbert names."""

    model_types = ["distilbert", "distil_bert"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.dim,
            intermediate_size=c.hidden_dim,
            num_layers=c.n_layers,
            num_heads=c.n_heads,
            max_seq_len=c.max_position_embeddings,
            causal=False,
            prenorm=False,
            embed_norm=True,
            norm="layernorm",
            norm_eps=1e-12,
            position="learned",
            activation="gelu",
            use_bias=True,
            tie_embeddings=True,
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L = cfg.num_layers
        pre = "distilbert." if any(k.startswith("distilbert.") for k in sd) else ""
        emb = f"{pre}embeddings."
        enc = f"{pre}transformer.layer."

        def lw(i, name):
            return _t(sd[f"{enc}{i}.{name}.weight"])

        def lb(i, name):
            return np.asarray(sd[f"{enc}{i}.{name}.bias"])

        layer = {
            "attn_norm_scale": _stack([sd[f"{enc}{i}.sa_layer_norm.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{enc}{i}.sa_layer_norm.bias"] for i in range(L)]),
            "wq": _stack([lw(i, "attention.q_lin") for i in range(L)]),
            "wk": _stack([lw(i, "attention.k_lin") for i in range(L)]),
            "wv": _stack([lw(i, "attention.v_lin") for i in range(L)]),
            "bq": _stack([lb(i, "attention.q_lin") for i in range(L)]),
            "bk": _stack([lb(i, "attention.k_lin") for i in range(L)]),
            "bv": _stack([lb(i, "attention.v_lin") for i in range(L)]),
            "wo": _stack([lw(i, "attention.out_lin") for i in range(L)]),
            "bo": _stack([lb(i, "attention.out_lin") for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"{enc}{i}.output_layer_norm.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{enc}{i}.output_layer_norm.bias"] for i in range(L)]),
            "w_in": _stack([lw(i, "ffn.lin1") for i in range(L)]),
            "b_in": _stack([lb(i, "ffn.lin1") for i in range(L)]),
            "w_out": _stack([lw(i, "ffn.lin2") for i in range(L)]),
            "b_out": _stack([lb(i, "ffn.lin2") for i in range(L)]),
        }
        return {
            "embed": {
                "tokens": np.asarray(sd[f"{emb}word_embeddings.weight"]),
                "pos": np.asarray(sd[f"{emb}position_embeddings.weight"]),
                "norm_scale": np.asarray(sd[f"{emb}LayerNorm.weight"]),
                "norm_bias": np.asarray(sd[f"{emb}LayerNorm.bias"]),
            },
            "layers": layer,
        }


class GPTNeoPolicy(DSPolicy):
    """gpt_neo (reference containers/gptneo.py): learned positions, gelu,
    qkv without biases. HF alternates global/local (windowed) attention
    blocks; this port computes full causal attention for both — identical
    whenever the sequence fits the local window (256 for the released
    checkpoints)."""

    model_types = ["gpt_neo", "gptneo"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=getattr(c, "intermediate_size", None) or 4 * c.hidden_size,
            num_layers=c.num_layers,
            num_heads=c.num_heads,
            max_seq_len=c.max_position_embeddings,
            norm="layernorm",
            position="learned",
            activation="gelu",
            use_bias=True,
            qkv_bias=False,
            attn_softmax_scale=1.0,  # GPT-Neo's unscaled attention scores
            tie_embeddings=True,
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L = cfg.num_layers
        pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

        def lw(i, name):
            return _t(sd[f"{pre}h.{i}.{name}.weight"])

        def lb(i, name):
            return np.asarray(sd[f"{pre}h.{i}.{name}.bias"])

        layer = {
            "attn_norm_scale": _stack([sd[f"{pre}h.{i}.ln_1.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{pre}h.{i}.ln_1.bias"] for i in range(L)]),
            "wq": _stack([lw(i, "attn.attention.q_proj") for i in range(L)]),
            "wk": _stack([lw(i, "attn.attention.k_proj") for i in range(L)]),
            "wv": _stack([lw(i, "attn.attention.v_proj") for i in range(L)]),
            "wo": _stack([lw(i, "attn.attention.out_proj") for i in range(L)]),
            "bo": _stack([lb(i, "attn.attention.out_proj") for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"{pre}h.{i}.ln_2.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{pre}h.{i}.ln_2.bias"] for i in range(L)]),
            "w_in": _stack([lw(i, "mlp.c_fc") for i in range(L)]),
            "b_in": _stack([lb(i, "mlp.c_fc") for i in range(L)]),
            "w_out": _stack([lw(i, "mlp.c_proj") for i in range(L)]),
            "b_out": _stack([lb(i, "mlp.c_proj") for i in range(L)]),
        }
        return {
            "embed": {
                "tokens": np.asarray(sd[f"{pre}wte.weight"]),
                "pos": np.asarray(sd[f"{pre}wpe.weight"]),
            },
            "layers": layer,
            "final_norm_scale": np.asarray(sd[f"{pre}ln_f.weight"]),
            "final_norm_bias": np.asarray(sd[f"{pre}ln_f.bias"]),
        }


class MegatronGPTPolicy(DSPolicy):
    """megatron_gpt (reference containers/megatron_gpt.py): Megatron-LM GPT
    layout — fused per-head-interleaved qkv (same [NH, 3, D] packing as
    NeoX, its descendant), learned positions, gelu."""

    model_types = ["megatron-gpt", "megatron_gpt", "megatron"]

    def build_config(self, c) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=getattr(c, "ffn_hidden_size", None) or 4 * c.hidden_size,
            num_layers=getattr(c, "num_layers", None) or c.num_hidden_layers,
            num_heads=getattr(c, "num_attention_heads", None),
            max_seq_len=getattr(c, "max_position_embeddings", 2048),
            norm="layernorm",
            position="learned",
            activation="gelu",
            use_bias=True,
            tie_embeddings=True,
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L, H = cfg.num_layers, cfg.hidden_size
        NH, D = cfg.num_heads, cfg.head_dim
        lyr = "language_model.transformer.layers."
        emb = "language_model.embedding."
        wqs, wks, wvs, bqs, bks, bvs = [], [], [], [], [], []
        for i in range(L):
            w = np.asarray(sd[f"{lyr}{i}.attention.query_key_value.weight"])
            b = np.asarray(sd[f"{lyr}{i}.attention.query_key_value.bias"])
            w = w.reshape(NH, 3, D, H)
            b = b.reshape(NH, 3, D)
            wqs.append(np.ascontiguousarray(w[:, 0].reshape(NH * D, H).T))
            wks.append(np.ascontiguousarray(w[:, 1].reshape(NH * D, H).T))
            wvs.append(np.ascontiguousarray(w[:, 2].reshape(NH * D, H).T))
            bqs.append(b[:, 0].reshape(-1))
            bks.append(b[:, 1].reshape(-1))
            bvs.append(b[:, 2].reshape(-1))
        layer = {
            "attn_norm_scale": _stack([sd[f"{lyr}{i}.input_layernorm.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{lyr}{i}.input_layernorm.bias"] for i in range(L)]),
            "wq": _stack(wqs), "wk": _stack(wks), "wv": _stack(wvs),
            "bq": _stack(bqs), "bk": _stack(bks), "bv": _stack(bvs),
            "wo": _stack([_t(sd[f"{lyr}{i}.attention.dense.weight"]) for i in range(L)]),
            "bo": _stack([sd[f"{lyr}{i}.attention.dense.bias"] for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"{lyr}{i}.post_attention_layernorm.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{lyr}{i}.post_attention_layernorm.bias"] for i in range(L)]),
        }
        if f"{lyr}0.mlp.dense_h_to_4h.weight" in sd:  # dense MLP (MoE subclass: experts)
            layer.update(
                w_in=_stack([_t(sd[f"{lyr}{i}.mlp.dense_h_to_4h.weight"]) for i in range(L)]),
                b_in=_stack([sd[f"{lyr}{i}.mlp.dense_h_to_4h.bias"] for i in range(L)]),
                w_out=_stack([_t(sd[f"{lyr}{i}.mlp.dense_4h_to_h.weight"]) for i in range(L)]),
                b_out=_stack([sd[f"{lyr}{i}.mlp.dense_4h_to_h.bias"] for i in range(L)]),
            )
        return {
            "embed": {
                "tokens": np.asarray(sd[f"{emb}word_embeddings.weight"]),
                "pos": np.asarray(sd[f"{emb}position_embeddings.weight"]),
            },
            "layers": layer,
            "final_norm_scale": np.asarray(sd["language_model.transformer.final_layernorm.weight"]),
            "final_norm_bias": np.asarray(sd["language_model.transformer.final_layernorm.bias"]),
        }


class MegatronGPTMoEPolicy(MegatronGPTPolicy):
    """megatron_gpt_moe (reference containers/megatron_gpt_moe.py): Megatron
    GPT whose MLPs are DeepSpeed-MoE expert banks
    (``mlp.deepspeed_moe.experts.deepspeed_experts.{e}.*`` + the gate).
    Converts onto ``MoETransformerLM`` (every layer MoE, top-k gate)."""

    model_types = ["megatron-gpt-moe", "megatron_gpt_moe"]

    def build_moe_config(self, c):
        from deepspeed_tpu.models.moe_transformer import MoETransformerConfig

        base = self.build_config(c)
        import dataclasses

        fields = {f.name: getattr(base, f.name) for f in dataclasses.fields(base)}
        fields.update(
            num_experts=getattr(c, "num_experts", 1),
            moe_top_k=getattr(c, "moe_top_k", 1),
            moe_layer_freq=1,
        )
        return MoETransformerConfig(**fields)

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        params = super().convert_weights(sd, cfg)  # attn/norm/embed fields
        L = cfg.num_layers
        E = cfg.num_experts
        lyr = "language_model.transformer.layers."
        layer = params["layers"]
        exp = "mlp.deepspeed_moe.experts.deepspeed_experts."
        moe = {
            "gate": {
                "wg": _stack(
                    [_t(sd[f"{lyr}{i}.mlp.deepspeed_moe.gate.wg.weight"]) for i in range(L)]
                )
            },
            "experts": {
                "w_in": _stack(
                    [
                        np.stack([_t(sd[f"{lyr}{i}.{exp}{e}.dense_h_to_4h.weight"]) for e in range(E)])
                        for i in range(L)
                    ]
                ),
                "b_in": _stack(
                    [
                        np.stack([np.asarray(sd[f"{lyr}{i}.{exp}{e}.dense_h_to_4h.bias"]) for e in range(E)])
                        for i in range(L)
                    ]
                ),
                "w_out": _stack(
                    [
                        np.stack([_t(sd[f"{lyr}{i}.{exp}{e}.dense_4h_to_h.weight"]) for e in range(E)])
                        for i in range(L)
                    ]
                ),
                "b_out": _stack(
                    [
                        np.stack([np.asarray(sd[f"{lyr}{i}.{exp}{e}.dense_4h_to_h.bias"]) for e in range(E)])
                        for i in range(L)
                    ]
                ),
            },
        }
        layer["moe"] = moe
        return params


class CLIPTextPolicy(DSPolicy):
    """clip (reference containers/clip.py): the CLIP *text* tower — pre-LN
    causal encoder with quick_gelu and learned positions. (The vision tower
    and the diffusers unet/vae containers are convolutional and outside the
    decoder family this framework fuses — reference parity for those is via
    plain XLA compilation of the user's model, not injection.)"""

    model_types = ["clip", "clip_text_model", "clip-text"]

    def build_config(self, c) -> TransformerConfig:
        c = getattr(c, "text_config", c)
        return TransformerConfig(
            vocab_size=c.vocab_size,
            hidden_size=c.hidden_size,
            intermediate_size=c.intermediate_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            causal=True,  # CLIP text uses a causal mask
            norm="layernorm",
            norm_eps=getattr(c, "layer_norm_eps", 1e-5),
            position="learned",
            activation="quick_gelu" if getattr(c, "hidden_act", "quick_gelu") == "quick_gelu" else "gelu",
            use_bias=True,
            tie_embeddings=True,
        )

    def convert_weights(self, sd, cfg) -> Dict[str, Any]:
        L = cfg.num_layers
        pre = "text_model." if any(k.startswith("text_model.") for k in sd) else ""
        enc = f"{pre}encoder.layers."

        def lw(i, name):
            return _t(sd[f"{enc}{i}.{name}.weight"])

        def lb(i, name):
            return np.asarray(sd[f"{enc}{i}.{name}.bias"])

        layer = {
            "attn_norm_scale": _stack([sd[f"{enc}{i}.layer_norm1.weight"] for i in range(L)]),
            "attn_norm_bias": _stack([sd[f"{enc}{i}.layer_norm1.bias"] for i in range(L)]),
            "wq": _stack([lw(i, "self_attn.q_proj") for i in range(L)]),
            "wk": _stack([lw(i, "self_attn.k_proj") for i in range(L)]),
            "wv": _stack([lw(i, "self_attn.v_proj") for i in range(L)]),
            "bq": _stack([lb(i, "self_attn.q_proj") for i in range(L)]),
            "bk": _stack([lb(i, "self_attn.k_proj") for i in range(L)]),
            "bv": _stack([lb(i, "self_attn.v_proj") for i in range(L)]),
            "wo": _stack([lw(i, "self_attn.out_proj") for i in range(L)]),
            "bo": _stack([lb(i, "self_attn.out_proj") for i in range(L)]),
            "mlp_norm_scale": _stack([sd[f"{enc}{i}.layer_norm2.weight"] for i in range(L)]),
            "mlp_norm_bias": _stack([sd[f"{enc}{i}.layer_norm2.bias"] for i in range(L)]),
            "w_in": _stack([lw(i, "mlp.fc1") for i in range(L)]),
            "b_in": _stack([lb(i, "mlp.fc1") for i in range(L)]),
            "w_out": _stack([lw(i, "mlp.fc2") for i in range(L)]),
            "b_out": _stack([lb(i, "mlp.fc2") for i in range(L)]),
        }
        return {
            "embed": {
                "tokens": np.asarray(sd[f"{pre}embeddings.token_embedding.weight"]),
                "pos": np.asarray(sd[f"{pre}embeddings.position_embedding.weight"]),
            },
            "layers": layer,
            "final_norm_scale": np.asarray(sd[f"{pre}final_layer_norm.weight"]),
            "final_norm_bias": np.asarray(sd[f"{pre}final_layer_norm.bias"]),
        }


# registry (reference replace_policy.py replace_policies). unet/vae are
# convolutional diffusers containers with no decoder analog — on TPU those
# models run through plain XLA compilation, not injection.
replace_policies: List[type] = [
    GPT2Policy,
    LlamaPolicy,
    OPTPolicy,
    GPTNeoXPolicy,
    BloomPolicy,
    GPTJPolicy,
    BertPolicy,
    DistilBertPolicy,
    GPTNeoPolicy,
    MegatronGPTPolicy,
    MegatronGPTMoEPolicy,
    CLIPTextPolicy,
]


def policy_for(model_type: str) -> DSPolicy:
    for cls in replace_policies:
        if cls.matches(model_type):
            return cls()
    raise ValueError(
        f"no injection policy for architecture {model_type!r}; "
        f"known: {[t for c in replace_policies for t in c.model_types]}"
    )
