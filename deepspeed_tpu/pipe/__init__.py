"""Pipeline-parallel user API (reference: ``deepspeed/pipe/__init__.py``)."""

from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec
