"""Environment / compatibility report (``ds_report`` CLI).

Counterpart of the reference's ``deepspeed/env_report.py``: prints framework
versions, accelerator status, and the op/kernels compatibility matrix so
users can diagnose an install at a glance.
"""

from __future__ import annotations

import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = f"{GREEN}[YES]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
FAIL = f"{RED}[NO]{END}"
OKAY = f"{GREEN}[OKAY]{END}"


def op_report():
    """Pallas/XLA op availability matrix (the reference's JIT/AOT native-op
    compat table, env_report.py op_report)."""
    rows = []
    try:
        from deepspeed_tpu.ops.transformer.flash_attention import flash_attention  # noqa: F401

        rows.append(("flash_attention (pallas)", True))
    except ImportError:
        rows.append(("flash_attention (pallas)", False))
    for name, modpath in [
        ("fused_adam", "deepspeed_tpu.ops.adam.fused_adam"),
        ("fused_lamb", "deepspeed_tpu.ops.lamb.fused_lamb"),
        ("cpu_adagrad", "deepspeed_tpu.ops.adagrad.cpu_adagrad"),
    ]:
        try:
            __import__(modpath)
            rows.append((name, True))
        except ImportError:
            rows.append((name, False))
    try:
        from deepspeed_tpu.ops.aio import AsyncIOBuilder

        rows.append(("async_io (native)", AsyncIOBuilder().is_compatible()))
    except ImportError:
        rows.append(("async_io (native)", False))
    try:
        from deepspeed_tpu.ops.adam.cpu_adam_native import native_adam_available

        rows.append(("cpu_adam (native AVX)", native_adam_available()))
    except ImportError:
        rows.append(("cpu_adam (native AVX)", False))

    max_dots = max(len(n) for n, _ in rows) + 4
    print("-" * 70)
    print("op name" + "." * (max_dots - 7) + " compatible")
    print("-" * 70)
    for name, ok in rows:
        print(name + "." * (max_dots - len(name)) + f" {SUCCESS if ok else FAIL}")
    print("-" * 70)
    return rows


def debug_report():
    import deepspeed_tpu

    try:
        import jax

        jax_version = jax.__version__
        try:
            devices = jax.devices()
            platform = devices[0].platform
            device_count = len(devices)
        except Exception as e:
            platform, device_count = f"unavailable ({e})", 0
    except ImportError:
        jax_version, platform, device_count = "not installed", "-", 0

    try:
        import flax

        flax_version = flax.__version__
    except ImportError:
        flax_version = "not installed"
    try:
        import optax

        optax_version = optax.__version__
    except ImportError:
        optax_version = "not installed"

    report = [
        ("deepspeed_tpu install path", deepspeed_tpu.__path__),
        ("deepspeed_tpu version", deepspeed_tpu.__version__),
        ("jax version", jax_version),
        ("flax version", flax_version),
        ("optax version", optax_version),
        ("platform", platform),
        ("device count", device_count),
        ("python version", sys.version.split()[0]),
    ]
    print("DeepSpeed-TPU general environment info:")
    for name, value in report:
        print(f"{name} ................... {value}")


def main():
    op_report()
    debug_report()


def cli_main():
    main()


if __name__ == "__main__":
    main()
