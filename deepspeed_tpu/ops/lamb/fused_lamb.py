"""FusedLamb.

Counterpart of ``deepspeed/ops/lamb/fused_lamb.py`` +
``csrc/lamb/fused_lamb_cuda_kernel.cu``: LAMB with per-layer trust ratio. One
jitted pass; per-leaf norms are small reductions XLA fuses into the update.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import DSOptimizer


class LambState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_sq: Any


class FusedLamb(DSOptimizer):
    def __init__(
        self,
        params=None,  # noqa: ARG002
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        max_grad_norm: float = 0.0,  # noqa: ARG002 - clipping handled by engine
        max_coeff: float = 10.0,
        min_coeff: float = 0.01,
        amsgrad: bool = False,
    ):
        if amsgrad:
            raise ValueError("FusedLamb does not support amsgrad")
        super().__init__(lr=lr, weight_decay=weight_decay, betas=betas, eps=eps)
        self.bias_correction = bias_correction
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff

    def init_state(self, params: Any) -> LambState:
        z = lambda: jax.tree_util.tree_map(lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
        return LambState(step=jnp.zeros((), jnp.int32), exp_avg=z(), exp_avg_sq=z())

    def state_specs(self, param_specs: Any) -> "LambState":
        from jax.sharding import PartitionSpec

        return LambState(step=PartitionSpec(), exp_avg=param_specs, exp_avg_sq=param_specs)

    def apply(self, grads: Any, state: LambState, params: Any, lr) -> Tuple[Any, LambState]:
        beta1, beta2 = self.defaults["betas"]
        eps = self.defaults["eps"]
        wd = self.defaults["weight_decay"]
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - beta1**stepf if self.bias_correction else jnp.float32(1.0)
        bc2 = 1.0 - beta2**stepf if self.bias_correction else jnp.float32(1.0)

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m = beta1 * m + (1.0 - beta1) * g
            v = beta2 * v + (1.0 - beta2) * (g * g)
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p32
            w_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(update.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                1.0,
            )
            return (p32 - lr * trust * update).astype(p.dtype), m, v

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        out = [leaf(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        return (
            treedef.unflatten([o[0] for o in out]),
            LambState(step, treedef.unflatten([o[1] for o in out]), treedef.unflatten([o[2] for o in out])),
        )
