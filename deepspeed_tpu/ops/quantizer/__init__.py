"""Block quantization ops.

Counterpart of the reference's quantization kernels (``csrc/quantization/``:
``quantize.cu``/``dequantize.cu``/``swizzled_quantize.cu``/``quant_reduce.cu``,
bindings pt_binding.cpp:228-251). On TPU these are jnp expressions fused by
XLA into the surrounding collectives — symmetric and asymmetric block
quantization to int8/int4, used by the ZeRO++ quantized collectives
(``runtime/comm/coalesced_collectives.py``) and QAT (``compression/``).

Layout: a flat tensor is viewed as [num_groups, group_size]; each group
carries its own scale (and min for asymmetric). int4 values occupy the low
nibble of an int8 (TPU has no packed-int4 array type at this layer; the
wire format stays int8 — bandwidth parity with int4 packing is handled by
the collectives packing two nibbles per byte when requested).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _grouped(x: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    flat = x.reshape(-1)
    assert flat.shape[0] % num_groups == 0, (
        f"{flat.shape[0]} elements not divisible into {num_groups} groups"
    )
    return flat.reshape(num_groups, -1)


def quantize(x: jnp.ndarray, num_groups: int, num_bits: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-group quantization (reference ``ds_quantize_*``).

    Returns (q [num_groups, group_size] int8, scales [num_groups] f32).
    """
    g = _grouped(x, num_groups).astype(jnp.float32)
    qmax = float(2 ** (num_bits - 1) - 1)
    absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(g / scale), -qmax - 1, qmax).astype(jnp.int8)
    return q, scale[:, 0]


def dequantize(q: jnp.ndarray, scale: jnp.ndarray, shape=None, dtype=jnp.float32) -> jnp.ndarray:
    out = q.astype(jnp.float32) * scale[:, None]
    if shape is not None:
        out = out.reshape(shape)
    return out.astype(dtype)


def quantize_asymmetric(
    x: jnp.ndarray, num_groups: int, num_bits: int = 8
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Asymmetric per-group quantization (min/scale), as the reference's
    activation quantizer uses. Returns (q uint-coded int8, scale, minv)."""
    g = _grouped(x, num_groups).astype(jnp.float32)
    levels = float(2**num_bits - 1)
    minv = jnp.min(g, axis=1, keepdims=True)
    maxv = jnp.max(g, axis=1, keepdims=True)
    scale = jnp.where(maxv > minv, (maxv - minv) / levels, 1.0)
    q = jnp.clip(jnp.round((g - minv) / scale), 0, levels).astype(
        jnp.uint16 if num_bits > 8 else jnp.uint8
    )
    return q, scale[:, 0], minv[:, 0]


def dequantize_asymmetric(q, scale, minv, shape=None, dtype=jnp.float32):
    out = q.astype(jnp.float32) * scale[:, None] + minv[:, None]
    if shape is not None:
        out = out.reshape(shape)
    return out.astype(dtype)


def fake_quantize(x: jnp.ndarray, num_groups: int, num_bits: int = 8) -> jnp.ndarray:
    """Quantize-dequantize roundtrip with a straight-through gradient —
    the reference's ``fake_quantizer.cu`` for QAT."""

    @jax.custom_vjp
    def _fq(x):
        q, s = quantize(x, num_groups, num_bits)
        return dequantize(q, s, shape=x.shape, dtype=x.dtype)

    def fwd(x):
        return _fq(x), None

    def bwd(_, g):
        return (g,)  # straight-through estimator

    _fq.defvjp(fwd, bwd)
    return _fq(x)


def swizzle_quant(x: jnp.ndarray, num_groups: int, num_bits: int = 8):
    """Parity shim for the reference's ``swizzled_quantize`` — the swizzle
    reorders groups for GPU warp-coalesced access; XLA chooses its own
    layouts, so this is plain quantize."""
    return quantize(x, num_groups, num_bits)


class Quantizer:
    """Object API used by compression/eigenvalue code paths."""

    def __init__(self, q_bits: int = 8, q_groups: int = 1):
        self.q_bits = q_bits
        self.q_groups = q_groups

    def quantize(self, x):
        return quantize(x, self.q_groups, self.q_bits)

    def dequantize(self, q, scale, shape=None, dtype=jnp.float32):
        return dequantize(q, scale, shape, dtype)
