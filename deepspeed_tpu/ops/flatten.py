"""Flatten/unflatten a list of arrays into one contiguous 1-D buffer.

TPU analogue of the reference's ``UtilsBuilder`` op (csrc flatten/unflatten
bound via op_builder/utils.py; used by the reference's ZeRO bucketing and
``deepspeed.runtime.utils``). Under XLA there is no apex to bind — the ops
are plain jnp concatenate/slice, which XLA fuses into the surrounding
program — but the module keeps the same two-function contract so code
written against ``UtilsBuilder().load()`` ports directly.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

__all__ = ["flatten", "unflatten"]


def flatten(tensors: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Concatenate ``tensors`` (any shapes) into one contiguous 1-D array,
    mirroring ``torch._utils._flatten_dense_tensors``."""
    if not tensors:
        return jnp.zeros((0,), dtype=jnp.float32)
    return jnp.concatenate([jnp.ravel(t) for t in tensors])


def unflatten(flat: jnp.ndarray, tensors: Sequence[jnp.ndarray]) -> list:
    """Split 1-D ``flat`` back into views shaped like ``tensors``, mirroring
    ``torch._utils._unflatten_dense_tensors``."""
    outputs = []
    offset = 0
    for t in tensors:
        numel = int(np.prod(t.shape)) if t.ndim else 1
        outputs.append(jnp.reshape(flat[offset : offset + numel], t.shape))
        offset += numel
    return outputs
