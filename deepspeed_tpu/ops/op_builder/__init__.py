"""Op-builder registry.

Counterpart of the reference's ``op_builder/`` tree (``OpBuilder`` ABC,
builder.py:102). On TPU there is nothing to nvcc: "building" an op resolves a
Pallas/XLA-backed implementation (always compatible), or compiles the C++ host
library (CPUAdam / async IO) on first use. ``get_accelerator().get_op_builder``
dispatches here (abstract_accelerator.py:233 pattern).
"""

from __future__ import annotations

import importlib
from typing import Optional

from deepspeed_tpu.utils.logging import logger


class OpBuilder:
    BUILD_VAR = "DS_BUILD_OPS"
    NAME = "base"

    def is_compatible(self, verbose: bool = True) -> bool:  # noqa: ARG002
        return True

    def load(self, verbose: bool = True):
        """Return the op module (imports resolve Pallas/XLA implementations)."""
        raise NotImplementedError

    def builder(self):
        return None

    @property
    def name(self) -> str:
        return self.NAME


class _ModuleOpBuilder(OpBuilder):
    """Builder that resolves to a python module path on load."""

    MODULE: str = ""

    def load(self, verbose: bool = True):
        if verbose:
            logger.debug(f"Loading op {self.NAME} from {self.MODULE}")
        return importlib.import_module(self.MODULE)


class FusedAdamBuilder(_ModuleOpBuilder):
    NAME = "fused_adam"
    MODULE = "deepspeed_tpu.ops.adam.fused_adam"


class CPUAdamBuilder(_ModuleOpBuilder):
    NAME = "cpu_adam"
    MODULE = "deepspeed_tpu.ops.adam.cpu_adam_native"

    def is_compatible(self, verbose: bool = True) -> bool:  # noqa: ARG002
        try:
            self.load(verbose=False)
            return True
        except Exception:
            return False


class CPUAdagradBuilder(_ModuleOpBuilder):
    NAME = "cpu_adagrad"
    MODULE = "deepspeed_tpu.ops.adagrad.cpu_adagrad"


class FusedLambBuilder(_ModuleOpBuilder):
    NAME = "fused_lamb"
    MODULE = "deepspeed_tpu.ops.lamb.fused_lamb"


class TransformerBuilder(_ModuleOpBuilder):
    NAME = "transformer"
    MODULE = "deepspeed_tpu.ops.transformer"


class InferenceBuilder(_ModuleOpBuilder):
    NAME = "transformer_inference"
    MODULE = "deepspeed_tpu.ops.transformer.decode_attention"


class QuantizerBuilder(_ModuleOpBuilder):
    NAME = "quantizer"
    MODULE = "deepspeed_tpu.ops.quantizer"


class SparseAttnBuilder(_ModuleOpBuilder):
    NAME = "sparse_attn"
    MODULE = "deepspeed_tpu.ops.sparse_attention"


class RandomLTDBuilder(_ModuleOpBuilder):
    NAME = "random_ltd"
    MODULE = "deepspeed_tpu.runtime.data_pipeline.data_routing"


class SpatialInferenceBuilder(_ModuleOpBuilder):
    NAME = "spatial_inference"
    MODULE = "deepspeed_tpu.models.unet"


class AsyncIOBuilder(_ModuleOpBuilder):
    NAME = "async_io"
    MODULE = "deepspeed_tpu.ops.aio"

    def is_compatible(self, verbose: bool = True) -> bool:  # noqa: ARG002
        try:
            self.load(verbose=False)
            return True
        except Exception:
            return False


class UtilsBuilder(_ModuleOpBuilder):
    NAME = "utils"
    MODULE = "deepspeed_tpu.ops.flatten"


_BUILDERS = {
    cls.NAME: cls
    for cls in (
        FusedAdamBuilder,
        CPUAdamBuilder,
        CPUAdagradBuilder,
        FusedLambBuilder,
        TransformerBuilder,
        InferenceBuilder,
        QuantizerBuilder,
        SparseAttnBuilder,
        RandomLTDBuilder,
        SpatialInferenceBuilder,
        AsyncIOBuilder,
        UtilsBuilder,
    )
}

ALL_OPS = dict(_BUILDERS)


def get_builder(op_name: str) -> Optional[type]:
    return _BUILDERS.get(op_name)
