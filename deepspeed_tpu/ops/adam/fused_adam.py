"""FusedAdam / Adam / AdamW.

Counterpart of the reference's ``deepspeed/ops/adam/fused_adam.py`` (CUDA
multi-tensor Adam, ``csrc/adam/multi_tensor_adam.cu``). The update runs as one
jitted pass over the whole (sharded) master-param tree; with ZeRO ≥ 1 each
chip updates only its 1/dp shard — identical math to the reference's
owner-rank update (stage_1_and_2.py:1705).

Matches torch.optim.Adam/AdamW semantics: bias correction, decoupled weight
decay when ``adam_w_mode`` (AdamW), coupled L2 otherwise.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import DSOptimizer


class AdamState(NamedTuple):
    step: jax.Array  # scalar int32
    exp_avg: Any  # pytree, fp32
    exp_avg_sq: Any  # pytree, fp32


class FusedAdam(DSOptimizer):
    def __init__(
        self,
        params=None,  # noqa: ARG002 - torch-API parity; functional state is built by the engine
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        set_grad_none: bool = True,  # noqa: ARG002
    ):
        if amsgrad:
            raise ValueError("FusedAdam does not support amsgrad (reference parity)")
        super().__init__(lr=lr, weight_decay=weight_decay, betas=betas, eps=eps)
        self.bias_correction = bias_correction
        self.adam_w_mode = adam_w_mode

    def init_state(self, params: Any) -> AdamState:
        zeros = jax.tree_util.tree_map(lambda p: jnp.zeros(jnp.shape(p), dtype=jnp.float32), params)
        zeros2 = jax.tree_util.tree_map(lambda p: jnp.zeros(jnp.shape(p), dtype=jnp.float32), params)
        return AdamState(step=jnp.zeros((), dtype=jnp.int32), exp_avg=zeros, exp_avg_sq=zeros2)

    def state_specs(self, param_specs: Any) -> "AdamState":
        from jax.sharding import PartitionSpec

        return AdamState(step=PartitionSpec(), exp_avg=param_specs, exp_avg_sq=param_specs)

    def apply(self, grads: Any, state: AdamState, params: Any, lr) -> Tuple[Any, AdamState]:
        beta1, beta2 = self.defaults["betas"]
        eps = self.defaults["eps"]
        wd = self.defaults["weight_decay"]
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - beta1**stepf
            bc2 = 1.0 - beta2**stepf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if wd and not self.adam_w_mode:
                g = g + wd * p32
            m = beta1 * m + (1.0 - beta1) * g
            v = beta2 * v + (1.0 - beta2) * (g * g)
            denom = jnp.sqrt(v / bc2) + eps
            update = (m / bc1) / denom
            if wd and self.adam_w_mode:
                update = update + wd * p32
            return (p32 - lr * update).astype(p.dtype), m, v

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        out = [leaf(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        new_p = treedef.unflatten([o[0] for o in out])
        new_m = treedef.unflatten([o[1] for o in out])
        new_v = treedef.unflatten([o[2] for o in out])
        return new_p, AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)


class Adam(FusedAdam):
    """Plain Adam (coupled L2)."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("adam_w_mode", False)
        super().__init__(*args, **kwargs)


class AdamW(FusedAdam):
    def __init__(self, *args, **kwargs):
        kwargs.setdefault("adam_w_mode", True)
        super().__init__(*args, **kwargs)
