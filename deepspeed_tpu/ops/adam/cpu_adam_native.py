"""Native AVX Adam on host partitions.

Python surface of ``csrc/adam/cpu_adam.cpp`` — the reference's
``DeepSpeedCPUAdam`` (``deepspeed/ops/adam/cpu_adam.py``): applies the fused
Adam/AdamW update to fp32 master partitions living in host DRAM (offloaded
optimizer state). Used by the engine's host-offload step
(``runtime/zero/offload_states.py``) so the TPU never holds optimizer
moments under ``offload_optimizer.device=cpu|nvme``.
"""

from __future__ import annotations

import ctypes
import itertools
from typing import Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.native.build import load_op

_ids = itertools.count()


def _lib() -> Optional[ctypes.CDLL]:
    lib = load_op("cpu_adam")
    if lib is None:
        return None
    lib.create_adam.argtypes = [
        ctypes.c_int,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_int,
    ]
    lib.destroy_adam.argtypes = [ctypes.c_int]
    lib.adam_update.argtypes = [
        ctypes.c_int,
        ctypes.c_int64,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_float,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
    ]
    lib.adam_simd_width.restype = ctypes.c_int
    return lib


def native_adam_available() -> bool:
    return _lib() is not None


def simd_width() -> int:
    lib = _lib()
    return lib.adam_simd_width() if lib is not None else 0


def _fptr(a: np.ndarray):
    assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class NativeCPUAdam:
    """Host-side fused Adam over flat fp32 numpy partitions.

    The reference class API (`DeepSpeedCPUAdam`): construct with hyperparams,
    call :meth:`step` per partition with (params, grads, exp_avg, exp_avg_sq)
    — all updated in place on the host.
    """

    def __init__(
        self,
        lr: float = 1e-3,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        adamw_mode: bool = True,
        fp32_optimizer_states: bool = True,  # noqa: ARG002 - parity
    ):
        if amsgrad:
            raise NotImplementedError("amsgrad is not supported (reference cpu_adam.py parity)")
        self.lib = _lib()
        if self.lib is None:
            raise RuntimeError("native cpu_adam unavailable (toolchain/build failure)")
        self.opt_id = next(_ids)
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        rc = self.lib.create_adam(
            self.opt_id, lr, betas[0], betas[1], eps, weight_decay, int(adamw_mode)
        )
        if rc != 0:
            raise RuntimeError("create_adam failed")

    def __del__(self):
        lib = getattr(self, "lib", None)
        if lib is not None:
            try:
                lib.destroy_adam(self.opt_id)
            except Exception:
                pass

    def step(
        self,
        params: np.ndarray,
        grads: np.ndarray,
        exp_avg: np.ndarray,
        exp_avg_sq: np.ndarray,
        step: Optional[int] = None,
        lr: Optional[float] = None,
        bias_correction: bool = True,
    ) -> None:
        """In-place fused update of one flat partition."""
        if step is None:
            self.step_count += 1
            step = self.step_count
        n = params.size
        assert grads.size == n and exp_avg.size == n and exp_avg_sq.size == n
        rc = self.lib.adam_update(
            self.opt_id,
            step,
            self.lr if lr is None else lr,
            self.betas[0],
            self.betas[1],
            self.eps,
            self.weight_decay,
            int(bias_correction),
            _fptr(params),
            _fptr(grads),
            _fptr(exp_avg),
            _fptr(exp_avg_sq),
            n,
        )
        if rc != 0:
            raise RuntimeError("adam_update failed (unknown optimizer id)")


class NativeCPUAdagrad:
    """Host-side Adagrad (csrc/adagrad/cpu_adagrad.cpp)."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10, weight_decay: float = 0.0):
        self.lib = load_op("cpu_adagrad")
        if self.lib is None:
            raise RuntimeError("native cpu_adagrad unavailable")
        self.lib.create_adagrad.argtypes = [
            ctypes.c_int,
            ctypes.c_float,
            ctypes.c_float,
            ctypes.c_float,
        ]
        self.lib.adagrad_update.argtypes = [
            ctypes.c_int,
            ctypes.c_float,
            ctypes.c_float,
            ctypes.c_float,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
        ]
        self.lib.destroy_adagrad.argtypes = [ctypes.c_int]
        self.opt_id = next(_ids)
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.lib.create_adagrad(self.opt_id, lr, eps, weight_decay)

    def __del__(self):
        lib = getattr(self, "lib", None)
        if lib is not None:
            try:
                lib.destroy_adagrad(self.opt_id)
            except Exception:
                pass

    def step(self, params: np.ndarray, grads: np.ndarray, accum: np.ndarray, lr: Optional[float] = None) -> None:
        rc = self.lib.adagrad_update(
            self.opt_id,
            self.lr if lr is None else lr,
            self.eps,
            self.weight_decay,
            _fptr(params),
            _fptr(grads),
            _fptr(accum),
            params.size,
        )
        if rc != 0:
            raise RuntimeError("adagrad_update failed")
