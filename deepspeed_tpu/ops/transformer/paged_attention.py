"""Ragged paged decode attention — serving-layer front end.

The serving layer (``inference/kv_pool.py`` + ``inference/scheduler.py``)
stores every sequence's KV cache as fixed-size pages in one shared pool
``[num_pages, NKV, page_size, D]`` per layer, addressed through per-sequence
page tables. This module is the single attention entry point for that
layout:

* ``paged_decode_attention`` — one generated token per sequence attends over
  its live pages. Dispatches to the Pallas kernel
  (``decode_attention._pallas_paged_decode``: the kv grid walks the page
  table via scalar prefetch, online softmax, GQA groups ride the sublane
  dim) on TPU, and to a gather-based XLA implementation everywhere else —
  interpret-mode Pallas inside a per-step serving program would dominate
  CPU-mesh test time.
* ``paged_prefill_attention`` — a token slab ``[B, T]`` attends causally
  over each row's own pages (prefix + the slab itself, already scattered
  in). Pure XLA: the slab paths are matmul-bound. Two callers: chunked
  prompt prefill (B = 1, T = chunk) and the speculative verify program
  (B = slot bucket, T = K+1 draft-and-bonus slots), which also passes
  per-row ``kv_lens`` so pad draft slots past a row's live prefix are
  masked out of every score.
* ``ragged_paged_attention`` — the unified entry the one-program ragged
  serving step dispatches (``decode.py:build_ragged_step``): mixed
  prefill-chunk / decode / verify rows in one ``[R, W]`` window, driven
  entirely by per-row ``(kv_len, q_len)`` metadata arrays so the mix
  never retraces. Pallas kernel on TPU
  (``decode_attention.ragged_paged_attention``: kv grid walks the page
  table via scalar prefetch, causal in-window mask, pages past a row's
  live length skipped), XLA gather fallback elsewhere.

GQA is handled by grouping — queries reshape to ``[B, NKV, G, D]`` and each
kv head's rows are read once — so no path here (kernel or fallback) ever
materializes an NH-wide copy of the cache the way a ``jnp.repeat`` expansion
would.

Page-table conventions (shared with ``inference/kv_pool.py``): ids < 0 or
>= num_pages are sentinels for unallocated slots; they are clamped to page 0
(the pool's reserved trash page) and their scores masked by the length, so
padded tables are always safe to read.

Tensor-parallel contract (``inference/tp.py``): every entry point here is
**shard-oblivious**. Under multi-chip serving the ragged step runs these
inside ``shard_map`` with the page pools sharded on the kv-head axis — the
kernel then simply sees the LOCAL ``NKV/tp`` kv heads of every page and the
matching ``NH/tp`` query heads (the GQA group size ``NH/NKV`` is invariant
under the split, and head blocks are contiguous, so q-head block i attends
exactly its kv-head block i). Page tables, lengths, and q_lens arrive
replicated. Nothing in this module reads a mesh axis: the same code is the
single-chip and the per-shard implementation.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.transformer.decode_attention import (
    NEG_INF,
    _on_tpu,
    paged_decode_attention as _pallas_paged_decode,
    ragged_paged_attention as _pallas_ragged_paged,
)


def _scale_or_default(scale: Optional[float], head_dim: int) -> float:
    return float(scale) if scale is not None else 1.0 / float(np.sqrt(head_dim))


def _gather_pages(pages: jnp.ndarray, page_table: jnp.ndarray) -> jnp.ndarray:
    """[NP, NKV, P, D] pool + [B, MAXP] table -> [B, MAXP*P, NKV, D] linear
    view (kv position s lives in table slot s // P at offset s % P)."""
    NP, NKV, P, D = pages.shape
    B, maxp = page_table.shape
    pt = jnp.clip(jnp.asarray(page_table, jnp.int32), 0, NP - 1)
    # [B, MAXP, NKV, P, D] -> [B, MAXP, P, NKV, D] -> [B, S, NKV, D]
    return pages[pt].transpose(0, 1, 3, 2, 4).reshape(B, maxp * P, NKV, D)


def paged_decode_attention_xla(
    q: jnp.ndarray,  # [B, NH, D]
    k_pages: jnp.ndarray,  # [NP, NKV, P, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, MAXP] int32
    kv_len,  # [B] int32 live lengths (or scalar)
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Gather-based reference/fallback: linearize each row's pages and run
    grouped-GQA masked attention. Rows with length 0 return exact zeros
    (matching the Pallas kernel's empty-accumulator output)."""
    B, NH, D = q.shape
    NP, NKV, P, _ = k_pages.shape
    assert v_pages.shape == k_pages.shape
    if NH % NKV:
        raise ValueError(f"query heads {NH} not a multiple of kv heads {NKV}")
    G = NH // NKV
    S = page_table.shape[1] * P
    scale_f = _scale_or_default(scale, D)
    k = _gather_pages(k_pages, page_table)  # [B, S, NKV, D]
    v = _gather_pages(v_pages, page_table)
    lens = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (B,))
    qg = q.reshape(B, NKV, G, D)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k).astype(jnp.float32) * scale_f
    kv_pos = jnp.arange(S, dtype=jnp.int32)
    live = kv_pos[None, None, None, :] < lens[:, None, None, None]
    scores = jnp.where(live, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v)
    out = jnp.where((lens > 0)[:, None, None, None], out, 0)
    return out.reshape(B, NH, D)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, NH, D]
    k_pages: jnp.ndarray,  # [NP, NKV, P, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, MAXP] int32
    kv_len,  # [B] int32 live lengths
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Single-token paged attention. ``impl``: ``auto`` picks the Pallas
    kernel on TPU and the XLA gather fallback elsewhere; ``pallas`` / ``xla``
    force one (``pallas`` off-TPU runs in interpret mode — tests only)."""
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "xla"
    if impl == "pallas":
        return _pallas_paged_decode(q, k_pages, v_pages, page_table, kv_len, scale=scale)
    if impl == "xla":
        return paged_decode_attention_xla(q, k_pages, v_pages, page_table, kv_len, scale=scale)
    raise ValueError(f"unknown paged attention impl {impl!r}; expected auto|pallas|xla")


def ragged_paged_attention(
    q: jnp.ndarray,  # [R, W, NH, D] — per-row padded token windows
    k_pages: jnp.ndarray,  # [NP, NKV, P, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [R, MAXP] int32
    kv_lens: jnp.ndarray,  # [R] live kv length INCLUDING this step's tokens
    q_lens: jnp.ndarray,  # [R] real tokens in the window (0 = dead row)
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Unified mixed-row attention for the one-program ragged serving step
    (arXiv 2604.15464): every row attends causally over its own pages with
    per-row ``(kv_len, q_len)`` metadata riding in as arrays — a decode row
    (q_len 1), a verify row (q_len K+1), and a prefill chunk (q_len C) all
    take the same code path, so shifting the mix never changes the program.
    ``impl``: ``auto`` picks the Pallas ragged kernel on TPU and the XLA
    gather fallback elsewhere; ``pallas`` / ``xla`` force one (``pallas``
    off-TPU runs in interpret mode — tests only). Rows with
    ``kv_lens == 0`` return exact zeros; window slots past ``q_lens``
    return garbage the caller ignores."""
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "xla"
    if impl == "pallas":
        return _pallas_ragged_paged(
            q, k_pages, v_pages, page_table, kv_lens, q_lens, scale=scale
        )
    if impl != "xla":
        raise ValueError(f"unknown ragged attention impl {impl!r}; expected auto|pallas|xla")
    R, W = q.shape[:2]
    lens = jnp.asarray(kv_lens, jnp.int32)
    qlens = jnp.asarray(q_lens, jnp.int32)
    # absolute query positions: the row's write base (kv_len - q_len) plus
    # the in-window offset — the causal mask then bounds every real slot,
    # and the kv_lens cap silences pad slots' reads above the live prefix
    q_positions = (lens - qlens)[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    return paged_prefill_attention(
        q, k_pages, v_pages, page_table, q_positions, scale=scale, kv_lens=lens
    )


def paged_prefill_attention(
    q: jnp.ndarray,  # [B, T, NH, D] — a prompt chunk's queries
    k_pages: jnp.ndarray,  # [NP, NKV, P, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, MAXP] int32
    q_positions: jnp.ndarray,  # [B, T] absolute positions of the chunk tokens
    scale: Optional[float] = None,
    kv_lens: Optional[jnp.ndarray] = None,  # [B] live kv bound (incl. the slab)
) -> jnp.ndarray:
    """Causal slab attention over each sequence's own pages: query at
    absolute position p sees kv positions <= p (the slab's k/v have already
    been scattered into the pages, so the slab attends to itself too).
    Positions past a slab's real end (pad tail) produce garbage rows the
    caller ignores — their writes land on the trash page and their reads are
    causally bounded, so they never contaminate live positions. ``kv_lens``
    additionally caps every row's visible kv range (the verify program's
    pad slots sit ABOVE live positions, where causality alone would let
    them read unwritten pages); rows with ``kv_lens == 0`` (dead bucket
    padding) return exact zeros."""
    B, T, NH, D = q.shape
    NP, NKV, P, _ = k_pages.shape
    if NH % NKV:
        raise ValueError(f"query heads {NH} not a multiple of kv heads {NKV}")
    G = NH // NKV
    S = page_table.shape[1] * P
    scale_f = _scale_or_default(scale, D)
    k = _gather_pages(k_pages, page_table)  # [B, S, NKV, D]
    v = _gather_pages(v_pages, page_table)
    qg = q.reshape(B, T, NKV, G, D)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) * scale_f
    kv_pos = jnp.arange(S, dtype=jnp.int32)
    mask = q_positions[:, None, None, :, None] >= kv_pos[None, None, None, None, :]
    if kv_lens is not None:
        lens = jnp.asarray(kv_lens, jnp.int32)
        mask = mask & (kv_pos[None, None, None, None, :] < lens[:, None, None, None, None])
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    out = out.reshape(B, T, NH, D)
    if kv_lens is not None:
        out = jnp.where((lens > 0)[:, None, None, None], out, 0)
    return out
