"""Pallas ragged KV-cache decode attention (TPU).

Counterpart of the reference's fused ``softmax_context`` decode kernel
(``csrc/transformer/inference/csrc/softmax.cu`` +
``pt_binding.cpp:1935-1974``): one generated token attends over the live
prefix of a preallocated KV cache.

Shape strategy: the single query token's HEADS ride the sublane dim — the
per-block score matmul is [NH, D] x [D, blk] on the MXU — and the kv grid
dimension walks cache blocks with online softmax, skipping blocks past the
row's live length entirely (``pl.when``): HBM reads scale with kv_len, not
cache capacity. Per-batch lengths arrive via scalar prefetch, making the
kernel ragged — each batch row stops at its own length (the paged/ragged
attention the reference approximates with masking).

The serving layer reaches the page-table variant (``paged_decode_attention``
below) through ``ops/transformer/paged_attention.py``, which fronts it with
an XLA gather fallback and the chunk-prefill attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s, *, scale, blk, nk):
    b = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(ki * blk < len_ref[b])
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [NH, D]
        k = k_ref[0].astype(jnp.float32)  # [blk, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [NH, blk]
        pos = ki * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < len_ref[b], s, NEG_INF)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + jax.lax.dot(p, v, preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_s[...] / safe_l).astype(o_ref.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, NH, D] — the current token's queries
    k_cache: jnp.ndarray,  # [B, S, NKV, D] — NO GQA pre-expansion needed
    v_cache: jnp.ndarray,
    kv_len,  # [B] int32 live lengths (ragged) or a scalar
    scale: Optional[float] = None,
    block_k: int = 256,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused single-token attention over each row's live cache prefix.

    Heads grouped per kv head: each grid row (batch, kv-head) computes
    [NH/NKV, D] x [D, blk] — GQA's shared kv rows are read once, not
    repeated NH/NKV times like the dense fallback's jnp.repeat."""
    B, NH, D = q.shape
    S, NKV = k_cache.shape[1], k_cache.shape[2]
    assert k_cache.shape == v_cache.shape == (B, S, NKV, D)
    if NH % NKV:
        raise ValueError(f"query heads {NH} not a multiple of kv heads {NKV}")
    scale_f = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = not _on_tpu()
    blk = min(block_k, S)
    if S % blk:
        raise ValueError(f"cache capacity {S} not divisible by block_k {blk}")
    nk = S // blk
    lens = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (B,))
    return _grouped_decode(q, k_cache, v_cache, lens, scale_f, blk, nk, interpret)


def _paged_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s, *, scale, page, maxp):
    b = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(ki * page < len_ref[b])
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [Hg, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [page, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        pos = ki * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < len_ref[b], s, NEG_INF)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + jax.lax.dot(p, v, preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == maxp - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0, 1.0, l)
        o_ref[0, 0] = (acc_s[...] / safe_l).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, NH, D]
    k_pages: jnp.ndarray,  # [NP, NKV, P, D] — the shared page pool
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, MAXP] int32 page ids per sequence
    kv_len,  # [B] int32 live lengths
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Paged (block-table) decode attention — the vLLM-style serving layout
    the reference approximates with contiguous per-sequence workspaces: each
    sequence's cache is a list of pages in a shared pool, so prefixes can be
    shared and memory allocates page-granular. The kernel's kv grid walks
    the page table via scalar prefetch (k/v BlockSpecs jump straight to the
    page). Compute for table slots past the live length is skipped, but the
    block FETCH is not (pl.when gates the body, not the BlockSpec), so the
    index map clamps ids into [0, NP): tables padded with -1 or sentinel
    ids >= NP read a valid page whose scores are then masked out."""
    B, NH, D = q.shape
    NP, NKV, P, Dk = k_pages.shape
    assert Dk == D and v_pages.shape == k_pages.shape
    if NH % NKV:
        raise ValueError(f"query heads {NH} not a multiple of kv heads {NKV}")
    maxp = page_table.shape[1]
    scale_f = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = not _on_tpu()
    Hg = NH // NKV
    qg = q.reshape(B, NKV, Hg, D)
    lens = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (B,))
    kernel = functools.partial(_paged_kernel, scale=scale_f, page=P, maxp=maxp)
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, NKV, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, Hg, D), lambda b, g, ki, pt, ln: (b, g, 0, 0)),
            pl.BlockSpec((1, 1, P, D), lambda b, g, ki, pt, ln: (jnp.clip(pt[b, ki], 0, NP - 1), g, 0, 0)),
            pl.BlockSpec((1, 1, P, D), lambda b, g, ki, pt, ln: (jnp.clip(pt[b, ki], 0, NP - 1), g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, Hg, D), lambda b, g, ki, pt, ln: (b, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hg, 128), jnp.float32),
            pltpu.VMEM((Hg, 128), jnp.float32),
            pltpu.VMEM((Hg, D), jnp.float32),
        ],
    )
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, NKV, Hg, D), q.dtype),
        interpret=interpret,
        **params,
    )(jnp.asarray(page_table, jnp.int32), lens, qg, k_pages, v_pages)
    return o.reshape(B, NH, D)


def _ragged_kernel(pt_ref, len_ref, qlen_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s,
                   acc_s, *, scale, page, maxp, Hg):
    b = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(ki * page < len_ref[b])
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [W*Hg, D] — W-major sublanes
        k = k_ref[0, 0].astype(jnp.float32)  # [page, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [W*Hg, page]
        kv_pos = ki * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # sublane i holds query slot w = i // Hg at absolute position
        # start + w, where start = kv_len - q_len (the row's write base)
        q_pos = (len_ref[b] - qlen_ref[b]) + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0
        ) // Hg
        live = (kv_pos <= q_pos) & (kv_pos < len_ref[b])
        s = jnp.where(live, s, NEG_INF)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + jax.lax.dot(p, v, preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ki == maxp - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0, 1.0, l)
        o_ref[0, 0] = (acc_s[...] / safe_l).astype(o_ref.dtype)


def ragged_paged_attention(
    q: jnp.ndarray,  # [R, W, NH, D] — each row's padded token window
    k_pages: jnp.ndarray,  # [NP, NKV, P, D] — the shared page pool
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [R, MAXP] int32 page ids per row
    kv_lens,  # [R] int32 live kv length INCLUDING this step's tokens
    q_lens,  # [R] int32 real tokens in the row's window (0 = dead row)
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """One ragged kernel for mixed prefill-chunk / decode / verify rows.

    The per-row ``(kv_len, q_len)`` metadata rides in as scalar-prefetch
    arrays (the Ragged Paged Attention design, arXiv 2604.15464): row r's
    window holds ``q_lens[r]`` real tokens written at absolute positions
    ``kv_lens[r] - q_lens[r] ..`` — a decode row is q_len 1, a verify row
    q_len K+1, a prefill chunk q_len C — and the kv grid walks the row's
    page table, skipping pages past ``kv_lens[r]`` entirely, so changing
    the prefill/decode/verify mix only changes ARRAY CONTENTS, never the
    program. Queries ride the sublane dim W-major over the GQA group
    (``[W*Hg, D] x [D, page]`` per block) with a causal in-window mask on
    top of the length mask. Window slots past ``q_lens[r]`` produce
    garbage rows the caller ignores (finite: masked softmax over the live
    prefix); rows with ``kv_lens[r] == 0`` return exact zeros."""
    R, W, NH, D = q.shape
    NP, NKV, P, Dk = k_pages.shape
    assert Dk == D and v_pages.shape == k_pages.shape
    if NH % NKV:
        raise ValueError(f"query heads {NH} not a multiple of kv heads {NKV}")
    maxp = page_table.shape[1]
    scale_f = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = not _on_tpu()
    Hg = NH // NKV
    # W-major sublane layout: query slot w of group head h sits at w*Hg + h
    qg = q.reshape(R, W, NKV, Hg, D).transpose(0, 2, 1, 3, 4).reshape(R, NKV, W * Hg, D)
    lens = jnp.broadcast_to(jnp.asarray(kv_lens, jnp.int32), (R,))
    qlens = jnp.broadcast_to(jnp.asarray(q_lens, jnp.int32), (R,))
    kernel = functools.partial(_ragged_kernel, scale=scale_f, page=P, maxp=maxp, Hg=Hg)
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(R, NKV, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, W * Hg, D), lambda b, g, ki, pt, ln, ql: (b, g, 0, 0)),
            pl.BlockSpec((1, 1, P, D), lambda b, g, ki, pt, ln, ql: (jnp.clip(pt[b, ki], 0, NP - 1), g, 0, 0)),
            pl.BlockSpec((1, 1, P, D), lambda b, g, ki, pt, ln, ql: (jnp.clip(pt[b, ki], 0, NP - 1), g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, W * Hg, D), lambda b, g, ki, pt, ln, ql: (b, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((W * Hg, 128), jnp.float32),
            pltpu.VMEM((W * Hg, 128), jnp.float32),
            pltpu.VMEM((W * Hg, D), jnp.float32),
        ],
    )
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, NKV, W * Hg, D), q.dtype),
        interpret=interpret,
        **params,
    )(jnp.asarray(page_table, jnp.int32), lens, qlens, qg, k_pages, v_pages)
    return o.reshape(R, NKV, W, Hg, D).transpose(0, 2, 1, 3, 4).reshape(R, W, NH, D)


def _grouped_decode(q, k_cache, v_cache, lens, scale_f, blk, nk, interpret):
    """Group heads by shared kv rows. With the cache stored per kv head and
    queries pre-grouped [B, G, Hg, D] (Hg = heads per kv head), each grid
    row (b, g) computes [Hg, D] x [D, blk] — for MHA Hg=1 folds into BN
    rows; for GQA the group's heads batch into the sublane dim."""
    B, NH, D = q.shape
    S = k_cache.shape[1]
    NKV = k_cache.shape[2]
    Hg = NH // NKV
    # q: [B, NKV, Hg, D] rows; kv: [B, NKV, S, D]
    qg = q.reshape(B, NKV, Hg, D).reshape(B * NKV, Hg, D)
    kg = k_cache.transpose(0, 2, 1, 3).reshape(B * NKV, S, D)
    vg = v_cache.transpose(0, 2, 1, 3).reshape(B * NKV, S, D)
    lens_g = jnp.repeat(lens, NKV)
    kernel = functools.partial(_decode_kernel, scale=scale_f, blk=blk, nk=nk)
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * NKV, nk),
        in_specs=[
            pl.BlockSpec((1, Hg, D), lambda b, ki, lens_ref: (b, 0, 0)),
            pl.BlockSpec((1, blk, D), lambda b, ki, lens_ref: (b, ki, 0)),
            pl.BlockSpec((1, blk, D), lambda b, ki, lens_ref: (b, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hg, D), lambda b, ki, lens_ref: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hg, 128), jnp.float32),
            pltpu.VMEM((Hg, 128), jnp.float32),
            pltpu.VMEM((Hg, D), jnp.float32),
        ],
    )
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * NKV, Hg, D), q.dtype),
        interpret=interpret,
        **params,
    )(lens_g, qg, kg, vg)
    return o.reshape(B, NKV, Hg, D).reshape(B, NH, D)
