"""Pallas flash attention (TPU).

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/softmax_kernels.cu`` + strided-batch-gemm training path
and ``csrc/transformer/inference/csrc/softmax.cu`` softmax_context): one
fused kernel that never materializes the [T, T] score matrix in HBM.

Layout: q/k/v as [BN, T, D] (batch*heads flattened into the leading grid
dim). Online-softmax forward with running (m, l) in VMEM scratch over the kv
grid dimension; the log-sum-exp is saved as a residual and the backward pass
recomputes probabilities blockwise (standard FlashAttention-2 scheme: one
kernel for dq accumulating over kv blocks, one for dk/dv accumulating over q
blocks).

Causal blocks above the diagonal are skipped via ``pl.when`` — with the kv
grid dimension marked "arbitrary" the skipped iterations cost only control
flow, halving work for causal attention.

The lse/delta residuals are stored lanes-broadcast as [BN, T, 128] f32 (the
layout jax's own TPU flash kernels use for l/m residuals): Mosaic requires
the last dim to tile to 128, so the broadcast buys tileability at T*512B of
HBM per (b, n) row per residual — real but small next to activations, and
only alive between fwd and bwd of one layer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _maybe_when(cond, fn):
    """Run ``fn`` under pl.when for traced conds, directly for static True."""
    if cond is True:
        fn()
    else:
        pl.when(cond)(fn)


def _causal_mask(s, qi, ki, blk_q, blk_k):
    rows = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = ki * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(rows >= cols, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *, scale, blk_q, blk_k, nk, causal):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    def _compute():
        # operands stay in their native dtype (bf16 in training): the MXU
        # multiplies bf16 at full rate and accumulates fp32 via
        # preferred_element_type; an explicit fp32 cast here would force
        # 1/8-rate fp32 MXU passes (measured 20 vs 197 TFLOP/s on v5e).
        # Softmax math runs fp32 on the VPU either way.
        q = q_ref[0]  # [blk_q, D]
        k = k_ref[0]  # [blk_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            s = _causal_mask(s, qi, ki, blk_q, blk_k)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    _maybe_when((ki * blk_k <= qi * blk_q + blk_q - 1) if causal else True, _compute)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_s[...] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = (m_s[...] + jnp.log(safe_l)).astype(lse_ref.dtype)  # lanes identical


def _block_specs(order):
    """q/k block index maps given which of (q, k) is the outer grid dim."""

    def q_map(b, x, y):
        qi = x if order == "q_outer" else y
        return (b, qi, 0)

    def k_map(b, x, y):
        ki = y if order == "q_outer" else x
        return (b, ki, 0)

    return q_map, k_map


def _flash_fwd(q, k, v, scale, causal, blk_q, blk_k, interpret):
    BN, T, D = q.shape
    nq, nk = T // blk_q, T // blk_k
    kernel = functools.partial(
        _fwd_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k, nk=nk, causal=causal
    )
    q_map, k_map = _block_specs("q_outer")
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    o, lse = pl.pallas_call(
        kernel,
        grid=(BN, nq, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, D), k_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, D), k_map, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, D), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_q, 128), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BN, T, D), q.dtype),
            jax.ShapeDtypeStruct((BN, T, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, 128), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
            pltpu.VMEM((blk_q, D), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(q, k, v)
    return o, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_s, *, scale, blk_q, blk_k, nk, causal):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    def _compute():
        # native-dtype operands + fp32 accumulation (see _fwd_kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, blk_q, blk_k)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_s[...] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    _maybe_when((ki * blk_k <= qi * blk_q + blk_q - 1) if causal else True, _compute)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_s, dv_s, *, scale, blk_q, blk_k, nq, causal):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    def _compute():
        # native-dtype operands + fp32 accumulation (see _fwd_kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, blk_q, blk_k)
        p = jnp.exp(s - lse)  # [blk_q, blk_k]
        p_lo = p.astype(do.dtype)
        dv_s[...] += jax.lax.dot_general(p_lo, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_s[...] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _maybe_when((qi * blk_q + blk_q - 1 >= ki * blk_k) if causal else True, _compute)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _flash_bwd(res, g, scale, causal, blk_q, blk_k, interpret):
    q, k, v, o, lse = res
    BN, T, D = q.shape
    nq, nk = T // blk_q, T // blk_k
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [BN, T]
    # lanes-broadcast residual layout: [BN, T, 128] satisfies the (8, 128)
    # Mosaic tile; ~T*512B of HBM per (b, n) row, negligible vs q/k/v
    lse = jnp.broadcast_to(lse[:, :, None], (BN, T, 128))
    delta = jnp.broadcast_to(delta[:, :, None], (BN, T, 128))
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )

    q_map, k_map = _block_specs("q_outer")
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k, nk=nk, causal=causal),
        grid=(BN, nq, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, D), k_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, D), k_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_q, D), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_q, 128), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_q, 128), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), q_map, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BN, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        interpret=interpret,
        **params,
    )(q, k, v, do, lse, delta)

    q_map2, k_map2 = _block_specs("k_outer")
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k, nq=nq, causal=causal),
        grid=(BN, nk, nq),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), q_map2, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, D), k_map2, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, D), k_map2, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_q, D), q_map2, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_q, 128), lambda b, ki, qi: (b, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_q, 128), lambda b, ki, qi: (b, qi, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, D), k_map2, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, D), k_map2, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BN, T, D), k.dtype),
            jax.ShapeDtypeStruct((BN, T, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, scale, causal, blk_q, blk_k, interpret):
    o, _ = _flash_fwd(q, k, v, scale, causal, blk_q, blk_k, interpret)
    return o


def _flash_core_fwd(q, k, v, scale, causal, blk_q, blk_k, interpret):
    o, lse = _flash_fwd(q, k, v, scale, causal, blk_q, blk_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(scale, causal, blk_q, blk_k, interpret, res, g):
    return _flash_bwd(res, g, scale, causal, blk_q, blk_k, interpret)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
):
    """Fused attention over [B, T, N, D] (heads-last layout like the model).

    GQA inputs (fewer kv heads) must be pre-expanded by the caller. The
    sequence is padded up to the block size; padded kv columns sit above the
    causal diagonal of every real row, and padded q rows are sliced off on
    return.
    """
    B, T, N, D = q.shape
    assert k.shape == v.shape == (B, T, N, D), "flash_attention requires equal q/kv heads"
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = not _on_tpu()

    import math

    blk_q = min(block_q, T)
    blk_k = min(block_k, T)
    # both block sizes must divide the padded length or grid truncation would
    # silently drop trailing blocks
    pad = (-T) % math.lcm(blk_q, blk_k)
    if pad and not causal:
        raise ValueError("non-causal flash attention requires T divisible by the block sizes")
    padded_T = T + pad
    assert padded_T % blk_q == 0 and padded_T % blk_k == 0
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))

    def to_bn(x):
        return x.transpose(0, 2, 1, 3).reshape(B * N, padded_T, D)

    o = _flash_core(to_bn(q), to_bn(k), to_bn(v), float(scale), causal, blk_q, blk_k, interpret)
    o = o.reshape(B, N, padded_T, D).transpose(0, 2, 1, 3)
    if pad:
        o = o[:, :T]
    return o
