"""Standalone fused transformer layer (reference:
``deepspeed/ops/transformer/transformer.py:296`` ``DeepSpeedTransformerLayer``
over the ~7.8k-LoC ``csrc/transformer`` CUDA stack).

One encoder/decoder layer as a functional module. The "fusion" the
reference hand-writes (strided-batch GEMMs + fused softmax/dropout/norm
kernels) is XLA's job here, with the Pallas flash kernel carrying the
attention when applicable — the layer shares ``TransformerLM._layer``, so
pre/post-LN, bias, dropout and GQA semantics match the model family
exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Reference config surface (transformer.py:34); fields the TPU layer
    does not need (local_rank, stream handles, gemm_algos) are accepted and
    ignored for drop-in compatibility."""

    batch_size: int = 1  # noqa - parity field; shapes are dynamic under jit
    hidden_size: int = 768
    intermediate_size: Optional[int] = None
    heads: int = 12
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = 1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = 0
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False  # parity; remat handles memory
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size


class DeepSpeedTransformerLayer:
    """One bidirectional (BERT-style) transformer layer with the reference's
    call shape: ``apply(params, hidden_states, attention_mask=None)``."""

    def __init__(self, config: DeepSpeedTransformerConfig):
        from deepspeed_tpu.models.config import TransformerConfig
        from deepspeed_tpu.models.transformer import TransformerLM

        self.config = config
        self._mcfg = TransformerConfig(
            vocab_size=1,  # unused: this is a single layer, no embedding
            hidden_size=config.hidden_size,
            intermediate_size=config.intermediate_size,
            num_layers=1,
            num_heads=config.heads,
            causal=False,
            prenorm=config.pre_layer_norm,
            norm="layernorm",
            norm_eps=config.layer_norm_eps,
            position="none",
            activation="gelu",
            attn_dropout=config.attn_dropout_ratio,
            hidden_dropout=config.hidden_dropout_ratio,
            use_bias=True,
            dtype="float16" if config.fp16 else "float32",
            flash_attention=False,
        )
        self._lm = TransformerLM(self._mcfg)

    def init(self, rng) -> Dict[str, Any]:
        """Per-layer param tree (the model family's layer leaves, unstacked)."""
        full = self._lm.init(rng, None)
        return jax.tree_util.tree_map(lambda a: a[0], full["layers"])

    def apply(self, params, hidden_states, attention_mask=None, *, rng=None, train: bool = True):
        x = jnp.asarray(hidden_states)
        B, T, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
        if attention_mask is not None:
            raise NotImplementedError(
                "DeepSpeedTransformerLayer on TPU does not take an attention "
                "mask (the shared layer assumes full visibility); pack inputs "
                "padding-free, or use ops.sparse_attention for masked encoders"
            )
        out, _aux = self._lm._layer(x, params, positions, rng, train)
        if self.config.return_tuple:
            return (out,)
        return out

    __call__ = apply
