from deepspeed_tpu.ops import op_builder
from deepspeed_tpu.ops.adam.fused_adam import Adam, AdamW, FusedAdam
from deepspeed_tpu.ops.adagrad.cpu_adagrad import DeepSpeedCPUAdagrad
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.ops.sgd import SGD

# reference exposes DeepSpeedCPUAdam; the host-offload variant shares FusedAdam
# math and is selected by the ZeRO offload config. Alias for API parity.
DeepSpeedCPUAdam = FusedAdam
