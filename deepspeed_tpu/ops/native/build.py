"""Native op JIT build system.

Counterpart of the reference's ``op_builder/builder.py`` ``OpBuilder`` JIT
path (ninja ``load()``): each native op is one C++ translation unit under
``csrc/``, compiled lazily on first use with the host toolchain into a
shared library cached by source hash, and loaded via ctypes. The AOT path
(reference ``DS_BUILD_*`` env flags) is ``DS_BUILD_NATIVE=1`` at setup time
(see ``setup.py``), which just calls :func:`build_all` eagerly.

ctypes instead of pybind11 (not in the image): every exported symbol is
``extern "C"`` with scalar/pointer args, and the python wrappers pass numpy
buffers by pointer.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path
from typing import Optional

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = Path(__file__).resolve().parents[3]
_CSRC = _REPO_ROOT / "csrc"
_CACHE_DIR = Path(
    os.environ.get(
        "DS_NATIVE_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu")
    )
)

_OPS = {
    "aio": ["aio/deepspeed_aio.cpp"],
    "cpu_adam": ["adam/cpu_adam.cpp"],
    "cpu_adagrad": ["adagrad/cpu_adagrad.cpp"],
}

_BASE_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-pthread"]
_loaded: dict = {}


def _march_flags() -> list:
    """-march=native unless the toolchain rejects it (non-x86 hosts)."""
    probe = subprocess.run(
        ["g++", "-march=native", "-E", "-x", "c++", "/dev/null"],
        capture_output=True,
    )
    return ["-march=native"] if probe.returncode == 0 else []


def _source_hash(sources) -> str:
    h = hashlib.sha256()
    for rel in sources:
        h.update((_CSRC / rel).read_bytes())
    return h.hexdigest()[:16]


def build_op(name: str, verbose: bool = False) -> Optional[Path]:
    """Compile one op's shared library (cached); returns the .so path or
    None when the toolchain is unavailable."""
    sources = _OPS[name]
    try:
        tag = _source_hash(sources)
    except FileNotFoundError:
        logger.warning(f"native op {name}: sources missing under {_CSRC}")
        return None
    out = _CACHE_DIR / f"lib_{name}_{tag}.so"
    if out.exists():
        return out
    _CACHE_DIR.mkdir(parents=True, exist_ok=True)
    cmd = (
        ["g++"]
        + _BASE_FLAGS
        + _march_flags()
        + [str(_CSRC / rel) for rel in sources]
        + ["-o", str(out)]
    )
    if verbose:
        logger.info(f"building native op {name}: {' '.join(cmd)}")
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        logger.warning(f"native op {name} build failed:\n{proc.stderr}")
        return None
    return out


def load_op(name: str) -> Optional[ctypes.CDLL]:
    """Build (if needed) and dlopen an op; memoized per process."""
    if name in _loaded:
        return _loaded[name]
    path = build_op(name)
    lib = None
    if path is not None:
        try:
            lib = ctypes.CDLL(str(path))
        except OSError as e:
            logger.warning(f"native op {name}: dlopen failed: {e}")
    _loaded[name] = lib
    return lib


def build_all(verbose: bool = True) -> dict:
    """AOT build of every native op (reference DS_BUILD_* semantics)."""
    return {name: build_op(name, verbose=verbose) for name in _OPS}
