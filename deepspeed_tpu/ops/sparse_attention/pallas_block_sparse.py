"""Pallas block-sparse attention (TPU).

The kernel the reference implements in 2,285 LoC of Triton
(``deepspeed/ops/sparse_attention/trsrc/*.tr``: block-sparse matmul +
softmax over a block layout): attention that only touches the live
(q-block, kv-block) pairs of a ``SparsityConfig`` layout.

Built on the flash kernel's online-softmax machinery
(``ops/transformer/flash_attention.py``) with one change: the kv grid
dimension walks a *compacted per-row live-block list* instead of all
columns. The lists ride scalar prefetch (``pltpu.PrefetchScalarGridSpec``)
so the k/v BlockSpec index maps can look up the actual kv block index per
grid step — the Pallas/TPU analog of Triton's block-pointer tables, and the
same trick jax's own sparse kernels use. Compute and HBM traffic scale with
``nnz_blocks``, not seq²; rows are padded to the densest row's population
and padded steps are skipped via ``pl.when``.

The backward reuses the flash scheme (dq over the row lists; dk/dv over the
transposed column lists) with lse/delta residuals in the lanes-broadcast
[BN, T, 128] layout.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def build_block_tables(layout_h: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Compact a [nq, nk] bool layout into padded live lists.

    Returns (row_idx [nq, Lr], row_cnt [nq], col_idx [nk, Lc], col_cnt [nk]).
    """
    layout_h = np.asarray(layout_h, dtype=bool)
    nq, nk = layout_h.shape

    def compact(mat):
        live = [np.nonzero(mat[r])[0] for r in range(mat.shape[0])]
        width = max(1, max((len(l) for l in live), default=1))
        idx = np.zeros((mat.shape[0], width), dtype=np.int32)
        cnt = np.zeros((mat.shape[0],), dtype=np.int32)
        for r, l in enumerate(live):
            idx[r, : len(l)] = l
            cnt[r] = len(l)
        return idx, cnt

    row_idx, row_cnt = compact(layout_h)
    col_idx, col_cnt = compact(layout_h.T)
    return row_idx, row_cnt, col_idx, col_cnt


def _pair_mask(s, q_blk_i, k_blk_i, blk, causal):
    rows = q_blk_i * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = k_blk_i * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if causal:
        return jnp.where(rows >= cols, s, NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(row_idx, row_cnt, q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *, scale, blk, width, causal):
    qi = pl.program_id(1)
    li = pl.program_id(2)

    @pl.when(li == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(li < row_cnt[qi])
    def _compute():
        ki = row_idx[qi, li]
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        s = _pair_mask(s * scale, qi, ki, blk, causal)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        # NEG_INF is finite, so exp(s - m_new) would be 1 (not 0) on rows
        # whose every listed block is causally dead; zero them explicitly so
        # fully-masked rows finish with l=0 → o=0, lse=NEG_INF.
        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + jax.lax.dot(p, v, preferred_element_type=jnp.float32)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(li == width - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_s[...] / safe_l).astype(o_ref.dtype)
        # fully-masked rows (no live blocks / all-dead causal rows): lse=-inf
        lse = jnp.where(l == 0, NEG_INF, m_s[:, :1] + jnp.log(safe_l))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape).astype(lse_ref.dtype)


def _sparse_fwd(q, k, v, row_idx, row_cnt, scale, blk, causal, interpret):
    BN, T, D = q.shape
    nq, width = row_idx.shape
    kernel = functools.partial(_fwd_kernel, scale=scale, blk=blk, width=width, causal=causal)
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BN, nq, width),
        in_specs=[
            pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, qi, 0)),
            pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, ri[qi, li], 0)),
            pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, ri[qi, li], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, qi, 0)),
            pl.BlockSpec((1, blk, 128), lambda b, qi, li, ri, rc: (b, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, 128), jnp.float32),
            pltpu.VMEM((blk, 128), jnp.float32),
            pltpu.VMEM((blk, D), jnp.float32),
        ],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BN, T, D), q.dtype),
            jax.ShapeDtypeStruct((BN, T, 128), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(row_idx, row_cnt, q, k, v)
    return o, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _dq_kernel(row_idx, row_cnt, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_s, *, scale, blk, width, causal):
    qi = pl.program_id(1)
    li = pl.program_id(2)

    @pl.when(li == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    @pl.when(li < row_cnt[qi])
    def _compute():
        ki = row_idx[qi, li]
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        s = _pair_mask(s * scale, qi, ki, blk, causal)
        # masked entries have s=NEG_INF (finite): exp(s - lse) is 1, not 0,
        # when lse is also NEG_INF (fully-masked row) — zero them explicitly
        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_s[...] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(li == width - 1)
    def _finish():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(col_idx, col_cnt, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_s, dv_s, *, scale, blk, width, causal):
    ki = pl.program_id(1)
    li = pl.program_id(2)

    @pl.when(li == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    @pl.when(li < col_cnt[ki])
    def _compute():
        qi = col_idx[ki, li]
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        s = _pair_mask(s * scale, qi, ki, blk, causal)
        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - lse), 0.0)
        dv_s[...] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_s[...] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(li == width - 1)
    def _finish():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _sparse_bwd(res, g, scale, blk, causal, interpret):
    q, k, v, o, lse, row_idx, row_cnt, col_idx, col_cnt = res
    BN, T, D = q.shape
    nq, width_r = row_idx.shape
    nk, width_c = col_idx.shape
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse[:, :, None], (BN, T, 128))
    delta_b = jnp.broadcast_to(delta[:, :, None], (BN, T, 128))
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, blk=blk, width=width_r, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BN, nq, width_r),
            in_specs=[
                pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, qi, 0)),
                pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, ri[qi, li], 0)),
                pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, ri[qi, li], 0)),
                pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, qi, 0)),
                pl.BlockSpec((1, blk, 128), lambda b, qi, li, ri, rc: (b, qi, 0)),
                pl.BlockSpec((1, blk, 128), lambda b, qi, li, ri, rc: (b, qi, 0)),
            ],
            out_specs=pl.BlockSpec((1, blk, D), lambda b, qi, li, ri, rc: (b, qi, 0)),
            scratch_shapes=[pltpu.VMEM((blk, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((BN, T, D), q.dtype),
        interpret=interpret,
        **params,
    )(row_idx, row_cnt, q, k, v, do, lse_b, delta_b)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, blk=blk, width=width_c, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(BN, nk, width_c),
            in_specs=[
                pl.BlockSpec((1, blk, D), lambda b, ki, li, ci, cc: (b, ci[ki, li], 0)),
                pl.BlockSpec((1, blk, D), lambda b, ki, li, ci, cc: (b, ki, 0)),
                pl.BlockSpec((1, blk, D), lambda b, ki, li, ci, cc: (b, ki, 0)),
                pl.BlockSpec((1, blk, D), lambda b, ki, li, ci, cc: (b, ci[ki, li], 0)),
                pl.BlockSpec((1, blk, 128), lambda b, ki, li, ci, cc: (b, ci[ki, li], 0)),
                pl.BlockSpec((1, blk, 128), lambda b, ki, li, ci, cc: (b, ci[ki, li], 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, blk, D), lambda b, ki, li, ci, cc: (b, ki, 0)),
                pl.BlockSpec((1, blk, D), lambda b, ki, li, ci, cc: (b, ki, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((blk, D), jnp.float32),
                pltpu.VMEM((blk, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BN, T, D), k.dtype),
            jax.ShapeDtypeStruct((BN, T, D), v.dtype),
        ],
        interpret=interpret,
        **params,
    )(col_idx, col_cnt, q, k, v, do, lse_b, delta_b)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _sparse_core(q, k, v, row_idx, row_cnt, col_idx, col_cnt, scale, blk, causal, interpret):
    o, _ = _sparse_fwd(q, k, v, row_idx, row_cnt, scale, blk, causal, interpret)
    return o


def _sparse_core_fwd(q, k, v, row_idx, row_cnt, col_idx, col_cnt, scale, blk, causal, interpret):
    o, lse = _sparse_fwd(q, k, v, row_idx, row_cnt, scale, blk, causal, interpret)
    return o, (q, k, v, o, lse, row_idx, row_cnt, col_idx, col_cnt)


def _sparse_core_bwd(scale, blk, causal, interpret, res, g):
    dq, dk, dv = _sparse_bwd(res, g, scale, blk, causal, interpret)
    return dq, dk, dv, None, None, None, None


_sparse_core.defvjp(_sparse_core_fwd, _sparse_core_bwd)


def pallas_block_sparse_attention(
    q: jnp.ndarray,  # [B, NH, T, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    layout: np.ndarray,  # [NH or 1, T/block, T/block] bool
    block: int,
    causal: bool = False,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused block-sparse attention over the layout's live blocks.

    Requirements: T divisible by ``block``; ``block`` a multiple of 8 (TPU
    sublanes). A shared layout (leading dim 1) folds heads into the batch;
    per-head layouts run one kernel per head (different live lists).
    """
    B, NH, T, D = q.shape
    if T % block:
        raise ValueError(f"seq len {T} not divisible by block {block}")
    if block % 8:
        raise ValueError(f"block {block} must be a multiple of 8 (TPU sublanes)")
    scale_f = float(scale) if scale is not None else 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = not _on_tpu()
    layout = np.asarray(layout, dtype=bool)

    def run(qbn, kbn, vbn, layout_h):
        row_idx, row_cnt, col_idx, col_cnt = build_block_tables(layout_h)
        return _sparse_core(
            qbn, kbn, vbn,
            jnp.asarray(row_idx), jnp.asarray(row_cnt),
            jnp.asarray(col_idx), jnp.asarray(col_cnt),
            scale_f, block, causal, interpret,
        )

    if layout.shape[0] == 1:
        fold = lambda x: x.reshape(B * NH, T, D)
        o = run(fold(q), fold(k), fold(v), layout[0])
        return o.reshape(B, NH, T, D)
    outs = [
        run(q[:, h], k[:, h], v[:, h], layout[h]) for h in range(NH)
    ]
    return jnp.stack(outs, axis=1)
