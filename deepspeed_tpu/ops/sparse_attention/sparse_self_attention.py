"""Block-sparse self-attention.

Counterpart of the reference's Triton block-sparse kernels
(``deepspeed/ops/sparse_attention/``: ``SparseSelfAttention``,
``MatMul``/``Softmax`` on block layouts, triton sources ``trsrc/*.tr``) and
the C++ layout utils (``csrc/sparse_attention/utils.cpp``).

TPU implementation: the block layout gathers only the LIVE kv blocks per
query block (dense gather → [rows, max_live, block, d]) so compute and
memory scale with the number of live blocks, not seq² — the same work-
skipping the Triton kernel gets from its block pointers, expressed in
XLA-friendly dense gathers (static shapes, MXU-shaped einsums). Numerics are
exact attention over the unmasked pairs (softmax in fp32 over live blocks
with per-element masking).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    DenseSparsityConfig,
    SparsityConfig,
)


def _layout_gather_indices(layout_h: np.ndarray):
    """Per query-block row: indices of live kv blocks, padded to the max
    row population (padding marked dead)."""
    num_blocks = layout_h.shape[0]
    live = [np.nonzero(layout_h[r])[0] for r in range(num_blocks)]
    max_live = max((len(l) for l in live), default=1)
    max_live = max(max_live, 1)
    idx = np.zeros((num_blocks, max_live), dtype=np.int32)
    mask = np.zeros((num_blocks, max_live), dtype=bool)
    for r, l in enumerate(live):
        idx[r, : len(l)] = l
        mask[r, : len(l)] = True
    return idx, mask


def block_sparse_attention(
    q: jnp.ndarray,  # [B, NH, T, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    layout: np.ndarray,  # [NH or 1, T/block, T/block]
    block: int,
    causal: bool = False,
    scale: Optional[float] = None,
    key_padding_mask: Optional[jnp.ndarray] = None,  # [B, T], True = keep
) -> jnp.ndarray:
    B, NH, T, D = q.shape
    nb = T // block
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    shared_layout = layout.shape[0] == 1

    def one_head_group(qh, kh, vh, layout_h, kp_mask):
        # qh: [Bh, T, D] for one head (or heads folded into batch when the
        # layout is shared); Bh = B or B*NH
        Bh = qh.shape[0]
        idx, live_mask = _layout_gather_indices(layout_h)
        max_live = idx.shape[1]
        qb = qh.reshape(Bh, nb, block, D)
        kb = kh.reshape(Bh, nb, block, D)
        vb = vh.reshape(Bh, nb, block, D)
        # gather live kv blocks per query row: [B, nb, max_live, block, D]
        kg = kb[:, idx]
        vg = vb[:, idx]
        scores = (
            jnp.einsum("brqd,brlkd->brqlk", qb, kg).astype(jnp.float32) * scale
        )  # [Bh, nb, block, max_live, block]
        # masks: dead blocks, causal within pairs, key padding
        neg = jnp.float32(-1e30)
        mask = jnp.asarray(live_mask)[None, :, None, :, None]
        if causal:
            q_pos = (
                jnp.arange(nb)[:, None] * block + jnp.arange(block)[None, :]
            )  # [nb, block]
            k_pos = (
                jnp.asarray(idx)[:, :, None] * block + jnp.arange(block)[None, None, :]
            )  # [nb, max_live, block]
            causal_mask = q_pos[:, :, None, None] >= k_pos[:, None, :, :]
            mask = mask & causal_mask[None]
        if kp_mask is not None:
            kp = kp_mask.reshape(Bh, nb, block)  # [Bh, nb_k, block]
            kp_g = kp[:, idx]  # [Bh, nb, max_live, block]
            mask = mask & kp_g[:, :, None, :, :]
        scores = jnp.where(mask, scores, neg)
        flat = scores.reshape(Bh, nb, block, max_live * block)
        probs = jax.nn.softmax(flat, axis=-1)
        # rows with no live keys (padded causal heads) -> zero out
        any_live = jnp.any(
            jnp.broadcast_to(mask, scores.shape).reshape(Bh, nb, block, -1),
            axis=-1, keepdims=True,
        )
        probs = jnp.where(any_live, probs, 0.0).astype(vh.dtype)
        probs = probs.reshape(Bh, nb, block, max_live, block)
        out = jnp.einsum("brqlk,brlkd->brqd", probs, vg)
        return out.reshape(Bh, T, D)

    if shared_layout:
        # fold heads into batch: one gather pattern for all heads
        qf = q.reshape(B * NH, T, D)
        kf = k.reshape(B * NH, T, D)
        vf = v.reshape(B * NH, T, D)
        kp = (
            jnp.repeat(key_padding_mask, NH, axis=0)
            if key_padding_mask is not None
            else None
        )
        out = one_head_group(qf, kf, vf, layout[0], kp)
        return out.reshape(B, NH, T, D)
    outs = [
        one_head_group(q[:, h], k[:, h], v[:, h], layout[h], key_padding_mask)
        for h in range(NH)
    ]
    return jnp.stack(outs, axis=1)


class SparseSelfAttention:
    """Reference ``SparseSelfAttention`` module surface: config-driven
    layout, q/k/v in [B, NH, T, D]."""

    def __init__(
        self,
        sparsity_config: SparsityConfig = None,
        key_padding_mask_mode: str = "add",  # noqa: ARG002 - parity
        attn_mask_mode: str = "mul",  # noqa: ARG002
        max_seq_length: int = 2048,
    ):
        self.sparsity_config = sparsity_config or DenseSparsityConfig(num_heads=4)
        self.max_seq_length = max_seq_length
        self._layouts = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, key_padding_mask=None, attn_mask=None):  # noqa: ARG002
        T = query.shape[2]
        layout = self.get_layout(T)
        causal = getattr(self.sparsity_config, "attention", "bidirectional") == "unidirectional"
        if not self.sparsity_config.different_layout_per_head:
            layout = layout[:1]
        if key_padding_mask is not None and key_padding_mask.dtype != jnp.bool_:
            key_padding_mask = key_padding_mask > 0
        block = self.sparsity_config.block
        # the fused Pallas kernel (live-block grid, online softmax) carries
        # the hot path; key-padding masks and odd blocks fall back to the
        # XLA dense-gather emulation
        if key_padding_mask is None and T % block == 0 and block % 8 == 0:
            from deepspeed_tpu.ops.sparse_attention.pallas_block_sparse import (
                pallas_block_sparse_attention,
            )

            return pallas_block_sparse_attention(
                query, key, value, layout, block, causal=causal
            )
        return block_sparse_attention(
            query,
            key,
            value,
            layout,
            block,
            causal=causal,
            key_padding_mask=key_padding_mask,
        )


class BertSparseSelfAttention:
    """Reference ``BertSparseSelfAttention``: fused qkv projection around
    SparseSelfAttention for BERT-shaped inputs [B, T, H]."""

    def __init__(self, config, sparsity_config=None):
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        self.sparse = SparseSelfAttention(
            sparsity_config or FixedDefault(self.num_heads)
        )

    def __call__(self, hidden, wq, wk, wv, attention_mask=None):
        B, T, H = hidden.shape

        def split(x):
            return x.reshape(B, T, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)

        q = split(hidden @ wq)
        k = split(hidden @ wk)
        v = split(hidden @ wv)
        out = self.sparse(q, k, v, key_padding_mask=attention_mask)
        return out.transpose(0, 2, 1, 3).reshape(B, T, H)


def FixedDefault(num_heads: int):
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import FixedSparsityConfig

    return FixedSparsityConfig(num_heads=num_heads)
