"""Block-sparsity configurations.

Counterpart of the reference's ``deepspeed/ops/sparse_attention/sparsity_config.py``:
each config builds a per-head block-level layout tensor
``[num_heads, num_blocks, num_blocks]`` (1 = attend) that the sparse
attention kernel consumes. The layout math is device-agnostic; the variants
(Dense/Fixed/BigBird/BSLongformer/Variable/Local) follow the published
patterns (Sparse Transformers, BigBird, Longformer).
"""

from __future__ import annotations

import random
from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: block size + head layout sharing (reference SparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 16, different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block size {self.block}"
            )
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attend (a correctness baseline, reference Dense)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers 'fixed' pattern: local blocks + strided global
    summary blocks (reference FixedSparsityConfig)."""

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        different_layout_per_head: bool = False,
        num_local_blocks: int = 4,
        num_global_blocks: int = 1,
        attention: str = "bidirectional",
        horizontal_global_attention: bool = False,
        num_different_global_patterns: int = 1,
    ):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError("num_local_blocks must be a multiple of num_global_blocks")
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError("attention must be uni- or bidirectional")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = (
            num_different_global_patterns if different_layout_per_head else 1
        )

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        for h in range(self.num_layout_heads):
            # local windows
            for start in range(0, num_blocks, self.num_local_blocks):
                end = min(start + self.num_local_blocks, num_blocks)
                for r in range(start, end):
                    hi = (r + 1) if self.attention == "unidirectional" else end
                    layout[h, r, start:hi] = 1
            # global summary columns: last num_global_blocks of each window
            pattern = h % self.num_different_global_patterns
            first_g = self.num_local_blocks - (1 + pattern) * self.num_global_blocks
            for start in range(0, num_blocks, self.num_local_blocks):
                g0 = start + first_g
                g1 = g0 + self.num_global_blocks
                if g0 < 0:
                    continue
                if self.attention == "unidirectional":
                    # rows BELOW the window attend back to its summary blocks
                    layout[h, start + self.num_local_blocks :, g0:g1] = 1
                else:
                    layout[h, :, g0:g1] = 1
                    if self.horizontal_global_attention:
                        layout[h, g0:g1, :] = 1
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Custom local windows + explicit global rows/cols
    (reference VariableSparsityConfig)."""

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        different_layout_per_head: bool = False,
        num_random_blocks: int = 0,
        local_window_blocks: Optional[List[int]] = None,
        global_block_indices: Optional[List[int]] = None,
        global_block_end_indices: Optional[List[int]] = None,
        attention: str = "bidirectional",
        horizontal_global_attention: bool = False,
    ):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        if global_block_end_indices is not None:
            if len(global_block_end_indices) != len(self.global_block_indices):
                raise ValueError("global block start/end lists must align")
        self.global_block_end_indices = global_block_end_indices

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        rng = random.Random(0)
        for h in range(self.num_layout_heads):
            # variable-width local windows, cycling the width list
            start = 0
            wi = 0
            while start < num_blocks:
                width = self.local_window_blocks[min(wi, len(self.local_window_blocks) - 1)]
                end = min(start + width, num_blocks)
                for r in range(start, end):
                    hi = (r + 1) if self.attention == "unidirectional" else end
                    layout[h, r, start:hi] = 1
                start = end
                wi += 1
            # globals
            for gi, g0 in enumerate(self.global_block_indices):
                if g0 >= num_blocks:
                    continue
                g1 = (
                    self.global_block_end_indices[gi]
                    if self.global_block_end_indices is not None
                    else g0 + 1
                )
                g1 = min(g1, num_blocks)
                if self.attention == "unidirectional":
                    layout[h, g0:, g0:g1] = 1
                else:
                    layout[h, :, g0:g1] = 1
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = 1
            # random blocks
            for r in range(num_blocks):
                for _ in range(self.num_random_blocks):
                    c = rng.randrange(num_blocks)
                    if self.attention == "unidirectional" and c > r:
                        c = r
                    layout[h, r, c] = 1
        if self.attention == "unidirectional":
            causal = np.tril(np.ones((num_blocks, num_blocks), dtype=np.int64))
            layout = layout * causal[None]
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global (reference BigBird...)."""

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        different_layout_per_head: bool = False,
        num_random_blocks: int = 1,
        num_sliding_window_blocks: int = 3,
        num_global_blocks: int = 1,
        attention: str = "bidirectional",
    ):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        rng = random.Random(0)
        for h in range(self.num_layout_heads):
            for r in range(num_blocks):
                lo, hi = max(0, r - w), min(num_blocks, r + w + 1)
                layout[h, r, lo:hi] = 1
                for _ in range(self.num_random_blocks):
                    layout[h, r, rng.randrange(num_blocks)] = 1
            g = self.num_global_blocks
            layout[h, :g, :] = 1
            layout[h, :, :g] = 1
        if self.attention == "unidirectional":
            causal = np.tril(np.ones((num_blocks, num_blocks), dtype=np.int64))
            layout = layout * causal[None]
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + selected global indices
    (reference BSLongformerSparsityConfig)."""

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        different_layout_per_head: bool = False,
        num_sliding_window_blocks: int = 3,
        global_block_indices: Optional[List[int]] = None,
        global_block_end_indices: Optional[List[int]] = None,
        attention: str = "bidirectional",
    ):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for r in range(num_blocks):
                layout[h, r, max(0, r - w) : min(num_blocks, r + w + 1)] = 1
            for gi, g0 in enumerate(self.global_block_indices):
                if g0 >= num_blocks:
                    continue
                g1 = (
                    self.global_block_end_indices[gi]
                    if self.global_block_end_indices is not None
                    else g0 + 1
                )
                g1 = min(g1, num_blocks)
                layout[h, :, g0:g1] = 1
                layout[h, g0:g1, :] = 1
        if self.attention == "unidirectional":
            causal = np.tril(np.ones((num_blocks, num_blocks), dtype=np.int64))
            layout = layout * causal[None]
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference LocalSlidingWindowSparsityConfig)."""

    def __init__(
        self,
        num_heads: int,
        block: int = 16,
        num_sliding_window_blocks: int = 3,
        attention: str = "unidirectional",
    ):
        super().__init__(num_heads, block, different_layout_per_head=False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for r in range(num_blocks):
            lo = max(0, r - w)
            hi = (r + 1) if self.attention == "unidirectional" else min(num_blocks, r + w + 1)
            layout[0, r, lo:hi] = 1
        return self.check_and_propagate_first_head_layout(layout)
