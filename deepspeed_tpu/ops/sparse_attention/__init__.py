"""Block-sparse attention (reference: ``deepspeed/ops/sparse_attention/``)."""

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    BertSparseSelfAttention,
    SparseSelfAttention,
    block_sparse_attention,
)
