"""SGD with momentum (torch.optim.SGD semantics) for baseline parity tests."""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import DSOptimizer


class SGDState(NamedTuple):
    step: jax.Array
    momentum: Any


class SGD(DSOptimizer):
    def __init__(self, params=None, lr: float = 1e-2, momentum: float = 0.0, weight_decay: float = 0.0, nesterov: bool = False):  # noqa: ARG002
        super().__init__(lr=lr, weight_decay=weight_decay, momentum=momentum)
        self.nesterov = nesterov

    def init_state(self, params: Any) -> SGDState:
        return SGDState(
            step=jnp.zeros((), jnp.int32),
            momentum=jax.tree_util.tree_map(lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params),
        )

    def state_specs(self, param_specs: Any) -> "SGDState":
        from jax.sharding import PartitionSpec

        return SGDState(step=PartitionSpec(), momentum=param_specs)

    def apply(self, grads: Any, state: SGDState, params: Any, lr) -> Tuple[Any, SGDState]:
        mom = self.defaults["momentum"]
        wd = self.defaults["weight_decay"]

        def leaf(p, g, b):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if wd:
                g = g + wd * p32
            b = mom * b + g
            d = g + mom * b if self.nesterov else b
            return (p32 - lr * d).astype(p.dtype), b

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_b = treedef.flatten_up_to(state.momentum)
        out = [leaf(p, g, b) for p, g, b in zip(flat_p, flat_g, flat_b)]
        return (
            treedef.unflatten([o[0] for o in out]),
            SGDState(state.step + 1, treedef.unflatten([o[1] for o in out])),
        )
