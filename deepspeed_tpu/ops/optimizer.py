"""Optimizer base.

The reference's optimizers are CUDA multi-tensor-apply kernels behind
torch.optim classes (``csrc/adam/multi_tensor_adam.cu``,
``deepspeed/ops/adam/fused_adam.py``). On TPU an optimizer is a pair of pure
functions — ``init_state(params)`` and ``apply(grads, state, params, lr)`` —
that the engine jits *inside* the train step, so the whole update is one fused
XLA program over the sharded master buffers: that is the multi-tensor-apply
equivalent (one fused loop over every leaf, no per-param kernel launches).

The class carries torch-style ``param_groups`` (a list of dicts with ``lr``
etc.) because the reference's LR schedulers mutate ``param_groups[i]["lr"]``
(``deepspeed/runtime/lr_schedules.py``) — the engine reads the group lr each
step and feeds it to the jitted update as a traced scalar, so lr changes never
trigger recompilation.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple


class DSOptimizer:
    """Base: subclasses implement init_state / apply as pure functions."""

    def __init__(self, lr: float, weight_decay: float = 0.0, **defaults):
        self.defaults: Dict[str, Any] = {"lr": lr, "weight_decay": weight_decay, **defaults}
        self.param_groups: List[Dict[str, Any]] = [dict(self.defaults)]

    # --- torch-style surface -------------------------------------------
    @property
    def lr(self) -> float:
        return self.param_groups[0]["lr"]

    @lr.setter
    def lr(self, value: float) -> None:
        for g in self.param_groups:
            g["lr"] = value

    def get_lr(self) -> List[float]:
        return [g["lr"] for g in self.param_groups]

    # --- functional surface ---------------------------------------------
    def init_state(self, params: Any) -> Any:
        raise NotImplementedError

    def apply(self, grads: Any, state: Any, params: Any, lr) -> Tuple[Any, Any]:
        """Return (new_params, new_state). Must be jit-traceable."""
        raise NotImplementedError

    def state_specs(self, param_specs: Any) -> Any:
        """PartitionSpec tree for the optimizer state, congruent with
        ``init_state``'s output, given the master-param spec tree. ZeRO ≥ 1
        shards the moments exactly like the master partitions
        (stage_1_and_2.py ``initialize_optimizer_states`` :636)."""
        raise NotImplementedError

    def state_dict_shapes(self, params: Any) -> Any:
        """Shapes/dtypes of the optimizer state (for checkpoint planning)."""
        import jax

        return jax.eval_shape(self.init_state, params)
