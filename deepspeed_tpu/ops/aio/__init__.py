"""Async file I/O op (ZeRO-Infinity disk swapping).

Python surface of the native library ``csrc/aio/deepspeed_aio.cpp`` —
mirrors the reference's ``AsyncIOBuilder`` op (``op_builder/async_io.py``)
and its ``aio_handle`` pybind class (``csrc/aio/py_lib/py_ds_aio.cpp``):

    handle = AsyncIOHandle(block_size=1MB, queue_depth=8,
                           single_submit=False, overlap_events=True,
                           thread_count=1)
    handle.async_pwrite(np_array, "/nvme/t.bin"); ...; handle.wait()

Buffers are numpy arrays (the host-DRAM staging the reference keeps in
pinned CPU tensors); callers own a buffer until the matching wait().
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.native.build import load_op

AIO_DEFAULT_DICT = {
    "block_size": 1048576,
    "queue_depth": 8,
    "thread_count": 1,
    "single_submit": False,
    "overlap_events": True,
}


class AsyncIOBuilder:
    """Availability probe matching the reference builder's surface."""

    NAME = "async_io"

    def is_compatible(self) -> bool:
        return load_op("aio") is not None

    def load(self):
        lib = load_op("aio")
        if lib is None:
            raise RuntimeError("native aio library unavailable (g++ missing or build failed)")
        return lib


def _lib() -> ctypes.CDLL:
    lib = AsyncIOBuilder().load()
    lib.aio_handle_create.restype = ctypes.c_void_p
    lib.aio_handle_create.argtypes = [
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.aio_handle_destroy.argtypes = [ctypes.c_void_p]
    lib.aio_wait.argtypes = [ctypes.c_void_p]
    lib.aio_file_size.restype = ctypes.c_int64
    lib.aio_file_size.argtypes = [ctypes.c_char_p]
    for fn in (lib.aio_async_pread, lib.aio_sync_pread):
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    for fn in (lib.aio_async_pwrite, lib.aio_sync_pwrite):
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    return lib


class AsyncIOHandle:
    """The reference's ``aio_handle`` (py_ds_aio.cpp:17-20)."""

    def __init__(
        self,
        block_size: int = AIO_DEFAULT_DICT["block_size"],
        queue_depth: int = AIO_DEFAULT_DICT["queue_depth"],
        single_submit: bool = AIO_DEFAULT_DICT["single_submit"],
        overlap_events: bool = AIO_DEFAULT_DICT["overlap_events"],
        thread_count: int = AIO_DEFAULT_DICT["thread_count"],
    ):
        self._lib = _lib()
        self._handle = self._lib.aio_handle_create(
            block_size, queue_depth, int(single_submit), int(overlap_events), thread_count
        )
        if not self._handle:
            raise RuntimeError("aio_handle_create failed")
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.single_submit = single_submit
        self.overlap_events = overlap_events
        self.thread_count = thread_count
        self._inflight = 0

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            try:
                self._lib.aio_wait(handle)
                self._lib.aio_handle_destroy(handle)
            except Exception:
                pass
            self._handle = None

    @staticmethod
    def _buf_ptr(arr: np.ndarray):
        if not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("aio buffers must be C-contiguous")
        return arr.ctypes.data_as(ctypes.c_void_p)

    # --- async: caller must wait() before touching the buffer ------------
    def async_pread(self, buffer: np.ndarray, filename: str) -> int:
        rc = self._lib.aio_async_pread(
            self._handle, self._buf_ptr(buffer), filename.encode(), buffer.nbytes
        )
        if rc != 0:
            raise IOError(f"aio async_pread submit failed for {filename}")
        self._inflight += 1
        return 0

    def async_pwrite(self, buffer: np.ndarray, filename: str) -> int:
        rc = self._lib.aio_async_pwrite(
            self._handle, self._buf_ptr(buffer), filename.encode(), buffer.nbytes
        )
        if rc != 0:
            raise IOError(f"aio async_pwrite submit failed for {filename}")
        self._inflight += 1
        return 0

    def wait(self) -> int:
        """Block until all submitted ops finish; returns completed op count
        (raises on any I/O failure)."""
        errors = self._lib.aio_wait(self._handle)
        done = self._inflight
        self._inflight = 0
        if errors:
            raise IOError(f"aio: {errors} chunk operations failed")
        return done

    # --- sync convenience (reference sync_pread/sync_pwrite) -------------
    def sync_pread(self, buffer: np.ndarray, filename: str) -> int:
        rc = self._lib.aio_sync_pread(
            self._handle, self._buf_ptr(buffer), filename.encode(), buffer.nbytes
        )
        if rc != 0:
            raise IOError(f"aio sync_pread failed for {filename}")
        return buffer.nbytes

    def sync_pwrite(self, buffer: np.ndarray, filename: str) -> int:
        rc = self._lib.aio_sync_pwrite(
            self._handle, self._buf_ptr(buffer), filename.encode(), buffer.nbytes
        )
        if rc != 0:
            raise IOError(f"aio sync_pwrite failed for {filename}")
        return buffer.nbytes


def aio_read(buffer: np.ndarray, filename: str) -> int:
    """Module-level sync read (reference py_ds_aio.cpp:14 ``aio_read``)."""
    h = AsyncIOHandle()
    return h.sync_pread(buffer, filename)


def aio_write(buffer: np.ndarray, filename: str) -> int:
    h = AsyncIOHandle()
    return h.sync_pwrite(buffer, filename)


def file_size(filename: str) -> int:
    lib = _lib()
    return lib.aio_file_size(filename.encode())
