"""Adagrad (reference: ``deepspeed/ops/adagrad/cpu_adagrad.py`` +
``csrc/adagrad/cpu_adagrad.cpp``).

The in-jit variant lives here; the true host-offloaded (C++/AVX) path is in
``deepspeed_tpu/ops/host_optimizer`` and shares this math.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import DSOptimizer


class AdagradState(NamedTuple):
    step: jax.Array
    sum_sq: Any


class DeepSpeedCPUAdagrad(DSOptimizer):
    def __init__(self, params=None, lr: float = 1e-2, eps: float = 1e-10, weight_decay: float = 0.0):  # noqa: ARG002
        super().__init__(lr=lr, weight_decay=weight_decay, eps=eps)

    def init_state(self, params: Any) -> AdagradState:
        return AdagradState(
            step=jnp.zeros((), jnp.int32),
            sum_sq=jax.tree_util.tree_map(lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params),
        )

    def state_specs(self, param_specs: Any) -> "AdagradState":
        from jax.sharding import PartitionSpec

        return AdagradState(step=PartitionSpec(), sum_sq=param_specs)

    def apply(self, grads: Any, state: AdagradState, params: Any, lr) -> Tuple[Any, AdagradState]:
        eps = self.defaults["eps"]
        wd = self.defaults["weight_decay"]

        def leaf(p, g, s):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if wd:
                g = g + wd * p32
            s = s + g * g
            return (p32 - lr * g / (jnp.sqrt(s) + eps)).astype(p.dtype), s

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(state.sum_sq)
        out = [leaf(p, g, s) for p, g, s in zip(flat_p, flat_g, flat_s)]
        return (
            treedef.unflatten([o[0] for o in out]),
            AdagradState(state.step + 1, treedef.unflatten([o[1] for o in out])),
        )
