"""Expert-parallel all-to-all exchange: the MoE training fast path.

Counterpart of the reference's explicit ``_AllToAll`` autograd function
(``deepspeed/moe/sharded_moe.py:98``): each data-parallel rank gates its OWN
tokens against a LOCAL capacity, dispatches them into a ``[E, C_local, H]``
buffer, and one all-to-all over the expert group hands every expert its
slice. The earlier GSPMD formulation in this repo annotated the global
``[S, E, C]`` gating tensors instead and let the partitioner derive the
exchange — which it did, but only after involuntarily replicating the token
matrix (SPMD "full rematerialization" on the ``[S, E]`` masks), leaving
exposed loop all-gathers the overlap pass flags.

This module restores the reference dataflow with ``shard_map``:

* **Per-shard gating** — ``ep_gate_dispatch`` runs ``topkgating`` on each
  token shard independently (capacity = ``ceil(S_local/E · cf)``, exactly
  the reference's per-rank capacity), so the cumsum/one-hot bookkeeping is
  pure local math: zero collectives, no partitioner guesswork, and the
  capacity-overflow drop pattern is a deterministic function of each
  shard's tokens alone.
* **Explicit dispatch/combine a2a** — ``lax.all_to_all`` over the
  ``expert`` mesh axis splits the local ``[E, C_l, H]`` buffer's expert dim
  and concatenates the received capacity blocks:
  ``[E, C_l, H] ↔ [E/e, e·C_l, H]``. The transpose of an all-to-all is the
  inverse all-to-all, so autodiff gives the backward exchange for free.
* **Int8 wire format** — ``quantized_all_to_all`` sends the payload as int8
  codes with a per-(expert, slot) fp32 scale side-channel (EQuARX-style,
  arXiv 2506.17615; generalizes ``inference/tp.py:quantized_all_reduce``
  from all-reduce to a2a op kinds). The cotangent rides the inverse
  exchange in the same wire format, so both directions cost fp32/4 on the
  wire; the collectives analysis pass prices the int8 payload via its
  ``quantized_*`` fields.

Every differentiable ``shard_map`` input/output is fully device-varying
(token-sharded or expert-sharded — the gate weight matmul stays OUTSIDE in
GSPMD-land), so gradients are exact without replication bookkeeping. The
expert FFN also runs outside, on the globally ``[E, n·C_l, H]``-shaped
dispatched tensor: both einsum operands are expert-sharded on the stacked
dim, so the compute is local and the expert-weight gradient reduction rides
the engine's existing ZeRO machinery.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.moe import sharded_moe

EXPERT_AXIS = "expert"

_SCALE_FLOOR = 1e-30  # an all-zero chunk must not divide by zero


def token_shard_axes(topo) -> Tuple[str, ...]:
    """Mesh axes the flattened ``[S, H]`` token dim is sharded over: the
    dense batch axes (B) followed by ``sequence`` (T) — the row-major merge
    order of ``x.reshape(-1, H)`` on a ``[B, T, H]`` activation."""
    axes = [a for a in ("data_outer", "data", EXPERT_AXIS) if topo.axis_size(a) > 1]
    if topo.axis_size("sequence") > 1:
        axes.append("sequence")
    return tuple(axes)


def _spec_entry(axes: Tuple[str, ...]):
    if not axes:
        return None
    if len(axes) == 1:
        return axes[0]
    return tuple(axes)


def ep_fast_path(topo, num_experts: int, num_tokens: int) -> bool:
    """True when the shard_map expert-parallel path applies: a real expert
    mesh axis that divides the expert count, and token shards of equal
    size (static shapes inside shard_map need even divisibility)."""
    if topo is None:
        return False
    e = topo.axis_size(EXPERT_AXIS)
    if e <= 1 or num_experts % e:
        return False
    n = int(np.prod([topo.axis_size(a) for a in token_shard_axes(topo)]))
    return n > 1 and num_tokens % n == 0


# --- wire formats -----------------------------------------------------------


def _all_to_all(x, split_axis: int, concat_axis: int, axis_name: str = EXPERT_AXIS):
    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def quantized_all_to_all(x, split_axis: int, concat_axis: int, axis_name: str = EXPERT_AXIS):
    """All-to-all with an int8 wire format (inside shard_map).

    Encode: per-chunk symmetric quantization over the trailing (hidden)
    dim — ``scale = max|chunk|/127`` — then TWO a2a ops: the int8 codes and
    the fp32 scale side-channel; decode on arrival. Wire cost is
    ``bytes/4 + bytes/H`` of the fp32 payload. Backward: the cotangent
    takes the INVERSE exchange in the same wire format (the reference's
    quantized-gradient-comm contract: lossy but symmetric), so training
    never moves an fp-width a2a payload.
    """
    return _qa2a(x, split_axis, concat_axis, axis_name)


def _qa2a(x, split_axis, concat_axis, axis_name):
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, _SCALE_FLOOR) / 127.0  # [E, C, 1] side-channel
    q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    qx = _all_to_all(q, split_axis, concat_axis, axis_name)
    sx = _all_to_all(scale, split_axis, concat_axis, axis_name)
    return (qx.astype(jnp.float32) * sx).astype(x.dtype)


def _qa2a_fwd(x, split_axis, concat_axis, axis_name):
    return _qa2a(x, split_axis, concat_axis, axis_name), None


def _qa2a_bwd(split_axis, concat_axis, axis_name, _res, g):
    # inverse exchange (swap split/concat), same int8 wire
    return (quantized_all_to_all(g, concat_axis, split_axis, axis_name),)


quantized_all_to_all.defvjp(_qa2a_fwd, _qa2a_bwd)


def exchange_shard(x, *, inverse: bool = False, quantized: bool = False,
                   axis_name: str = EXPERT_AXIS):
    """Per-shard expert exchange ``[E, C, H] ↔ [E/e, e·C, H]`` (call inside
    shard_map). ``inverse=False`` is dispatch (split experts, gather
    capacity); ``inverse=True`` is combine."""
    split, concat = (1, 0) if inverse else (0, 1)
    if quantized:
        return quantized_all_to_all(x, split, concat, axis_name)
    return _all_to_all(x, split, concat, axis_name)


# --- global-view wrappers ---------------------------------------------------


def ep_gate_dispatch(
    tokens,
    logits,
    topo,
    *,
    k: int,
    capacity_factor: float,
    min_capacity: int,
    drop_tokens: bool = True,
    use_rts: bool = True,
    noisy_gate_policy: Optional[str] = None,
    rng: Optional[jax.Array] = None,
    used_token_mask=None,
    quantized: bool = False,
):
    """Per-shard gating + capacity dispatch + the dispatch all-to-all.

    ``tokens [S, H]`` / ``logits [S, E]`` arrive token-sharded; returns

    * ``dispatched [E, n·C_l, H]`` — expert-sharded on dim 0 (each expert
      shard holds every token shard's capacity block for its experts),
    * ``combine_w [S, E, C_l]`` — token-sharded, consumed by
      :func:`ep_combine`,
    * ``l_aux [n]`` — one load-balance loss per token shard (mean them),
    * ``exp_counts [n, E]`` — per-shard routed-token counts (sum them).
    """
    mesh = topo.mesh
    tok_axes = token_shard_axes(topo)
    rest = tuple(a for a in tok_axes if a != EXPERT_AXIS)
    tok_e, rest_e = _spec_entry(tok_axes), _spec_entry(rest)
    n = int(np.prod([topo.axis_size(a) for a in tok_axes]))

    in_specs = [P(tok_e, None), P(tok_e, None)]
    args = [tokens, logits]
    has_rng = rng is not None
    if has_rng:
        # one independent key per token shard, passed as sharded DATA so
        # every shard_map input stays device-varying
        keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(jnp.arange(n))
        in_specs.append(P(tok_e) if keys.ndim == 1 else P(tok_e, None))
        args.append(keys)
    has_mask = used_token_mask is not None
    if has_mask:
        in_specs.append(P(tok_e))
        args.append(used_token_mask)

    def body(tok_l, lg_l, *extra):
        i = 0
        key = None
        if has_rng:
            key = extra[0][0]
            i = 1
        mask_l = extra[i] if has_mask else None
        l_aux, cw, dm, counts = sharded_moe.topkgating(
            lg_l,
            k,
            capacity_factor,
            min_capacity,
            drop_tokens=drop_tokens,
            rng=key,
            noisy_gate_policy=noisy_gate_policy,
            use_rts=use_rts,
            used_token_mask=mask_l,
        )
        d = sharded_moe.dispatch(tok_l, dm)  # [E, C_l, H], local
        d = exchange_shard(d, quantized=quantized)  # the dispatch a2a
        return d, cw, l_aux[None], counts[None]

    out_specs = (
        P(EXPERT_AXIS, rest_e, None),
        P(tok_e, None, None),
        P(tok_e),
        P(tok_e, None),
    )
    return shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_specs, check_rep=False
    )(*args)


def ep_combine(expert_out, combine_w, topo, *, quantized: bool = False):
    """The combine all-to-all + weighted un-dispatch: ``expert_out
    [E, n·C_l, H]`` (expert-sharded) → ``[S, H]`` (token-sharded)."""
    mesh = topo.mesh
    tok_axes = token_shard_axes(topo)
    rest = tuple(a for a in tok_axes if a != EXPERT_AXIS)
    tok_e, rest_e = _spec_entry(tok_axes), _spec_entry(rest)

    def body(eo_l, cw_l):
        back = exchange_shard(eo_l, inverse=True, quantized=quantized)  # [E, C_l, H]
        return sharded_moe.combine(back, cw_l)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(EXPERT_AXIS, rest_e, None), P(tok_e, None, None)),
        out_specs=P(tok_e, None),
        check_rep=False,
    )(expert_out, combine_w)
