"""Mixture-of-Experts (expert parallelism).

TPU-native counterpart of ``deepspeed/moe/``: top-1/top-2 gating with
capacity + load-balance loss, expert dispatch over the ``expert`` mesh axis
(GSPMD all-to-all), stacked-expert FFNs, PR-MoE residual.
"""

from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.experts import (
    apply_expert_ffn,
    expert_partition_rules,
    init_expert_ffn,
)
from deepspeed_tpu.moe.sharded_moe import (
    combine,
    dispatch,
    multiplicative_jitter,
    top1gating,
    top2gating,
    topkgating,
)

__all__ = [
    "MoE",
    "top1gating",
    "top2gating",
    "topkgating",
    "dispatch",
    "combine",
    "multiplicative_jitter",
    "init_expert_ffn",
    "apply_expert_ffn",
    "expert_partition_rules",
]
