"""TP token mappings for MoE (reference: ``deepspeed/moe/mappings.py`` —
``gather_tokens``/``drop_tokens`` all-gather or shard activations along a
dim over the tensor-parallel group, with hand-written autograd duals).

TPU-native design: both are sharding constraints touching ONLY the mapped
dim — every other dim stays ``UNCONSTRAINED`` so existing data/sequence
shardings survive (the reference likewise only moves data over the TP
group). ``drop_tokens`` pins the dim to the ``model`` axis; ``gather_tokens``
pins it unsharded (XLA inserts the TP all-gather). NOTE on backward:
``with_sharding_constraint`` transposes to the SAME constraint (cotangents
take the forward layout, not the reference's inverse reshard) — values are
identical, only gradient layout differs, and GSPMD reshards lazily at the
next use."""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel import mesh as mesh_mod

_U = PartitionSpec.UNCONSTRAINED


def _live_tp():
    """(topology, tp_size) without side effects: no topology is CREATED here
    — before initialize_topology these are identity maps (reference returns
    the input unchanged when mpu is None / mp_size == 1)."""
    topo = mesh_mod._TOPOLOGY
    if topo is None:
        return None, 1
    return topo, topo.axis_size("model")


def gather_tokens(input_, dim: int = 0):
    """Un-shard ``dim`` from the TP group (reference ``gather_tokens``):
    the dim becomes whole on every TP shard; other dims keep their layout."""
    topo, tp = _live_tp()
    if tp <= 1:
        return input_
    spec = [_U] * input_.ndim
    spec[dim] = None
    return jax.lax.with_sharding_constraint(
        input_, NamedSharding(topo.mesh, PartitionSpec(*spec))
    )


def drop_tokens(input_, dim: int = 0):
    """Shard ``dim`` over the TP group (reference ``drop_tokens``): each
    shard keeps its own chunk; other dims keep their layout."""
    topo, tp = _live_tp()
    if tp <= 1:
        return input_
    if input_.shape[dim] % tp != 0:
        raise ValueError(
            f"dimension {dim} ({input_.shape[dim]}) is not divisible by the "
            f"tensor-parallel world size ({tp})"
        )
    spec = [_U] * input_.ndim
    spec[dim] = "model"
    return jax.lax.with_sharding_constraint(
        input_, NamedSharding(topo.mesh, PartitionSpec(*spec))
    )


# reference private aliases
_gather_tokens = gather_tokens
_drop_tokens = drop_tokens
