"""MoE layer: gate + sharded experts (+ PR-MoE residual).

Counterpart of ``deepspeed/moe/layer.py`` (``MoE`` :16) and the ``MOELayer``
/ ``TopKGate`` pair (``deepspeed/moe/sharded_moe.py:435,:370``). The
reference binds experts to an expert-parallel process group created lazily in
``set_deepspeed_parallelism`` (layer.py:87); here expert placement is the
``expert`` mesh axis: the stacked ``[E, ...]`` expert weights and the
dispatched ``[E, C, H]`` activations both carry an ``expert``-axis sharding
constraint, and GSPMD materializes the reference's ``_AllToAll`` exchange
(sharded_moe.py:98) as XLA all-to-alls over ICI.

``use_residual=True`` gives PR-MoE (pyramid-residual, layer.py use_residual
branch): a dense MLP runs in parallel and a learned 2-way coefficient mixes
both outputs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.moe import a2a, sharded_moe
from deepspeed_tpu.moe.experts import (
    apply_dense_ffn,
    apply_expert_ffn,
    expert_partition_rules,
    init_dense_ffn,
    init_expert_ffn,
)


class MoE:
    """Mixture of Experts layer (functional).

    ``init(rng)`` builds the param tree; ``apply(params, x, ...)`` returns
    ``(output, l_aux, exp_counts)`` exactly like the reference's
    ``MoE.forward`` (layer.py:115).
    """

    def __init__(
        self,
        hidden_size: int,
        num_experts: int = 1,
        ep_size: int = 1,
        k: int = 1,
        capacity_factor: float = 1.0,
        eval_capacity_factor: float = 1.0,
        min_capacity: int = 4,
        use_residual: bool = False,
        noisy_gate_policy: Optional[str] = None,
        drop_tokens: bool = True,
        use_rts: bool = True,
        intermediate_size: Optional[int] = None,
        activation: str = "gelu",
        use_bias: bool = True,
        out_std: Optional[float] = None,
        quantized_a2a: bool = False,
    ):
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        # ep_size is accepted for reference-API parity (layer.py:16) but expert
        # placement is mesh-driven here: the 'expert' axis of the device mesh
        # (config "mesh": {"expert": N}) decides the parallel degree.
        self.ep_size = ep_size
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.use_residual = use_residual
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self.use_rts = use_rts
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.activation = activation
        self.use_bias = use_bias
        self.out_std = out_std
        # int8 dispatch/combine wire format (EQuARX-style); an active
        # OverlapPlan's a2a stage overrides this layer-local default
        self.quantized_a2a = quantized_a2a

    # --- params ---------------------------------------------------------
    def init(self, rng) -> Dict[str, Any]:
        kg, ke, km, kc = jax.random.split(rng, 4)
        params: Dict[str, Any] = {
            # gate weight is fp32 always (reference TopKGate keeps wg in fp32)
            "gate": {"wg": jax.random.normal(kg, (self.hidden_size, self.num_experts), jnp.float32) * 0.02},
            "experts": init_expert_ffn(
                ke,
                self.num_experts,
                self.hidden_size,
                self.intermediate_size,
                activation=self.activation,
                use_bias=self.use_bias,
                out_std=self.out_std,
            ),
        }
        if self.use_residual:
            H = self.hidden_size
            params["mlp"] = init_dense_ffn(
                km,
                H,
                self.intermediate_size,
                activation=self.activation,
                use_bias=self.use_bias,
                out_std=self.out_std,
            )
            params["coefficient"] = {
                "w": jax.random.normal(kc, (H, 2), jnp.float32) * 0.02,
                "b": jnp.zeros((2,)),
            }
        return params

    # --- sharding -------------------------------------------------------
    def partition_rules(self, params: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Expert weights over the ``expert`` axis; gate/residual replicated."""
        if params is None:
            params = jax.eval_shape(lambda r: self.init(r), jax.random.PRNGKey(0))
        rules = jax.tree_util.tree_map(lambda p: P(*([None] * np.ndim(p))), params)
        rules["experts"] = expert_partition_rules(params["experts"])
        return rules

    def _constrain(self, x, spec):
        """Sharding constraint against the active topology (no-op off-mesh)."""
        from deepspeed_tpu.parallel.mesh import _TOPOLOGY

        if _TOPOLOGY is None or _TOPOLOGY.config.expert <= 1:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(_TOPOLOGY.mesh, spec))

    # --- forward --------------------------------------------------------
    def apply(
        self,
        params: Dict[str, Any],
        x: jnp.ndarray,
        *,
        train: bool = True,
        rng: Optional[jax.Array] = None,
        used_token_mask: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        orig_shape = x.shape
        H = orig_shape[-1]
        tokens = x.reshape(-1, H)

        gate_in = tokens
        if self.noisy_gate_policy == "Jitter" and train and rng is not None:
            rng, sub = jax.random.split(rng)
            gate_in = sharded_moe.multiplicative_jitter(tokens, sub)
        logits = gate_in.astype(jnp.float32) @ params["gate"]["wg"]

        cf = self.capacity_factor if train else self.eval_capacity_factor
        from deepspeed_tpu.parallel.mesh import _TOPOLOGY

        if a2a.ep_fast_path(_TOPOLOGY, self.num_experts, tokens.shape[0]):
            # expert-parallel fast path: per-shard gating + explicit
            # dispatch/combine all-to-alls (moe/a2a.py). The dispatch a2a is
            # emitted before the residual/shared-dense branch and the combine
            # before the next layer's gating — both independent of that
            # compute, so the overlap pass finds real work to hide them
            # behind. Wire format comes from the engine's OverlapPlan a2a
            # stage when one is active (training trace), else the layer knob.
            from deepspeed_tpu.runtime.zero.overlap import active_plan

            plan = active_plan()
            quantized = (
                plan.a2a_quantized
                if plan is not None and plan.a2a_quantized is not None
                else self.quantized_a2a
            )
            dispatched, combine_w, l_aux_shards, count_shards = a2a.ep_gate_dispatch(
                tokens,
                logits,
                _TOPOLOGY,
                k=self.k,
                capacity_factor=cf,
                min_capacity=self.min_capacity,
                drop_tokens=self.drop_tokens,
                use_rts=self.use_rts,
                noisy_gate_policy=self.noisy_gate_policy if train else None,
                rng=rng if train else None,
                used_token_mask=used_token_mask,
                quantized=quantized,
            )
            rest = tuple(
                x for x in a2a.token_shard_axes(_TOPOLOGY) if x != "expert"
            )
            ep_spec = P("expert", rest if rest else None, None)
            expert_out = apply_expert_ffn(params["experts"], dispatched, self.activation)
            expert_out = self._constrain(expert_out, ep_spec)
            out = a2a.ep_combine(expert_out, combine_w, _TOPOLOGY, quantized=quantized)
            l_aux = jnp.mean(l_aux_shards)
            exp_counts = jnp.sum(count_shards, axis=0)
        else:
            l_aux, combine_w, dispatch_m, exp_counts = sharded_moe.topkgating(
                logits,
                self.k,
                cf,
                self.min_capacity,
                drop_tokens=self.drop_tokens,
                rng=rng if train else None,
                noisy_gate_policy=self.noisy_gate_policy if train else None,
                use_rts=self.use_rts,
                used_token_mask=used_token_mask,
            )

            dispatched = sharded_moe.dispatch(tokens, dispatch_m)
            dispatched = self._constrain(dispatched, P("expert", None, None))
            expert_out = apply_expert_ffn(params["experts"], dispatched, self.activation)
            expert_out = self._constrain(expert_out, P("expert", None, None))
            out = sharded_moe.combine(expert_out, combine_w)

        if self.use_residual:
            mlp_out = apply_dense_ffn(params["mlp"], tokens, self.activation)
            coef = tokens.astype(jnp.float32) @ params["coefficient"]["w"] + params["coefficient"]["b"]
            coef = jax.nn.softmax(coef, axis=-1).astype(out.dtype)
            out = out * coef[..., 0:1] + mlp_out * coef[..., 1:2]

        return out.reshape(orig_shape), l_aux, exp_counts
