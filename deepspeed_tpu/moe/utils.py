"""MoE parameter utilities.

Counterpart of ``deepspeed/moe/utils.py`` (``is_moe_param`` :23,
``split_params_into_shared_and_expert_params`` :29,
``split_params_grads_into_shared_and_expert_params`` :40,
``split_params_into_different_moe_groups_for_optimizer`` :65,
``has_moe_layers`` :11).

TPU-native design: the reference tags ``nn.Parameter`` objects with an
``allreduce=False`` attribute at construction; here expert-ness is a
property of a leaf's PATH in the param pytree — expert weights live under an
``experts`` subtree (``moe/layer.py`` init; the gate stays replicated) — so
classification is a
pure function of the tree, usable on params AND on grad trees (which share
the structure). Splitting returns same-structure trees with ``None`` holes,
ready for tree_map-based norm/clip math (the reference's use case: separate
grad-norms for expert vs shared params)."""

from __future__ import annotations

from typing import Any, Dict, List, Tuple, Union

import jax

# only the weights living under an "experts" subtree shard over the expert
# axis; the gate / PR-MoE residual mlp / coefficient under "moe" are
# REPLICATED (moe/layer.py partition rules) and must stay in the shared set
_EXPERT_PATH_MARKERS = ("experts",)


def _path_names(path) -> List[str]:
    out = []
    for entry in path:
        key = getattr(entry, "key", None)
        if key is None:
            key = getattr(entry, "idx", None)
        if key is None:
            key = getattr(entry, "name", None)  # GetAttrKey pytree nodes
        out.append(str(key))
    return out


def is_moe_param_path(path_names: Union[str, List[str]]) -> bool:
    """True when a tree path addresses an expert weight (reference
    ``is_moe_param`` — the ``allreduce=False`` tag, re-expressed as a path
    property). The gate and other replicated MoE-layer params are NOT
    expert params."""
    if isinstance(path_names, str):
        path_names = path_names.split("/")
    if not all(isinstance(n, str) for n in path_names):
        raise TypeError(
            "is_moe_param_path takes a 'a/b/c' string or a list of path "
            "names — in this functional design expert-ness is a property "
            "of a leaf's tree path, not of the array"
        )
    return any(
        name in _EXPERT_PATH_MARKERS or name.startswith("expert_")
        for name in path_names
    )


# reference-shaped alias: there is no tensor tag to read here, so the path
# form IS the API (arrays are rejected with a clear TypeError above)
is_moe_param = is_moe_param_path


def has_moe_layers(model_or_params: Any) -> Tuple[bool, int]:
    """(has_moe, num_experts) — accepts a model family instance or a param
    tree (reference :11 walks modules looking for MoE layers; an MoE layer
    with one expert is still an MoE layer). The tree form reports
    num_experts=0 (unknown from structure alone)."""
    cfg = getattr(model_or_params, "config", None)
    if cfg is not None and hasattr(cfg, "num_experts"):
        return True, int(getattr(cfg, "num_experts", 0))
    tree = model_or_params
    if hasattr(model_or_params, "get_params"):
        tree = model_or_params.get_params()
    if not isinstance(tree, dict):
        return False, 0
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return any(is_moe_param_path(_path_names(p)) for p, _ in flat), 0


def split_params_into_shared_and_expert_params(params: Dict[str, Any]) -> Tuple[Dict, Dict]:
    """Two same-structure trees with ``None`` holes: (shared, expert)
    (reference :29). Works on grad trees too — structure is shared."""

    def pick(want_expert):
        def visit(path, leaf):
            return leaf if is_moe_param_path(_path_names(path)) == want_expert else None

        return jax.tree_util.tree_map_with_path(visit, params)

    return pick(False), pick(True)


# the grads variant is the same split — grad trees mirror the param tree
split_params_grads_into_shared_and_expert_params = split_params_into_shared_and_expert_params


def split_params_into_different_moe_groups_for_optimizer(
    param_groups: Union[Dict, List[Dict], Tuple[Dict, ...]],
) -> List[Dict]:
    """Split optimizer param groups so expert subtrees sit in their own
    groups flagged ``moe=True`` (reference :65 — ZeRO/optimizers treat
    expert groups with expert-data-parallel reduction). Each group's
    ``params`` is a pytree; expert leaves move to a parallel group named
    ``<name>_moe`` with the same hyperparameters."""
    if isinstance(param_groups, dict):
        param_groups = [param_groups]
    else:
        param_groups = list(param_groups)
    out: List[Dict] = []
    for group in param_groups:
        if "params" not in group:
            raise ValueError("param group is missing a 'params' entry")
        shared, expert = split_params_into_shared_and_expert_params(group["params"])
        base = {k: v for k, v in group.items() if k != "params"}
        shared_group = dict(base, params=shared, moe=False)
        out.append(shared_group)
        if any(leaf is not None for leaf in jax.tree_util.tree_leaves(expert)):
            name = group.get("name", "group")
            out.append(dict(base, params=expert, moe=True, name=f"{name}_moe"))
    return out
