"""Stacked expert FFNs.

Counterpart of ``deepspeed/moe/experts.py`` (``Experts`` — a ModuleList of
deep-copied expert modules, each rank holding ``num_local_experts``). The
TPU-native layout stacks every expert's weights on a leading ``[E, ...]`` dim
sharded over the ``expert`` mesh axis, so "local experts" are the shards XLA
assigns — expert compute is one batched einsum that lands on the MXU, and no
Python loop over experts exists.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


def init_dense_ffn(
    rng,
    hidden_size: int,
    intermediate_size: int,
    activation: str = "gelu",
    use_bias: bool = True,
    std: float = 0.02,
    out_std: float = None,
) -> Dict[str, Any]:
    """Single dense FFN params (the MoE residual branch / per-layer MLP)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    H, I = hidden_size, intermediate_size
    out_std = std if out_std is None else out_std
    params: Dict[str, Any] = {}
    if activation in ("swiglu", "geglu"):
        params["w_gate"] = jax.random.normal(k1, (H, I), jnp.float32) * std
        params["w_up"] = jax.random.normal(k3, (H, I), jnp.float32) * std
    else:
        params["w_in"] = jax.random.normal(k1, (H, I), jnp.float32) * std
        if use_bias:
            params["b_in"] = jnp.zeros((I,))
    params["w_out"] = jax.random.normal(k2, (I, H), jnp.float32) * out_std
    if use_bias:
        params["b_out"] = jnp.zeros((H,))
    return params


def _pointwise_activation(x: jnp.ndarray, activation: str) -> jnp.ndarray:
    if activation == "gelu":
        return jax.nn.gelu(x)
    if activation == "relu":
        return jax.nn.relu(x)
    if activation == "quick_gelu":  # CLIP: x * sigmoid(1.702 x)
        return x * jax.nn.sigmoid(1.702 * x)
    raise ValueError(f"unknown pointwise activation {activation!r}")


def apply_dense_ffn(params: Dict[str, Any], x: jnp.ndarray, activation: str = "gelu",
                    tp=None) -> jnp.ndarray:
    """[..., H] → [..., H] dense FFN; single source of activation semantics
    (shared by TransformerLM layers and the PR-MoE residual branch).
    ``qmatmul`` fuses int8-weight dequantization when the leaves are
    quantized (``compression/int8.py``). Under tensor-parallel serving
    (``tp``, a ``inference/tp.py:TPServing`` inside shard_map) the up/gate
    projections are column-parallel (weights arrive pre-sliced), the down
    projection is row-parallel through ``tp.row_matmul``'s all-reduce, and
    the replicated output bias is added once, after the reduce."""
    from deepspeed_tpu.compression.int8 import qmatmul

    dt = x.dtype
    if activation in ("swiglu", "geglu"):
        gate = qmatmul(x, params["w_gate"])
        up = qmatmul(x, params["w_up"])
        act = jax.nn.silu(gate) if activation == "swiglu" else jax.nn.gelu(gate)
        inner = act * up
    else:
        inner = qmatmul(x, params["w_in"])
        if "b_in" in params:
            inner = inner + params["b_in"].astype(dt)
        inner = _pointwise_activation(inner, activation)
    out = (
        tp.row_matmul(inner, params["w_out"]) if tp is not None
        else qmatmul(inner, params["w_out"])
    ).astype(dt)
    if "b_out" in params:
        out = out + params["b_out"].astype(dt)
    return out


def init_expert_ffn(
    rng,
    num_experts: int,
    hidden_size: int,
    intermediate_size: int,
    activation: str = "gelu",
    use_bias: bool = True,
    std: float = 0.02,
    out_std: float = None,
) -> Dict[str, Any]:
    """Stacked expert MLP params: every leaf leads with the expert dim [E, ...]."""
    k1, k2, k3 = jax.random.split(rng, 3)
    E, H, I = num_experts, hidden_size, intermediate_size
    out_std = std if out_std is None else out_std
    params: Dict[str, Any] = {}
    if activation in ("swiglu", "geglu"):
        params["w_gate"] = jax.random.normal(k1, (E, H, I), jnp.float32) * std
        params["w_up"] = jax.random.normal(k3, (E, H, I), jnp.float32) * std
    else:
        params["w_in"] = jax.random.normal(k1, (E, H, I), jnp.float32) * std
        if use_bias:
            params["b_in"] = jnp.zeros((E, I))
    params["w_out"] = jax.random.normal(k2, (E, I, H), jnp.float32) * out_std
    if use_bias:
        params["b_out"] = jnp.zeros((E, H))
    return params


def apply_expert_ffn(params: Dict[str, Any], x: jnp.ndarray, activation: str = "gelu") -> jnp.ndarray:
    """[E, C, H] → [E, C, H]: each expert's FFN on its capacity slice.
    The batched ``x @ w`` contracts H per expert (einsum ``ech,ehi->eci``);
    ``qmatmul`` fuses int8-weight dequantization when the stacked leaves
    are quantized — its ``[E, 1, I]`` per-output-channel scales broadcast
    over the capacity dim — so MoE serving rides the same int8 weights as
    the dense path."""
    from deepspeed_tpu.compression.int8 import qmatmul

    dt = x.dtype
    if activation in ("swiglu", "geglu"):
        gate = qmatmul(x, params["w_gate"])
        up = qmatmul(x, params["w_up"])
        act = jax.nn.silu(gate) if activation == "swiglu" else jax.nn.gelu(gate)
        inner = act * up
    else:
        inner = qmatmul(x, params["w_in"])
        if "b_in" in params:
            inner = inner + params["b_in"][:, None, :].astype(dt)
        inner = _pointwise_activation(inner, activation)
    out = qmatmul(inner, params["w_out"]).astype(dt)
    if "b_out" in params:
        out = out + params["b_out"][:, None, :].astype(dt)
    return out


def expert_partition_rules(params: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpecs putting the stacked expert dim on the ``expert`` mesh
    axis (the reference's expert-parallel group, groups.py:113); remaining
    dims left for the ZeRO partitioner / TP to extend."""
    return jax.tree_util.tree_map(
        lambda p: P(*(("expert",) + (None,) * (np.ndim(p) - 1))), params
    )
