"""Top-k gating + capacity-based dispatch (TPU-native MoE core).

Counterpart of the reference's ``deepspeed/moe/sharded_moe.py`` (``top1gating``
:193, ``top2gating`` :290, ``MOELayer`` :435). The reference dispatches with
einsums and an explicit ``_AllToAll`` autograd function over the
expert-parallel process group (sharded_moe.py:98); here the dispatch/combine
einsums are identical, but the all-to-all is *implied*: the dispatched tensor
``[E, C, H]`` carries a sharding constraint putting dim 0 on the ``expert``
mesh axis while tokens arrive sharded over ``data`` — the XLA SPMD partitioner
inserts the all-to-all over ICI, and its inverse on combine. Differentiation
through the collective is automatic (no hand-written autograd function).

Everything is static-shaped for the MXU: capacity is a Python int derived
from token count, dropped tokens are masked (not ragged), and expert FFNs run
as one batched einsum over the stacked ``[E, ...]`` expert weights.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

uniform_map = None  # parity marker (reference caches torch.distributions here)


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float, min_capacity: int) -> int:
    """Static tokens-per-expert capacity (reference sharded_moe.py:85)."""
    capacity = math.ceil(num_tokens / num_experts * capacity_factor)
    return max(capacity, min_capacity)


def multiplicative_jitter(x, rng, epsilon: float = 1e-2):
    """'Jitter' noisy gate policy (reference sharded_moe.py:106)."""
    if epsilon == 0 or rng is None:
        return x
    noise = jax.random.uniform(
        rng, x.shape, dtype=jnp.float32, minval=1.0 - epsilon, maxval=1.0 + epsilon
    )
    return x * noise.astype(x.dtype)


def gumbel_rsample(shape, rng):
    return jax.random.gumbel(rng, shape, dtype=jnp.float32)


def _one_hot(indices, num_classes):
    return jax.nn.one_hot(indices, num_classes, dtype=jnp.float32)


def _priority_locations(mask: jnp.ndarray, rng: Optional[jax.Array], use_rts: bool) -> jnp.ndarray:
    """Position of each token within its expert's queue, [S, E].

    Default priority is sequence order (cumsum). With Random Token Selection
    (``use_rts``, reference sharded_moe.py top1gating RTS branch) tokens are
    ranked by a random permutation so capacity drops are unbiased instead of
    biased against late positions.
    """
    S = mask.shape[0]
    if use_rts and rng is not None:
        perm = jax.random.permutation(rng, S)
        inv = jnp.argsort(perm)
        permuted = mask[perm]
        locations = (jnp.cumsum(permuted, axis=0) - permuted)[inv]
    else:
        locations = jnp.cumsum(mask, axis=0) - mask
    return locations


def top1gating(
    logits: jnp.ndarray,
    capacity_factor: float,
    min_capacity: int,
    used_token_mask: Optional[jnp.ndarray] = None,
    noisy_gate_policy: Optional[str] = None,
    drop_tokens: bool = True,
    use_rts: bool = True,
    rng: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-1 gating (reference ``top1gating`` sharded_moe.py:193).

    Args: ``logits`` [S, E] raw gate scores.
    Returns ``(l_aux, combine_weights [S,E,C], dispatch_mask [S,E,C], exp_counts [E])``.
    """
    S, E = logits.shape
    capacity = _capacity(S, E, capacity_factor, min_capacity)
    if not drop_tokens:
        capacity = S  # every token fits; no drops (reference drop_tokens=False path)

    logits32 = logits.astype(jnp.float32)
    if noisy_gate_policy == "RSample" and rng is not None:
        rng, sub = jax.random.split(rng)
        logits_w_noise = logits32 + gumbel_rsample(logits32.shape, sub)
    else:
        logits_w_noise = logits32

    gates = jax.nn.softmax(logits32, axis=1)
    indices1 = jnp.argmax(logits_w_noise, axis=1)
    mask1 = _one_hot(indices1, E)
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None].astype(mask1.dtype)

    # load-balance aux loss: E * <fraction routed> . <mean gate prob>
    # (reference sharded_moe.py l_aux = num_experts * sum(me * ce))
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)

    rng_rts = None
    if rng is not None:
        rng, rng_rts = jax.random.split(rng)
    locations1 = _priority_locations(mask1, rng_rts, use_rts and drop_tokens)
    mask1 = mask1 * (locations1 < capacity).astype(mask1.dtype)
    locations1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)

    gates1_s = jnp.sum(gates * mask1, axis=1)  # gate prob of kept assignment
    locations1_sc = _one_hot(locations1_s, capacity) * jnp.sum(mask1, axis=1, keepdims=True)
    combine_weights = gates1_s[:, None, None] * mask1[:, :, None] * locations1_sc[:, None, :]
    dispatch_mask = combine_weights > 0
    return l_aux, combine_weights, dispatch_mask, exp_counts


def top2gating(
    logits: jnp.ndarray,
    capacity_factor: float,
    min_capacity: int,
    drop_tokens: bool = True,
    top2_2nd_expert_sampling: bool = True,
    rng: Optional[jax.Array] = None,
    used_token_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-2 gating (reference ``top2gating`` sharded_moe.py:290)."""
    S, E = logits.shape
    capacity = _capacity(S, E, capacity_factor * 2.0, min_capacity)
    if not drop_tokens:
        capacity = S

    logits32 = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits32, axis=1)

    indices1 = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1, E)
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None].astype(mask1.dtype)

    second_logits = logits32
    if top2_2nd_expert_sampling and rng is not None:
        rng, sub = jax.random.split(rng)
        second_logits = logits32 + gumbel_rsample(logits32.shape, sub)
    masked_second = jnp.where(mask1 > 0, -jnp.inf, second_logits)
    indices2 = jnp.argmax(masked_second, axis=1)
    mask2 = _one_hot(indices2, E)
    if used_token_mask is not None:
        mask2 = mask2 * used_token_mask[:, None].astype(mask2.dtype)

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    # second choices queue behind all first choices (reference :321)
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    exp_counts = jnp.sum(mask1 + mask2, axis=0).astype(jnp.int32)

    mask1 = mask1 * (locations1 < capacity).astype(mask1.dtype)
    mask2 = mask2 * (locations2 < capacity).astype(mask2.dtype)
    locations1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)
    locations2_s = jnp.sum(locations2 * mask2, axis=1).astype(jnp.int32)

    gates1_s = jnp.sum(gates * mask1, axis=1)
    gates2_s = jnp.sum(gates * mask2, axis=1)
    denom = jnp.clip(gates1_s + gates2_s, min=jnp.finfo(jnp.float32).eps)
    gates1_s = gates1_s / denom
    gates2_s = gates2_s / denom

    locations1_sc = _one_hot(locations1_s, capacity) * jnp.sum(mask1, axis=1, keepdims=True)
    locations2_sc = _one_hot(locations2_s, capacity) * jnp.sum(mask2, axis=1, keepdims=True)
    combine1 = gates1_s[:, None, None] * mask1[:, :, None] * locations1_sc[:, None, :]
    combine2 = gates2_s[:, None, None] * mask2[:, :, None] * locations2_sc[:, None, :]
    combine_weights = combine1 + combine2
    dispatch_mask = combine_weights > 0
    return l_aux, combine_weights, dispatch_mask, exp_counts


def topkgating(
    logits: jnp.ndarray,
    k: int,
    capacity_factor: float,
    min_capacity: int,
    drop_tokens: bool = True,
    rng: Optional[jax.Array] = None,
    noisy_gate_policy: Optional[str] = None,
    use_rts: bool = True,
    used_token_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dispatch to the k-specific gate (reference TopKGate.forward :407)."""
    if k == 1:
        return top1gating(
            logits,
            capacity_factor,
            min_capacity,
            used_token_mask=used_token_mask,
            noisy_gate_policy=noisy_gate_policy,
            drop_tokens=drop_tokens,
            use_rts=use_rts,
            rng=rng,
        )
    if k == 2:
        # noisy_gate_policy maps onto top-2's 2nd-expert Gumbel sampling
        # (reference top2gating has no RSample/Jitter branch either)
        return top2gating(
            logits,
            capacity_factor,
            min_capacity,
            drop_tokens=drop_tokens,
            rng=rng,
            used_token_mask=used_token_mask,
            top2_2nd_expert_sampling=rng is not None,
        )
    raise ValueError(f"Only top-1 and top-2 gating are supported (got k={k})")


def dispatch(tokens: jnp.ndarray, dispatch_mask: jnp.ndarray) -> jnp.ndarray:
    """[S, H] tokens → [E, C, H] expert inputs (reference einsum "sec,sm->ecm"
    sharded_moe.py:476)."""
    return jnp.einsum("sec,sh->ech", dispatch_mask.astype(tokens.dtype), tokens)


def combine(expert_out: jnp.ndarray, combine_weights: jnp.ndarray) -> jnp.ndarray:
    """[E, C, H] expert outputs → [S, H] (reference einsum "sec,ecm->sm" :497)."""
    return jnp.einsum("sec,ech->sh", combine_weights.astype(expert_out.dtype), expert_out)
