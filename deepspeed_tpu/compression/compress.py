"""Compression entry points.

Counterpart of the reference's ``deepspeed/compression/compress.py``
(``init_compression`` :100, ``redundancy_clean`` :148,
``student_initialization`` :192). Functional translation:

* ``init_compression(model, config)`` wraps a DSModule so the configured
  transforms (QAT weight quantization, pruning masks) apply to matching
  param leaves during every forward — training sees compressed weights,
  gradients flow straight-through;
* ``redundancy_clean(params, config)`` bakes the masks/quantization into the
  stored parameters (the reference's post-training cleanup);
* module matching uses the reference's config shape: per-method blocks with
  ``modules`` name patterns (here: path regexes over the param tree).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.basic_layer import (
    head_pruning_mask,
    quantize_weight,
    row_pruning_mask,
    sparse_pruning_mask,
)
from deepspeed_tpu.runtime.module import DSModule, wrap_module
from deepspeed_tpu.utils.logging import logger

WEIGHT_QUANTIZATION = "weight_quantization"
ACTIVATION_QUANTIZATION = "activation_quantization"
SPARSE_PRUNING = "sparse_pruning"
ROW_PRUNING = "row_pruning"
HEAD_PRUNING = "head_pruning"
CHANNEL_PRUNING = "channel_pruning"
LAYER_REDUCTION = "layer_reduction"

SHARED_PARAMETERS = "shared_parameters"
DIFFERENT_GROUPS = "different_groups"


def _method_specs(compression_config: Dict) -> List[Tuple[str, Dict, List[str]]]:
    """Flatten the reference's nested config into
    (method, params, module_patterns) rows. ``schedule_offset``(+``_end``)
    ride along in params — the staging the compression scheduler drives."""
    rows = []
    for method in (
        WEIGHT_QUANTIZATION,
        ACTIVATION_QUANTIZATION,
        SPARSE_PRUNING,
        ROW_PRUNING,
        HEAD_PRUNING,
        CHANNEL_PRUNING,
    ):
        block = compression_config.get(method)
        if not block:
            continue
        shared = block.get(SHARED_PARAMETERS, {})
        if not shared.get("enabled", False):
            continue
        for group_name, group in block.get(DIFFERENT_GROUPS, {}).items():
            params = dict(shared)
            params.update(group.get("params", {}))
            modules = group.get("modules", ["*"])
            rows.append((method, params, modules))
    return rows


def _row_active(params: Dict, step: int) -> bool:
    """A method group is live once training reaches its schedule_offset and
    (when set) until schedule_offset_end (reference scheduler semantics)."""
    start = int(params.get("schedule_offset", 0) or 0)
    end = int(params.get("schedule_offset_end", 0) or 0)
    if step < start:
        return False
    if end and step > end:
        return False
    return True


def _pattern_to_regex(pat: str) -> str:
    return "^" + re.escape(pat).replace(r"\*", ".*") + "$"


def _matches(path: str, patterns: List[str]) -> bool:
    return any(re.match(_pattern_to_regex(p), path) for p in patterns)


def _transform_leaf(method: str, params: Dict, w: jnp.ndarray) -> jnp.ndarray:
    if method == WEIGHT_QUANTIZATION:
        bits = params.get("start_bits", params.get("quantize_weight_in_forward", 8))
        if isinstance(bits, bool):
            bits = 8
        return quantize_weight(w, bits=int(bits), num_groups=int(params.get("quantize_groups", 1)))
    if method == SPARSE_PRUNING:
        return w * sparse_pruning_mask(w, float(params.get("dense_ratio", 0.5)))
    if method == ROW_PRUNING:
        return w * row_pruning_mask(w, float(params.get("dense_ratio", 0.5)))
    if method == CHANNEL_PRUNING:
        from deepspeed_tpu.compression.basic_layer import channel_pruning_mask

        return w * channel_pruning_mask(w, float(params.get("dense_ratio", 0.5)))
    if method == HEAD_PRUNING:
        return w * head_pruning_mask(
            w, float(params.get("dense_ratio", 0.5)), int(params.get("num_heads", 1))
        )
    return w


class CompressedModule(DSModule):
    """DSModule wrapper applying compression transforms each forward."""

    def __init__(self, inner: DSModule, compression_config: Dict):
        self.inner = inner
        self.rows = _method_specs(compression_config)
        self.enabled_methods = {m for m, _, _ in self.rows}
        # staging: methods activate at their schedule_offset. active_rows is
        # read at TRACE time — direct apply() picks a flip up immediately,
        # but an engine's cached step needs the CompressionScheduler(engine=)
        # edge-triggered rebuild to see it
        self._step = 0
        logger.info(
            f"init_compression: {len(self.rows)} group(s), methods={sorted(self.enabled_methods)}"
        )

    def set_step(self, step: int) -> None:
        self._step = int(step)

    def active_rows(self):
        return [r for r in self.rows if _row_active(r[1], self._step)]

    def _compress(self, params):
        # weight-leaf transforms only; activation_quantization rows are
        # delivered through the trace-time scope in apply()
        rows = [r for r in self.active_rows() if r[0] != ACTIVATION_QUANTIZATION]

        def walk(prefix, tree):
            if isinstance(tree, dict):
                return {k: walk(f"{prefix}/{k}" if prefix else k, v) for k, v in tree.items()}
            if isinstance(tree, (list, tuple)):
                return type(tree)(walk(f"{prefix}/{i}", v) for i, v in enumerate(tree))
            w = tree
            if jnp.ndim(w) < 2:
                return w  # biases/norms stay exact (reference behavior)
            for method, p, patterns in rows:
                if _matches(prefix, patterns):
                    w = _transform_leaf(method, p, w)
            return w

        return walk("", params)

    def init(self, rng, batch):
        return self.inner.init(rng, batch)

    def apply(self, params, batch, *, rngs=None, train: bool = True):
        from deepspeed_tpu.compression.act_quant import activation_quantization_scope

        act_rows = [
            (int(p.get("bits", p.get("start_bits", 8))), patterns)
            for method, p, patterns in self.active_rows()
            if method == ACTIVATION_QUANTIZATION
        ]
        with activation_quantization_scope(act_rows):
            return self.inner.apply(self._compress(params), batch, rngs=rngs, train=train)

    def tp_partition_rules(self, params_shapes=None):
        return self.inner.tp_partition_rules(params_shapes)

    def keep_fp32_params(self, params_shapes=None):
        return self.inner.keep_fp32_params(params_shapes)


def init_compression(model, deepspeed_config, teacher_model=None, mpu=None) -> DSModule:  # noqa: ARG001
    """(reference compress.py:100) Wrap the model so compression applies in
    the forward; pass the wrapped module to ``deepspeed.initialize``."""
    cfg = deepspeed_config
    if hasattr(cfg, "compression_config"):
        cfg = cfg.compression_config
    elif isinstance(cfg, dict):
        cfg = cfg.get("compression_training", cfg)
    module = wrap_module(model)
    return CompressedModule(module, cfg or {})


def redundancy_clean(params, deepspeed_config, mpu=None):  # noqa: ARG001
    """(reference compress.py:148) Bake the transforms into stored params —
    after this the plain (unwrapped) module reproduces compressed outputs."""
    cfg = deepspeed_config
    if isinstance(cfg, dict):
        cfg = cfg.get("compression_training", cfg)
    shim = CompressedModule(wrap_module(_IdentityModule()), cfg or {})
    return shim._compress(params)


class CompressionScheduler:
    """Drives the staging (reference ``compression_scheduler``): call
    ``step(global_step)`` each optimizer step; the wrapped module's method
    groups activate/deactivate per their schedule_offset windows.

    Pass the TRAINING ENGINE too when the module is driven through
    ``deepspeed.initialize``: the engine's step programs are traced once,
    and ``active_rows`` is read at trace time — without a retrace a
    mid-training activation would never reach the compiled forward. The
    scheduler detects the activation edge and rebuilds the engine's jitted
    step exactly once per flip."""

    def __init__(self, module: "CompressedModule", engine=None):
        if not isinstance(module, CompressedModule):
            raise TypeError("CompressionScheduler wraps a CompressedModule")
        self.module = module
        self.engine = engine

    def step(self, global_step: int) -> None:
        if self.engine is None:
            self.module.set_step(global_step)
            return
        before = self.module.active_rows()
        self.module.set_step(global_step)
        if self.module.active_rows() != before:
            self.engine.invalidate_compiled_step()

    def active_methods(self):
        return sorted({m for m, _, _ in self.module.active_rows()})


def _get_by_path(tree, path: str):
    node = tree
    for part in path.split("."):
        node = node[part]
    return node


def _set_by_path(tree, path: str, value):
    parts = path.split(".")
    node = tree
    for part in parts[:-1]:
        node = node[part]
    node[parts[-1]] = value


def student_initialization(student_params, teacher_params, deepspeed_config):
    """(reference compress.py:192) Layer-reduction distillation init.

    With ``compression_training.layer_reduction`` configured, the student's
    stacked layer tree is built from the teacher's selected layers
    (``teacher_layer``, e.g. [1,3,5,7] initializes a 4-layer student from
    alternating teacher layers) and the subtrees named in
    ``other_module_name`` (dot paths, e.g. "embed") copy over whole.
    Without the config: shape-matched leaves copy (the generic warm start).
    """
    import numpy as np

    cfg = deepspeed_config
    if isinstance(cfg, dict):
        cfg = cfg.get("compression_training", cfg)
    lr_cfg = (cfg or {}).get(LAYER_REDUCTION, {})
    if lr_cfg.get("enabled", False):
        teacher_layer = list(lr_cfg["teacher_layer"])
        prefix = lr_cfg.get("module_name_prefix", "layers")
        others = lr_cfg.get("other_module_name", [])
        out = jax.tree_util.tree_map(lambda s: s, student_params)  # copy structure
        t_layers = _get_by_path(teacher_params, prefix)
        s_layers = _get_by_path(student_params, prefix)
        n_student = jax.tree_util.tree_leaves(s_layers)[0].shape[0]
        if len(teacher_layer) != n_student:
            raise ValueError(
                f"teacher_layer selects {len(teacher_layer)} layers but the "
                f"student has {n_student}"
            )
        sel = np.asarray(teacher_layer)
        _set_by_path(
            out, prefix, jax.tree_util.tree_map(lambda a: jnp.asarray(a)[sel], t_layers)
        )
        for name in others:
            _set_by_path(out, name, _get_by_path(teacher_params, name))
        return out

    def walk(s, t):
        if isinstance(s, dict):
            return {k: walk(s[k], t.get(k, s[k])) if isinstance(t, dict) else s[k] for k in s}
        if hasattr(s, "shape") and hasattr(t, "shape") and s.shape == t.shape:
            return t
        return s

    return walk(student_params, teacher_params)


class _IdentityModule(DSModule):
    def init(self, rng, batch):  # noqa: ARG002
        return {}

    def apply(self, params, batch, *, rngs=None, train=True):  # noqa: ARG002
        return batch
