"""Int8 weight quantization for sharded serving (ISSUE 13).

DeepSpeed-Inference (PAPERS.md, arXiv 2207.00032) serves large models with
int8 weights and fp accumulation: HBM (and, under tensor parallelism, the
weight-shard footprint per chip) drops 2-4x while the matmul epilogue
dequantizes at no extra memory traffic. The TPU-native translation here:

* a weight is stored as a :class:`QuantizedTensor` — int8 codes in the
  weight's own shape plus **per-output-channel** fp32 scales (``keepdims``
  on the contraction axis, so a stacked ``[L, in, out]`` layer weight
  scans exactly like its unquantized form);
* ``scale = max|w| / 127`` per output channel, ``q = round(w / scale)`` —
  the roundtrip error is elementwise ``|w - q*scale| <= scale / 2
  = max|w_channel| / 254`` (the documented tolerance bound the int8
  serving tests assert);
* :func:`qmatmul` fuses dequantization into the matmul epilogue:
  ``(h @ q) * scale`` — one multiply per output element, never a
  materialized dequantized copy of the weight. Because the scales are
  per **output** channel they commute with a tensor-parallel row split:
  each chip's partial sum is already scaled, so partials add (and
  all-reduce) correctly without touching the scales.

``QuantizedTensor`` is a NamedTuple and therefore a pytree: quantized
param trees flow through ``jax.device_put`` / ``shard_map`` specs like any
other tree (``inference/tp.py`` emits a matching spec pair per quantized
leaf — codes shard like the weight, scales follow the output channels).
"""

from __future__ import annotations

from typing import Any, Dict, FrozenSet, NamedTuple

import jax
import jax.numpy as jnp

# the matmul weights of the flagship serving layout (models/transformer.py
# param names): attention projections, FFN, and the LM head. Embeddings
# stay exact — their use is a gather, and a tied head would silently
# quantize the logits path twice.
DEFAULT_QUANT_LEAVES: FrozenSet[str] = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_in", "w_out", "lm_head"}
)

_SCALE_FLOOR = 1e-30  # an all-zero channel must not divide by zero


class QuantizedTensor(NamedTuple):
    """Int8 weight codes + per-output-channel fp32 scales.

    ``q`` has the original weight's shape; ``scale`` keeps the contraction
    (second-to-last) axis as a singleton so both leaves slice identically
    under a leading scan/stack dim."""

    q: jax.Array  # int8, the weight's shape
    scale: jax.Array  # float32, weight shape with axis -2 reduced to 1

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def quantize_weight_int8(w) -> QuantizedTensor:
    """Per-output-channel symmetric int8 quantization of a matmul weight
    ``[..., in, out]``. Elementwise roundtrip error is bounded by
    ``scale/2 = max|w_channel|/254``."""
    w = jnp.asarray(w)
    if w.ndim < 2:
        raise ValueError(f"quantize_weight_int8 needs a matmul weight, got ndim {w.ndim}")
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True) / 127.0
    scale = jnp.maximum(scale, _SCALE_FLOOR)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale)


def dequantize(w: QuantizedTensor, dtype=jnp.float32):
    return (w.q.astype(jnp.float32) * w.scale).astype(dtype)


def qmatmul(h, w):
    """``h @ w`` with dequantization fused into the epilogue when ``w`` is
    quantized — the one matmul entry every serving projection site goes
    through, so int8 weights ride the same programs as fp weights. Plain
    arrays take the exact path the call sites used before."""
    if isinstance(w, QuantizedTensor):
        out = h @ w.q.astype(h.dtype)
        # scale is [..., 1, out]; the product lost the contraction axis.
        # Batched weight stacks (the [E, H, I] expert FFNs) keep the
        # size-1 axis so the scale broadcasts over the capacity dim.
        scale = w.scale.astype(h.dtype)
        if w.q.ndim == 2:
            scale = scale[..., 0, :]
        return out * scale
    return h @ w.astype(h.dtype)


def slice_out_channels(w, start: int, size: int):
    """Slice a weight's output-channel (last) axis — the tensor-parallel
    chunked row-matmul splits its all-reduces along it. Quantized weights
    slice codes and scales in lockstep."""
    if isinstance(w, QuantizedTensor):
        return QuantizedTensor(
            q=jax.lax.slice_in_dim(w.q, start, start + size, axis=-1),
            scale=jax.lax.slice_in_dim(w.scale, start, start + size, axis=-1),
        )
    return jax.lax.slice_in_dim(w, start, start + size, axis=-1)


def quantize_params_int8(params: Any, leaves: FrozenSet[str] = DEFAULT_QUANT_LEAVES) -> Any:
    """Quantize the named matmul weights of a serving param tree to int8
    (everything else — embeddings, norms, biases — stays exact). Quantize
    BEFORE tensor-parallel sharding: the per-output-channel scales are
    then global, so row-parallel partial sums dequantize consistently on
    every chip."""

    def walk(tree):
        if isinstance(tree, QuantizedTensor):
            return tree  # already quantized
        if isinstance(tree, dict):
            out: Dict[str, Any] = {}
            for k, v in tree.items():
                if (
                    k in leaves
                    and not isinstance(v, (dict, list, tuple))
                    and jnp.ndim(v) >= 2
                    and jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                ):
                    out[k] = quantize_weight_int8(v)
                else:
                    out[k] = walk(v)
            return out
        if isinstance(tree, (list, tuple)):
            return type(tree)(walk(v) for v in tree)
        return tree

    return walk(params)
