"""Compression (reference: ``deepspeed/compression/``)."""

from deepspeed_tpu.compression.compress import (
    CompressionScheduler,
    init_compression,
    redundancy_clean,
    student_initialization,
)
from deepspeed_tpu.compression.basic_layer import (
    head_pruning_mask,
    quantize_activation,
    quantize_weight,
    row_pruning_mask,
    sparse_pruning_mask,
)
