"""Compression (reference: ``deepspeed/compression/``)."""

from deepspeed_tpu.compression.compress import (
    CompressionScheduler,
    init_compression,
    redundancy_clean,
    student_initialization,
)
from deepspeed_tpu.compression.basic_layer import (
    head_pruning_mask,
    quantize_activation,
    quantize_weight,
    row_pruning_mask,
    sparse_pruning_mask,
)
from deepspeed_tpu.compression.int8 import (
    QuantizedTensor,
    dequantize,
    qmatmul,
    quantize_params_int8,
    quantize_weight_int8,
)
