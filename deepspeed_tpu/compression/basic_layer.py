"""Compression primitives.

Counterpart of the reference's ``deepspeed/compression/basic_layer.py``
(``LinearLayer_Compress`` :121 and friends). The reference swaps nn.Modules
for compressed variants; with functional params the same transforms are
pure functions applied to weight leaves inside the forward:

* weight/activation quantization-aware training → ``fake_quantize`` with a
  straight-through gradient (``ops/quantizer``);
* sparse / row / column / head pruning → masks derived from weight magnitude
  at a configured ratio, applied multiplicatively.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer import fake_quantize


def quantize_weight(w: jnp.ndarray, bits: int = 8, num_groups: int = 1) -> jnp.ndarray:
    """QAT weight transform (reference ``weight_quantization``)."""
    groups = num_groups
    if w.size % groups != 0:
        groups = 1
    return fake_quantize(w, num_groups=groups, num_bits=bits)


def quantize_activation(x: jnp.ndarray, bits: int = 8) -> jnp.ndarray:
    """QAT activation transform (reference ``activation_quantization``);
    per-tensor (one group per leading index) to keep scales cheap."""
    groups = x.shape[0] if x.ndim > 1 else 1
    return fake_quantize(x, num_groups=groups, num_bits=bits)


def sparse_pruning_mask(w: jnp.ndarray, ratio: float, method: str = "l1") -> jnp.ndarray:
    """Unstructured magnitude mask keeping the top (1-ratio) fraction
    (reference ``sparse_pruning`` with method l1/topk)."""
    if method not in ("l1", "topk"):
        raise ValueError(f"unsupported sparse pruning method {method!r}")
    k = max(1, int(round(w.size * (1.0 - ratio))))
    flat = jnp.abs(w).reshape(-1)
    threshold = jnp.sort(flat)[-k]
    return (jnp.abs(w) >= threshold).astype(w.dtype)


def row_pruning_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Structured row mask by row L1 norm (reference ``row_pruning``);
    rows = output features of a [in, out] matmul weight → mask dim -1."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
    k = max(1, int(round(norms.shape[0] * (1.0 - ratio))))
    threshold = jnp.sort(norms)[-k]
    mask = (norms >= threshold).astype(w.dtype)
    return jnp.broadcast_to(mask, w.shape)


def channel_pruning_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Structured input-channel mask (reference ``channel_pruning``):
    mask dim -2 (input features)."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(i for i in range(w.ndim) if i != w.ndim - 2))
    k = max(1, int(round(norms.shape[0] * (1.0 - ratio))))
    threshold = jnp.sort(norms)[-k]
    return jnp.broadcast_to(mask_expand(mask := (norms >= threshold).astype(w.dtype), w.ndim, w.ndim - 2), w.shape)


def head_pruning_mask(w: jnp.ndarray, ratio: float, num_heads: int) -> jnp.ndarray:
    """Attention-head mask on an output-projection weight [NH*D, H]
    (reference ``head_pruning``): per-head L1 over the input dim."""
    in_dim = w.shape[0]
    head_dim = in_dim // num_heads
    per_head = jnp.sum(jnp.abs(w.reshape(num_heads, head_dim, -1)), axis=(1, 2))
    k = max(1, int(round(num_heads * (1.0 - ratio))))
    threshold = jnp.sort(per_head)[-k]
    head_mask = (per_head >= threshold).astype(w.dtype)
    return jnp.repeat(head_mask, head_dim)[:, None] * jnp.ones_like(w)


def mask_expand(mask: jnp.ndarray, ndim: int, axis: int) -> jnp.ndarray:
    shape = [1] * ndim
    shape[axis] = mask.shape[0]
    return mask.reshape(shape)
