"""Activation-quantization runtime scope.

Counterpart of the reference's activation path in
``deepspeed/compression/basic_layer.py`` (``LinearLayer_Compress.forward``
quantizes the INPUT of each compressed linear when
``activation_quantization`` is enabled via ``compress.py:100``).

With functional models there is no nn.Module boundary to wrap, so the
transform is delivered through a trace-time scope: ``CompressedModule.apply``
enters :func:`activation_quantization_scope` with the active config rows, and
model forwards call :func:`maybe_quantize` at their linear-input sites
(``TransformerLM._layer``: ``layers/attn_input`` and ``layers/mlp_input``).
The scope is read while JAX traces the forward, so the quantization is baked
into the compiled program — zero overhead when disabled.

Only dynamic (per-call scale) quantization is implemented — the natural fit
for a traced program; the reference's static-range calibration would need
threaded calibration state.
"""

from __future__ import annotations

import re
from contextlib import contextmanager
from typing import List, Tuple

import jax.numpy as jnp

from deepspeed_tpu.compression.basic_layer import quantize_activation

# (bits, site_patterns) rows active for the current trace; module-level is
# correct here because entry/exit bracket a single (traced) forward call.
_ACTIVE: List[Tuple[int, List[str]]] = []


def _site_matches(site: str, patterns: List[str]) -> bool:
    for pat in patterns:
        if re.match("^" + re.escape(pat).replace(r"\*", ".*") + "$", site):
            return True
    return False


@contextmanager
def activation_quantization_scope(rows: List[Tuple[int, List[str]]]):
    """``rows``: (bits, module_patterns) for each active config group."""
    _ACTIVE.extend(rows)
    try:
        yield
    finally:
        del _ACTIVE[len(_ACTIVE) - len(rows):]


def maybe_quantize(x: jnp.ndarray, site: str) -> jnp.ndarray:
    """Fake-quantize ``x`` (straight-through gradient) if any active row's
    patterns match ``site``; identity otherwise. Model forwards call this at
    their linear-input sites."""
    for bits, patterns in _ACTIVE:
        if _site_matches(site, patterns):
            return quantize_activation(x, bits=bits)
    return x


def is_active() -> bool:
    return bool(_ACTIVE)
