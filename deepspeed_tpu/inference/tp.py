"""Tensor-parallel serving context (ISSUE 13): multi-chip sharded ragged
serving on the mesh.

One :class:`TPServing` object carries everything the serving program
builders (``inference/decode.py:build_ragged_step`` /
``build_ragged_multistep``) need to run the SAME ragged step body across a
``model``-axis mesh under ``shard_map``:

* **weight sharding** — the reference AutoTP / ``SpecLayout`` fsdp×tp
  pattern specialised to the serving layout (``module_inject/auto_tp.py``
  sketches the map): column-parallel q/k/v/gate/up (output features =
  heads shard, so the contiguous slice each chip holds is a contiguous
  block of heads), row-parallel o/down (input features shard; the partial
  sums meet in the per-layer all-reduces), vocab-column-parallel LM head
  (greedy argmax resolves globally in-program), everything else —
  embeddings, norms, row biases — replicated. Int8-quantized weights
  (``compression/int8.py``) shard code-and-scale in lockstep.
* **KV sharding over the kv-head axis** — the paged pools
  ``[L, NP, NKV, P, D]`` shard axis 2 only. Page *tables* stay host-side
  numpy and replicated, so ``PagePool`` (free lists, refcounts, prefix
  index, CoW, journal, fleet router) is completely untouched: only the
  page CONTENTS shard, and each chip's attention kernel sees the local
  ``NKV/tp`` heads of every page through the same table.
* **explicit TP collectives** — the row-parallel projections all-reduce
  their partial sums per layer. ``comm_chunks`` splits each projection's
  output features so chunk ``j``'s all-reduce overlaps chunk ``j+1``'s
  matmul (the static ``overlap`` pass verifies every loop collective has
  independent MXU work to hide behind). ``quantized_allreduce`` swaps the
  fp ``psum`` for the EQuARX-style quantized exchange (PAPERS.md,
  arXiv 2506.17615): int8 all-to-all → local fp32 reduce → int8
  all-gather — 4x fewer bytes on the wire per phase at a bounded
  quantization error (two symmetric int8 stages ≈ 1% relative), so the
  decode-critical-path comm cost drops to ``fp_bytes / 4`` (the
  ``collectives`` pass accounts it by wire dtype).

The context is **host-constructed and trace-time-consumed**: building one
allocates nothing on device; ``shard_params`` places the weights once and
``shard_program`` wraps a step body so the scheduler's dispatch path is
byte-for-byte the single-chip one (same program names, same ≤2-program
budget, same one-fetch-per-step contract).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.compression.int8 import QuantizedTensor, qmatmul, slice_out_channels
from deepspeed_tpu.utils.jax_compat import mesh_fingerprint, shard_map

# serving-layout classification (models/transformer.py param names; the
# AutoTP walk in module_inject/auto_tp.py generalizes the same policy)
_COLUMN = frozenset({"wq", "wk", "wv", "w_gate", "w_up", "w_in"})
_ROW = frozenset({"wo", "w_out"})
_COLUMN_BIAS = frozenset({"bq", "bk", "bv", "b_in"})


def serving_mesh(tp_degree: int, devices=None, axis: str = "model") -> Mesh:
    """A compact 1-D ``(axis,)`` mesh over the first ``tp_degree`` devices
    — one tensor-parallel serving group. Replication across groups is the
    fleet layer's job (``inference/fleet.py``), not this mesh's."""
    devices = list(devices if devices is not None else jax.devices())
    if tp_degree < 1:
        raise ValueError(f"tp_degree must be >= 1, got {tp_degree}")
    if len(devices) < tp_degree:
        raise ValueError(
            f"tp_degree={tp_degree} needs at least that many devices, "
            f"have {len(devices)}"
        )
    return Mesh(np.asarray(devices[:tp_degree]), (axis,))


def quantized_all_reduce(x, axis: str, degree: int):
    """EQuARX-style quantized all-reduce over a shard_map axis: split the
    last dim into ``degree`` chunks, int8-quantize each chunk with its own
    scale, **all-to-all** so chip ``i`` holds every chip's chunk ``i``,
    dequantize + reduce locally in fp32, re-quantize the reduced chunk,
    and **all-gather** the results. Per phase the payload is int8 — the
    wire cost of the whole exchange is the fp ring all-reduce's ÷ 4 (the
    fp32 per-chunk scales ride as side-channel scalars). Falls back to a
    plain ``psum`` when the last dim does not split ``degree`` ways.

    Error model: two symmetric int8 stages, each elementwise-bounded by
    ``max|chunk| / 254`` — the serving contract under this knob is
    allclose, not byte-identical (README "Multi-chip serving")."""
    if degree == 1:
        return x
    shp = x.shape
    if shp[-1] % degree:
        return jax.lax.psum(x, axis)
    xs = jnp.moveaxis(
        x.reshape(shp[:-1] + (degree, shp[-1] // degree)), -2, 0
    )  # [tp, ..., F/tp]
    red = tuple(range(1, xs.ndim))
    s = jnp.max(jnp.abs(xs.astype(jnp.float32)), axis=red, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-30)
    q = jnp.clip(jnp.round(xs.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    q = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0)
    s = jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0)
    y = jnp.sum(q.astype(jnp.float32) * s, axis=0)  # local reduced chunk
    t = jnp.maximum(jnp.max(jnp.abs(y)) / 127.0, 1e-30)
    qy = jnp.clip(jnp.round(y / t), -127, 127).astype(jnp.int8)
    qg = jax.lax.all_gather(qy, axis)  # [tp, ..., F/tp]
    tg = jax.lax.all_gather(t, axis)  # [tp]
    yg = qg.astype(jnp.float32) * tg.reshape((degree,) + (1,) * (qg.ndim - 1))
    return jnp.moveaxis(yg, 0, -2).reshape(shp).astype(x.dtype)


class TPServing:
    """Tensor-parallel context for the paged serving programs.

    Construct from a mesh (``serving_mesh(tp)``) or a live
    :class:`~deepspeed_tpu.parallel.mesh.Topology`, call
    :meth:`shard_params` once (places the weights, records the spec tree),
    and hand the context to ``PagedServer(tp=...)`` — the scheduler passes
    it through to the program builders. ``degree == 1`` is a valid
    degenerate context (identity reduces), which the parity tests use to
    pin the shard_map-wrapped program against the plain oracle."""

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        axis: str = "model",
        quantized_allreduce: bool = False,
        comm_chunks: int = 2,
        topology=None,
    ):
        if mesh is None:
            if topology is None:
                from deepspeed_tpu.parallel.mesh import get_topology

                topology = get_topology()
            mesh = topology.mesh
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        self.degree = int(dict(zip(mesh.axis_names, mesh.devices.shape))[axis])
        self.quantized_allreduce = bool(quantized_allreduce)
        self.comm_chunks = max(1, int(comm_chunks))
        self.kv_spec = P(None, None, axis, None, None)
        # the TP context OWNS this sharding; the pool adopts it read-only
        # at construction (DS-R007 protects the POOL's copy from writers)
        self.kv_sharding = NamedSharding(mesh, self.kv_spec)  # lint: allow(DS-R007)
        self.param_specs = None  # set by shard_params
        self.head_sharded = False  # vocab-column-parallel LM head in play
        self.quantized_weights = False

    # --- identity (program-cache key component) --------------------------
    def cache_key(self):
        return (
            self.degree,
            self.axis,
            self.quantized_allreduce,
            self.comm_chunks,
            self.head_sharded,
            self.quantized_weights,
            mesh_fingerprint(self.mesh),
        )

    # --- config & weights ------------------------------------------------
    def validate_cfg(self, cfg) -> None:
        if cfg.num_heads % self.degree or cfg.num_kv_heads % self.degree:
            raise ValueError(
                f"tensor-parallel serving shards the head axes: num_heads="
                f"{cfg.num_heads} and num_kv_heads={cfg.num_kv_heads} must "
                f"both divide by tp={self.degree}"
            )

    def local_cfg(self, cfg):
        """The per-shard view of the model config inside shard_map: each
        chip computes ``NH/tp`` query heads against its ``NKV/tp`` kv-head
        slice of every page (hidden size, head_dim, and the GQA group size
        are unchanged)."""
        if self.degree == 1:
            return cfg
        return dataclasses.replace(
            cfg,
            num_heads=cfg.num_heads // self.degree,
            num_kv_heads=cfg.num_kv_heads // self.degree,
        )

    def _leaf_spec(self, name: str, leaf, cfg) -> Any:
        ndim = leaf.ndim if isinstance(leaf, QuantizedTensor) else jnp.ndim(leaf)
        axis = self.axis

        def wspec(kind):
            stacked = ndim == 3
            if kind == "col":
                return P(None, None, axis) if stacked else P(None, axis)
            if kind == "row":
                return P(None, axis, None) if stacked else P(axis, None)
            return P(*([None] * ndim))

        if name in _COLUMN:
            spec = wspec("col")
        elif name in _ROW:
            spec = wspec("row")
        elif name in _COLUMN_BIAS:
            spec = P(None, axis) if ndim == 2 else P(axis)
        elif name == "lm_head" and cfg.vocab_size % self.degree == 0:
            self.head_sharded = True
            spec = wspec("col")
        elif name == "lm_head_bias" and cfg.vocab_size % self.degree == 0:
            spec = P(axis)
        else:
            spec = P(*([None] * ndim))
        if isinstance(leaf, QuantizedTensor):
            self.quantized_weights = True
            # scales follow the OUTPUT channels: a column weight's scales
            # shard with it; a row weight's scales (full output width,
            # identical on every chip) replicate
            scale_entries = list(spec) + [None] * (ndim - len(spec))
            if ndim >= 2:
                scale_entries[-2] = None  # the keepdims contraction axis
            return QuantizedTensor(q=spec, scale=P(*scale_entries))
        return spec

    def partition_specs(self, params, cfg):
        """PartitionSpec tree for the serving param layout (matches the
        params structure leaf-for-leaf, incl. QuantizedTensor pairs)."""

        def walk(name, tree):
            if isinstance(tree, QuantizedTensor):
                return self._leaf_spec(name, tree, cfg)
            if isinstance(tree, dict):
                return {k: walk(k, v) for k, v in tree.items()}
            if isinstance(tree, (list, tuple)):
                return type(tree)(walk(name, v) for v in tree)
            return self._leaf_spec(name, tree, cfg)

        return walk("", params)

    def shard_params(self, cfg, params):
        """Validate the config, compute the serving spec tree, and place
        the weights (one ``device_put``; already-sharded trees reshard).
        Must run before any program builds — the specs are baked into the
        shard_map wrapper."""
        if self.degree > 1:
            self.validate_cfg(cfg)
        specs = self.partition_specs(params, cfg)
        self.param_specs = specs
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        return jax.device_put(params, shardings)

    # --- declared comm/sharding contract (analysis memory pass) ----------
    def declared_collectives(self):
        """The collective op kinds the serving programs INTENTIONALLY
        contain, for the sharding auditor's undeclared-reshard check: the
        row-parallel fp path all-reduces partial sums; the quantized
        exchange swaps that for all-to-all + all-gather (psum fallback when
        a projection's last dim does not split); the vocab-sharded argmax
        all-gathers its (max, index) pairs. Anything else in a compiled
        serving module is a pjit-inserted reshard the engine never
        planned."""
        if self.degree == 1:
            return []
        ops = {"all-reduce"}
        if self.quantized_allreduce:
            ops |= {"all-to-all", "all-gather"}
        if self.head_sharded:
            ops.add("all-gather")
        return sorted(ops)

    def sharding_rules(self, min_bytes: int = 1 << 16):
        """Declared "these leaves shard" rules for the auditor: every
        column/row-parallel weight name (dict-key path match) plus the
        rank-5 ``[L, NP, NKV, P, D]`` page pools, which enter the serving
        programs positionally. A matching leaf ≥ ``min_bytes`` found fully
        replicated on the mesh is a red finding — per-chip HBM is paying
        the whole buffer the layout promised to split."""
        if self.degree == 1:
            return []
        names = set(_COLUMN) | set(_ROW)
        if self.head_sharded:
            names.add("lm_head")
        pattern = "|".join(sorted(names))
        return [
            {"pattern": f"\\['({pattern})'\\]", "min_bytes": int(min_bytes)},
            {"rank": 5, "pattern": "", "min_bytes": int(min_bytes)},
        ]

    # --- trace-time pieces (used inside the shard_map body) --------------
    def reduce(self, x):
        """Sum row-parallel partials across the tp axis (fp psum, or the
        quantized exchange under ``quantized_allreduce``)."""
        if self.degree == 1:
            return x
        if self.quantized_allreduce:
            return quantized_all_reduce(x, self.axis, self.degree)
        return jax.lax.psum(x, self.axis)

    def row_matmul(self, h, w):
        """Row-parallel projection: ``h_local @ w_local`` partial-summed
        across the axis. The output features split into ``comm_chunks``
        and each chunk's partial sum reduces independently — chunk j's
        collective has chunk j+1's matmul as dependency-free compute, the
        structure the ``overlap`` pass certifies as hidden."""
        F = (w.q if isinstance(w, QuantizedTensor) else w).shape[-1]
        C = self.comm_chunks if self.comm_chunks > 1 and F % self.comm_chunks == 0 else 1
        if C == 1:
            return self.reduce(qmatmul(h, w))
        step = F // C
        parts = [
            self.reduce(qmatmul(h, slice_out_channels(w, j * step, step)))
            for j in range(C)
        ]
        return jnp.concatenate(parts, axis=-1)

    def argmax(self, logits):
        """Greedy argmax over (possibly vocab-sharded) logits, exactly
        matching the single-chip ``jnp.argmax`` semantics: the FIRST
        global index achieving the max wins. Shards exchange only their
        local (max value, global index) pair — no logits gather."""
        if self.degree == 1 or not self.head_sharded:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        v_local = logits.shape[-1]
        loc = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        val = jnp.take_along_axis(logits, loc[..., None], axis=-1)[..., 0]
        idx = loc + jax.lax.axis_index(self.axis).astype(jnp.int32) * v_local
        vals = jax.lax.all_gather(val, self.axis)  # [tp, ...]
        idxs = jax.lax.all_gather(idx, self.axis)
        best = jnp.max(vals, axis=0)
        cand = jnp.where(vals == best, idxs, jnp.iinfo(jnp.int32).max)
        return jnp.min(cand, axis=0).astype(jnp.int32)

    def shard_program(self, f, n_args: int):
        """Wrap a serving step body for the mesh: params take the recorded
        spec tree, the two page pools shard on the kv-head axis, and every
        host-built array (tokens, page tables, lengths, q_lens, window
        masks) replicates. Outputs are the packed host fetch (replicated —
        every chip resolves the same tokens) plus the sharded pools, so
        the donated pages alias shard-for-shard."""
        if self.param_specs is None:
            raise RuntimeError("TPServing.shard_params must run before building programs")
        in_specs = (self.param_specs, P(), self.kv_spec, self.kv_spec) + (P(),) * (
            n_args - 4
        )
        return shard_map(
            f,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(P(), self.kv_spec, self.kv_spec),
            check_vma=False,
        )
