"""SLA-aware multi-tenant traffic layer over the paged serving scheduler.

Production serving is not one queue: millions of users arrive as unequal,
bursty, per-tenant request streams with different latency contracts. This
module layers tenancy on ``PagedServer`` (``inference/scheduler.py``)
through its ``SchedulingPolicy`` seam — the base server keeps its
token-exactness, one-dispatch-per-round, and preemption-recompute
contracts, and this layer decides only WHO goes next:

* ``TenantSpec`` — one tenant's contract: a **token budget weight** (its
  fair share of served tokens), a **priority class** (strictly ordered:
  higher admits first and is preempted last), TTFT/TPOT **SLA targets**
  (observability: attainment is reported, not enforced), and **admission
  control** caps (queue depth, live slots).
* ``SLAPolicy`` — the scheduling brain. Admission picks, among queued
  tenants (respecting live-slot caps), the highest priority class and
  within it the tenant with the smallest ``served_tokens / weight``
  (weighted deficit fairness — a backlogged tenant can be outrun but
  never starved: its deficit only falls while it is being served).
  Preemption victims are chosen lowest-priority-first, then
  most-over-budget, then youngest — the inverse of admission, so the
  requests evicted are exactly the ones fairness would admit last.
* ``MultiTenantServer`` — the front door: per-tenant ``submit`` with
  queue-cap rejection, delegation of the step loop, and
  ``serve_stats()`` extended with per-tenant budget shares, goodput
  shares, rejections, and SLA attainment.

Greedy output streams are byte-identical to single-tenant sharing-off
serving for the same request set: scheduling order changes WHEN a request
runs, never WHAT it generates (the recompute-preemption and prefix-cache
exactness contracts of the underlying server).

Multi-step windows (``paged_kv.multi_step``) ride underneath unchanged:
the base server only fuses N decode rounds into one dispatch when NOTHING
is queued and nothing is prefilling, so a tenant's pending admission
always breaks the window first (its TTFT is never parked behind a fused
window) and ``on_emit`` deficit accounting still sees every token — the
SLA policy is indifferent to whether tokens arrived one dispatch or N
dispatches at a time.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from deepspeed_tpu.inference.scheduler import (
    PagedServer,
    Request,
    SchedulingPolicy,
)


@dataclass
class TenantSpec:
    """One tenant's serving contract.

    ``weight`` is the tenant's token-budget share: over any backlogged
    interval it is entitled to ``weight / sum(weights of backlogged
    tenants)`` of the served tokens. ``priority`` classes are strict
    (higher wins admission and survives preemption longer) — use weights
    for proportional sharing inside a class, priorities for hard tiers.
    ``ttft_target_ms`` / ``tpot_target_ms`` define the SLA used for
    goodput and attainment reporting. ``max_queued`` / ``max_live_slots``
    are admission control: submissions beyond the queue cap are REJECTED
    (not silently queued forever), and live-slot caps stop one tenant from
    monopolizing the batch even when others are momentarily idle."""

    name: str
    weight: float = 1.0
    priority: int = 0
    ttft_target_ms: Optional[float] = None
    tpot_target_ms: Optional[float] = None
    max_queued: Optional[int] = None
    max_live_slots: Optional[int] = None


_DEFAULT_SPEC = TenantSpec(name="default")


class SLAPolicy(SchedulingPolicy):
    """Weighted-deficit + priority scheduling over ``PagedServer``'s
    policy hooks. Unknown tenants fall back to a weight-1 priority-0
    default spec, so the policy is always total.

    Served-token counters span CONTINUOUS backlog periods only (real
    WDRR semantics): a tenant entering the backlog joins at the current
    service floor (the least-served contender's normalized service), and
    a tenant whose work drains loses its counter. Tokens served while
    others were idle therefore never buy an unbounded catch-up window
    against a later arrival — the fairness horizon is the contention
    period, not process lifetime."""

    def __init__(self, tenants: Dict[str, TenantSpec]):
        self.tenants = dict(tenants)
        self.served: Dict[str, float] = {}
        self._backlogged: set = set()

    def _spec(self, name: str) -> TenantSpec:
        return self.tenants.get(name, _DEFAULT_SPEC)

    def _deficit(self, name: str) -> float:
        """Tokens served normalized by budget weight — smaller = more
        underserved. Admission minimizes it; preemption maximizes it."""
        return self.served.get(name, 0) / max(self._spec(name).weight, 1e-9)

    def _sync_backlog(self, queue: Sequence[Request], server) -> None:
        """Track idle<->backlogged transitions: newly backlogged tenants
        join at the current floor, drained tenants drop their counters."""
        current = {r.tenant for r in queue}
        if server is not None:
            current |= {r.tenant for r in server._active}
        newly = current - self._backlogged
        if newly:
            still = self._backlogged & current
            floor = min((self._deficit(t) for t in still), default=0.0)
            for t in newly:
                w = max(self._spec(t).weight, 1e-9)
                self.served[t] = max(self.served.get(t, 0.0), floor * w)
        for t in self._backlogged - current:
            self.served.pop(t, None)
        self._backlogged = current

    # --- hooks ----------------------------------------------------------
    def next_admission(self, queue: Sequence[Request], server: PagedServer):
        self._sync_backlog(queue, server)
        best = None
        best_key = None
        seen = set()
        for req in queue:  # queue order = FIFO within a tenant
            if req.tenant in seen:
                continue
            seen.add(req.tenant)
            spec = self._spec(req.tenant)
            if (
                spec.max_live_slots is not None
                and server.live_count(req.tenant) >= spec.max_live_slots
            ):
                continue
            key = (-spec.priority, self._deficit(req.tenant))
            if best is None or key < best_key:
                best, best_key = req, key
        return best

    def preemption_victim(
        self,
        candidates: Sequence[Request],
        server: PagedServer,
        for_req: Optional[Request] = None,
    ) -> Request:
        # lowest priority class first, most-over-budget tenant next,
        # youngest admission last — the exact inverse of admission order,
        # and always total (liveness: when the pool is dry SOMEONE yields,
        # even a high-priority request, rather than deadlocking)
        def badness(item):
            i, r = item
            spec = self._spec(r.tenant)
            return (spec.priority, -self._deficit(r.tenant), -i)

        return min(enumerate(candidates), key=badness)[1]

    def on_emit(self, req: Request, server: PagedServer) -> None:
        self.served[req.tenant] = self.served.get(req.tenant, 0) + 1


class MultiTenantServer:
    """Multi-tenant front over a ``PagedServer``: installs the
    ``SLAPolicy``, enforces per-tenant admission control at ``submit``,
    and reports per-tenant budget/goodput/SLA breakdowns.

    Compatible with the ``PagedServer`` surface the engine and the load
    harness drive (``submit`` / ``step`` / ``run`` / ``serve`` /
    ``has_work`` / ``result`` / ``serve_stats``)."""

    def __init__(
        self,
        server: PagedServer,
        tenants: Sequence[Union[TenantSpec, Dict]],
        default_tenant: str = "default",
    ):
        specs: Dict[str, TenantSpec] = {}
        for t in tenants or []:
            spec = t if isinstance(t, TenantSpec) else TenantSpec(**dict(t))
            specs[spec.name] = spec
        if default_tenant not in specs:
            specs[default_tenant] = TenantSpec(name=default_tenant)
        self.tenants = specs
        self.default_tenant = default_tenant
        self.server = server
        self.policy = SLAPolicy(specs)
        server.policy = self.policy
        self.rejected: Dict[str, int] = {name: 0 for name in specs}

    # --- intake with admission control ----------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        tenant: Optional[str] = None,
    ) -> Optional[int]:
        """Submit under a tenant's contract; returns the uid, or None when
        the tenant's queue cap rejects the request (overload shedding —
        the SLA answer to an unbounded queue is a fast no)."""
        tenant = tenant or self.default_tenant
        spec = self.tenants.get(tenant)
        if spec is None:
            raise KeyError(
                f"unknown tenant {tenant!r}: register it first "
                f"(known: {sorted(self.tenants)})"
            )
        if (
            spec.max_queued is not None
            and self.server.queued_count(tenant) >= spec.max_queued
        ):
            self.rejected[tenant] += 1
            return None
        return self.server.submit(
            prompt, max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            tenant=tenant,
        )

    def register_tenant(self, spec: Union[TenantSpec, Dict]) -> None:
        spec = spec if isinstance(spec, TenantSpec) else TenantSpec(**dict(spec))
        self.tenants[spec.name] = spec
        self.policy.tenants[spec.name] = spec
        self.rejected.setdefault(spec.name, 0)

    # --- step-loop delegation -------------------------------------------
    def step(self) -> None:
        self.server.step()

    def run(self):
        return self.server.run()

    def has_work(self) -> bool:
        return self.server.has_work()

    def result(self, uid: int):
        return self.server.result(uid)

    def take_result(self, uid: int):
        return self.server.take_result(uid)

    def queued_count(self, tenant: Optional[str] = None) -> int:
        return self.server.queued_count(tenant)

    def live_count(self, tenant: Optional[str] = None) -> int:
        return self.server.live_count(tenant)

    def recover(self, states, next_uid: int = 0, migrated_in: bool = False) -> int:
        # fleet migration / crash adoption lands on the wrapped server; the
        # SLA policy sees the re-queued requests through its normal hooks
        return self.server.recover(states, next_uid, migrated_in=migrated_in)

    def extract_request(self, uid: int):
        return self.server.extract_request(uid)

    def finalize_migration(self, uid: int) -> None:
        self.server.finalize_migration(uid)

    def finished_log(self):
        return self.server.finished_log()

    @property
    def pool(self):
        return self.server.pool

    @property
    def stats(self):
        return self.server.stats

    @property
    def tracer(self):
        # the unified tracing plane lives on the wrapped PagedServer (one
        # timeline per engine); the SLA layer adds no phases of its own
        return self.server.tracer

    @property
    def metrics(self):
        return self.server.metrics

    def serve(
        self,
        prompts: Sequence,
        max_new_tokens=32,
        eos_token_id: Optional[int] = None,
        tenant=None,
    ) -> List[Optional[np.ndarray]]:
        """Batch convenience: ``tenant`` is a name or a per-request list.
        Rejected submissions return None in their output position."""
        n = len(prompts)
        if isinstance(max_new_tokens, (int, np.integer)):
            max_new_tokens = [max_new_tokens] * n
        if tenant is None or isinstance(tenant, str):
            tenant = [tenant or self.default_tenant] * n
        if len(max_new_tokens) != n or len(tenant) != n:
            raise ValueError(
                f"{n} prompts but {len(max_new_tokens)} max_new_tokens / "
                f"{len(tenant)} tenants"
            )
        uids = [
            self.submit(p, max_new_tokens=int(m), eos_token_id=eos_token_id,
                        tenant=t)
            for p, m, t in zip(prompts, max_new_tokens, tenant)
        ]
        self.server.run()
        return [None if u is None else self.server.take_result(u) for u in uids]

    # --- observability ---------------------------------------------------
    def serve_stats(self) -> Dict:
        """The base server's stats (incl. the multi-step window block —
        ``window_steps`` / ``dispatches_per_token`` /
        ``window_break_reasons``) with per-tenant SLA/budget breakdowns:
        ``budget_share`` (weight over all configured weights),
        ``goodput_share`` (fraction of served tokens), ``rejected``, and
        TTFT/TPOT SLA attainment (fraction of finished requests meeting
        the tenant's target; None when no target is set)."""
        s = self.server.serve_stats()
        tenants = s.setdefault("tenants", {})
        total_weight = sum(t.weight for t in self.tenants.values()) or 1.0
        total_tokens = sum(rec.get("tokens", 0) for rec in tenants.values())
        raw = self.server._tenant_stats
        for name, spec in self.tenants.items():
            rec = tenants.setdefault(
                name,
                {"submitted": 0, "finished": 0, "tokens": 0,
                 "ttft_ms": {"count": 0}, "tpot_ms": {"count": 0}},
            )
            rec["weight"] = spec.weight
            rec["priority"] = spec.priority
            rec["rejected"] = self.rejected.get(name, 0)
            rec["budget_share"] = spec.weight / total_weight
            rec["goodput_share"] = (
                rec.get("tokens", 0) / total_tokens if total_tokens else 0.0
            )
            for kind, target in (
                ("ttft", spec.ttft_target_ms),
                ("tpot", spec.tpot_target_ms),
            ):
                att = None
                samples = raw.get(name, {}).get(f"{kind}_ms", ())
                if target is not None and len(samples):
                    vals = np.asarray(samples, np.float64)
                    att = float((vals <= target).mean())
                rec[f"{kind}_sla_attainment"] = att
        return s
