"""Inference engine.

Counterpart of the reference's ``InferenceEngine``
(``deepspeed/inference/engine.py:37``). Round-1 scope: jitted forward over a
(possibly model-sharded) param tree with dtype conversion, checkpoint loading
through the Orbax engine, and greedy ``generate``. The CUDA-graph
capture/replay pair (engine.py:489,508) maps onto jit's compile cache — the
first call compiles, subsequent calls replay. Kernel-injection policies and
paged KV-cache attention land with the module_inject/auto-TP subsystem.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig, DtypeEnum
from deepspeed_tpu.parallel.mesh import get_topology
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry
from deepspeed_tpu.profiling.tracer import MetricsRegistry, ObservabilityHub, Tracer
from deepspeed_tpu.runtime.module import wrap_module
from deepspeed_tpu.utils.logging import log_dist

_DTYPES = {
    DtypeEnum.fp32: jnp.float32,
    DtypeEnum.fp16: jnp.float16,
    DtypeEnum.bf16: jnp.bfloat16,
    DtypeEnum.int8: jnp.int8,
}


def _is_hf_model(model) -> bool:
    cfg = getattr(model, "config", None)
    return cfg is not None and hasattr(cfg, "model_type") and hasattr(model, "state_dict")


class InferenceEngine:
    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None):
        self._config = config or DeepSpeedInferenceConfig()
        self.topology = get_topology()
        # the engine owns TP-group creation (reference
        # _create_model_parallel_group, inference/engine.py:217): when the
        # config asks for tp_size and the live topology has no model axis,
        # rebuild the mesh as model=tp_size x data=rest
        tp_req = int(self._config.tensor_parallel.tp_size or 1)
        if tp_req > 1 and self.topology.get_model_parallel_world_size() == 1:
            from deepspeed_tpu.parallel.mesh import build_serving_mesh, set_topology

            self.topology = build_serving_mesh(tp_req)
            set_topology(self.topology)
        self.mesh = self.topology.mesh
        self.dtype = _DTYPES[self._config.dtype]
        self._params = None
        self._jit_forward = None
        self._cached_tp_rules = None
        self._rng = jax.random.PRNGKey(0)
        self._ds_config = None  # TransformerConfig when kernel-injected
        # ZeRO-Inference (reference engine.py:1499-1520: stage-3 offload
        # without an optimizer): params live in host DRAM / on NVMe and
        # stream through HBM per layer — capacity over latency
        self._param_stream = None
        self._zero_config = self._parse_zero_inference()
        # model profiling (reference engine.py:167 profile_model_time,
        # :518 model_times): per-forward wall latency, drained at read
        self.model_profile_enabled = False
        self._model_times = []
        # compile telemetry over every jitted program this engine runs
        # (forward, the KV-cached decode loops, the paged serving programs)
        # — same contract as the training engine's compile_stats()
        self._telemetry = CompileTelemetry()
        # unified tracing/metrics plane: serving step phases + per-request
        # lifecycle spans land here (the PagedServer gets this tracer);
        # observability() merges it with compile/analysis/serve stats
        tcfg = self._config.tracing
        self.tracer = Tracer(max_spans=tcfg.max_spans, enabled=tcfg.enabled)
        self.metrics = MetricsRegistry()
        self._obs_hub = ObservabilityHub(self.tracer, self.metrics)
        self._obs_hub.add_source("compile", self.compile_stats)
        self._obs_hub.add_source("analysis", self.analysis_report)
        self._obs_hub.add_source("serve", self.serve_stats)
        # enforce=False: an over-budget ledger surfaces IN the snapshot
        # rather than failing the observability read
        self._obs_hub.add_source(
            "memory", lambda: self.memory_report(enforce=False)
        )
        if tcfg.flight_recorder:
            self._obs_hub.install_flight_recorder(
                dump_dir=tcfg.flight_recorder_dir,
                last_spans=tcfg.flight_recorder_spans,
            )
        self._paged_server = None  # lazy; rebuilt when weights change
        # analysis.verify: static passes on each program at first compile
        if self._config.analysis.verify != "off":
            self._telemetry.on_compile = self._verify_program_static

        injected = False
        if self._config.replace_with_kernel_inject and _is_hf_model(model):
            # reference _apply_injection_policy (inference/engine.py:371):
            # convert the HF model to the fused TPU decoder + weights
            from deepspeed_tpu.module_inject.replace_module import replace_transformer_layer

            ds_model, params = replace_transformer_layer(
                model=model, dtype=jnp.dtype(self.dtype).name
            )
            self._ds_config = ds_model.config
            self.module = ds_model
            if params is not None:
                self.set_params(params)
            injected = True
        else:
            self.module = wrap_module(model)
        # checkpoint handed to init_inference (reference engine.py:406):
        # a path string — engine-format dir, or an mp-checkpoint manifest
        ckpt = self._config.checkpoint
        if isinstance(ckpt, str) and ckpt.endswith(".json") and not self._is_mp_manifest(ckpt):
            self._load_sd_checkpoint(ckpt)
        elif isinstance(ckpt, str):
            self._load_checkpoint(ckpt)
        elif isinstance(ckpt, dict):
            # the reference's SD-loader descriptor form (engine.py:406 →
            # SDLoaderFactory.get_sd_loader_json): a dict/json naming the
            # legacy sharded file list
            self._load_sd_checkpoint(ckpt)
        elif ckpt is not None:
            raise NotImplementedError(
                "init_inference checkpoint= takes a path string (engine "
                "checkpoint dir or mp-checkpoint manifest) or an SD-loader "
                "descriptor dict/json ({'type': 'Megatron', 'checkpoints': "
                "[...], 'version': ...})"
            )
        log_dist(
            f"InferenceEngine: dtype={self._config.dtype} "
            f"tp_size={self._config.tensor_parallel.tp_size} kernel_inject={injected}",
            ranks=[0],
        )

    def _parse_zero_inference(self):
        """DeepSpeedZeroConfig when the config asks for ZeRO-Inference
        (stage 3 + offload_param), else None."""
        zdict = self._config.zero or {}
        if not zdict:
            return None
        from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig

        zcfg = DeepSpeedZeroConfig(**zdict)
        off = zcfg.offload_param
        if int(zcfg.stage) >= 3 and off is not None and str(off.device) not in (
            "none",
            "OffloadDeviceEnum.none",
        ):
            return zcfg
        return None

    def _init_param_stream(self, params) -> None:
        """ZeRO-Inference: install params into the layer-stream store
        (host DRAM or NVMe) instead of HBM."""
        from deepspeed_tpu.runtime.zero.param_offload import ParamStreamEngine

        self._param_stream = ParamStreamEngine(
            self.module,
            params,
            self.topology,
            self._zero_config,
            {},  # no optimizer: inference never steps (moments stay unallocated)
            self.dtype,
        )
        self._params = None

    # --- weights --------------------------------------------------------
    def set_params(self, params: Any) -> None:
        """Install a param pytree (cast to the inference dtype). Sharded
        over the 'model' axis (AutoTP) when tp_size > 1 and over the
        'expert' axis for MoE modules when ep_size > 1 — the reference's MP
        + expert inference groups (``deepspeed/inference/engine.py:217,230``),
        expressed as GSPMD placements instead of process groups."""
        if self._zero_config is not None:
            if self._config.save_mp_checkpoint_path:
                log_dist(
                    "save_mp_checkpoint_path is ignored under ZeRO-Inference "
                    "offload (weights live in the layer stream, not HBM)",
                    ranks=[0],
                )
            self._init_param_stream(params)
            return
        cast = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p).astype(self.dtype)
            if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating)
            else jnp.asarray(p),
            params,
        )
        if self._config.quant.enabled:
            # weight quantization (reference MoQ inference): int8 roundtrip
            # per group — numerics match int8-weight kernels; the wire/HBM
            # win comes from qwZ-style boundaries when sharded
            from deepspeed_tpu.ops.quantizer import fake_quantize

            gs = int(self._config.quant.group_size or 64)
            bits = int(self._config.quant.num_bits or 8)

            def quant_leaf(p):
                if jnp.ndim(p) < 2 or not jnp.issubdtype(p.dtype, jnp.floating):
                    return p
                # group count must divide the element count exactly
                groups = p.size // gs if gs and p.size % gs == 0 else 1
                return fake_quantize(p, num_groups=groups, num_bits=bits)

            cast = jax.tree_util.tree_map(quant_leaf, cast)
        tp = self.topology.get_model_parallel_world_size() > 1
        ep = self.topology.axis_size("expert") > 1
        self._cached_tp_rules = None
        if tp or ep:
            from jax.sharding import NamedSharding, PartitionSpec

            tp_rules = self._tp_rules(cast)
            self._cached_tp_rules = tp_rules  # save_mp_checkpoint reuses this
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                tp_rules,
                is_leaf=lambda x: isinstance(x, PartitionSpec),
            )
            cast = jax.device_put(cast, shardings)
        self._params = cast
        self._jit_forward = None
        self._paged_server = None
        if self._config.save_mp_checkpoint_path:
            # reference inference/engine.py:406: persist the sharded layout
            # the moment the weights are resident, so later engines load
            # pre-split files
            self.save_mp_checkpoint(self._config.save_mp_checkpoint_path)

    def _tp_rules(self, params):
        """PartitionSpec tree for the weights: model-family rules when the
        module provides them (carry 'model' and 'expert' axes), else the
        AutoTP walk (reference module_inject/auto_tp.py:170)."""
        tp_rules = None
        if hasattr(self.module, "tp_partition_rules"):
            tp_rules = self.module.tp_partition_rules(params)
        if tp_rules is None:
            from deepspeed_tpu.module_inject.auto_tp import AutoTP

            tp_rules = AutoTP().partition_specs(params)
        return tp_rules

    def save_mp_checkpoint(self, save_path: str, tag: str = "ds-inference") -> str:
        """Write a pre-sharded TP inference checkpoint + manifest (reference
        ``save_mp_checkpoint_path``, inference/engine.py:406). Returns the
        manifest path; load it back via ``init_inference(model,
        checkpoint=<manifest>)`` or ``load_checkpoint``."""
        if self._param_stream is not None:
            raise NotImplementedError(
                "save_mp_checkpoint is unsupported under ZeRO-Inference "
                "offload: the weights live in the layer stream, not HBM"
            )
        if self._params is None:
            raise RuntimeError("save_mp_checkpoint before weights are set")
        from deepspeed_tpu.inference.mp_checkpoint import save_mp_checkpoint

        rules = self._cached_tp_rules
        if rules is None:
            rules = self._tp_rules(self._params)
        tp_size = max(1, self.topology.get_model_parallel_world_size())
        return save_mp_checkpoint(
            self._params,
            rules,
            save_path,
            tag=tag,
            tp_size=tp_size,
        )

    def init_params(self, batch, rng=None) -> None:
        if rng is not None:
            self._rng = rng
        params = self.module.init(self._rng, batch)
        self.set_params(params)

    @staticmethod
    def _is_mp_manifest(path: str) -> bool:
        from deepspeed_tpu.inference.mp_checkpoint import is_mp_checkpoint

        try:
            return is_mp_checkpoint(path)
        except Exception:
            return False

    def _load_sd_checkpoint(self, descriptor) -> None:
        """Legacy sharded (SplitCheckpoint) load: merge the file list to the
        FULL state dict (reference per-rank loads are GSPMD placements here)
        and convert through the container policy for the descriptor's
        model_type (default megatron)."""
        from deepspeed_tpu.module_inject.containers import policy_for
        from deepspeed_tpu.runtime.state_dict_factory import SDLoaderFactory

        # precondition first: merging can be GBs of torch.load — don't pay
        # for it just to discover the module can't accept the weights
        mcfg = getattr(self.module, "config", None)
        if mcfg is None:
            raise ValueError(
                "SD-loader checkpoints need an injected module with a model "
                "config (build the model via init_inference kernel injection "
                "or replace_transformer_layer first)"
            )
        if isinstance(descriptor, str):
            import json as _json

            with open(descriptor) as f:
                descriptor = _json.load(f)
        loader = SDLoaderFactory.get_sd_loader_json(descriptor)
        if isinstance(loader, dict):
            raise NotImplementedError(
                f"pre-sharded '{loader.get('type')}' descriptors load via the "
                "mp-checkpoint manifest path"
            )
        _, sd, _ = loader.load(mp_world_size=1, mp_rank=0)
        merged = loader.get_module(sd)
        model_type = descriptor.get("model_type", "megatron")
        policy = policy_for(model_type)
        self.set_params(policy.convert_weights(merged, mcfg))

    def _load_checkpoint(self, load_dir: str) -> None:
        from deepspeed_tpu.inference.mp_checkpoint import is_mp_checkpoint, load_mp_checkpoint

        if is_mp_checkpoint(load_dir):
            # pre-sharded layout (manifest json or its directory)
            params, _ = load_mp_checkpoint(load_dir)
            self.set_params(params)
            return
        from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import OrbaxCheckpointEngine

        state = OrbaxCheckpointEngine().load(load_dir)
        params = state.get("module", state)
        self.set_params(params)

    load_checkpoint = _load_checkpoint

    def profile_model_time(self, use_cuda_events: bool = True) -> None:  # noqa: ARG002
        """Record per-forward latency (reference engine.py:167; cuda events
        map onto a device-sync'd wall clock here)."""
        self.model_profile_enabled = True

    def model_times(self):
        """Collected per-forward latencies, cleared on read (reference
        engine.py:518)."""
        assert self.model_profile_enabled, "model profiling is not enabled"
        times = self._model_times
        self._model_times = []
        return times

    # --- forward --------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        if self.model_profile_enabled:
            # timed through the tracer's clock (DS-R009: no raw
            # perf_counter in the hot loop) and recorded on the timeline
            t0 = self.tracer.clock()
            out = self._forward_impl(*inputs, **kwargs)
            # close the async dispatch window: wait on one output element
            leaf = jax.tree_util.tree_leaves(out)[0]
            if hasattr(leaf, "ravel"):
                jax.device_get(jnp.ravel(leaf)[:1])
            t1 = self.tracer.clock()
            self.tracer.add_span("infer.forward", t0, t1)
            self._model_times.append(t1 - t0)
            return out
        return self._forward_impl(*inputs, **kwargs)

    def _forward_impl(self, *inputs, **kwargs):
        if self._zero_config is not None:
            batch = inputs[0] if len(inputs) == 1 else (inputs if inputs else kwargs)
            if self._param_stream is None:
                self.init_params(batch)
            from deepspeed_tpu.models.transformer import _split_batch

            tokens, labels = _split_batch(batch)
            return self._param_stream.eval_forward(jnp.asarray(tokens), labels)
        if self._params is None:
            batch = inputs[0] if inputs else kwargs
            self.init_params(batch)
        if self._jit_forward is None:
            module = self.module

            def fwd(params, batch, rng):
                return module.apply(params, batch, rngs={"dropout": rng}, train=False)

            self._jit_forward = self._telemetry.instrument("forward", fwd)
        batch = inputs[0] if len(inputs) == 1 else (inputs if inputs else kwargs)
        self._rng, sub = jax.random.split(self._rng)
        return self._jit_forward(self._params, batch, sub)

    __call__ = forward

    # --- generation -----------------------------------------------------
    def generate(self, *args, **kwargs):
        """Latency-recording wrapper over ``_generate_impl`` (whose
        signature this function adopts via functools.wraps below)."""
        if not self.model_profile_enabled:
            return self._generate_impl(*args, **kwargs)
        t0 = self.tracer.clock()
        out = self._generate_impl(*args, **kwargs)
        np.asarray(out[..., -1:])  # drain: wait for the last emitted token
        # one entry per generate call (the reference records per-token
        # kernel times; the whole decode is one program here)
        t1 = self.tracer.clock()
        self.tracer.add_span("infer.generate", t0, t1)
        self._model_times.append(t1 - t0)
        return out

    def _generate_impl(
        self,
        input_ids,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        pad_token_id: int = 0,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        num_beams: int = 1,
        length_penalty: float = 1.0,
    ):
        """Token generation (greedy by default; temperature/top-k/top-p
        sampling and beam search like the reference's HF-generate dispatch,
        ``deepspeed/inference/engine.py:578``). Kernel-injected models take
        the KV-cached single-program decode loop (beam search reorders the
        cache on device); arbitrary modules get one full-forward compiled
        program per (batch, max_len) bucket."""
        from deepspeed_tpu.inference.generation import greedy_generate

        if num_beams > 1:
            if self._ds_config is None or self._params is None:
                raise NotImplementedError(
                    "num_beams > 1 requires the kernel-injected (KV-cached) "
                    "path: build the engine with replace_with_kernel_inject "
                    "or a converted model family"
                )
            if temperature or top_k or top_p < 1.0:
                raise ValueError(
                    "beam search is deterministic; temperature/top_k/top_p "
                    "cannot be combined with num_beams > 1"
                )
            from deepspeed_tpu.inference.decode import beam_generate

            return beam_generate(
                self._ds_config,
                self._params,
                input_ids,
                max_new_tokens,
                num_beams=num_beams,
                eos_token_id=eos_token_id,
                pad_token_id=pad_token_id,
                length_penalty=length_penalty,
                telemetry=self._telemetry,
            )
        if self._zero_config is not None:
            if self._param_stream is None:
                self.init_params(jnp.asarray(input_ids))
            return self._zero_generate(
                input_ids, max_new_tokens, eos_token_id, pad_token_id,
                temperature=temperature, top_k=top_k, top_p=top_p,
            )
        if self._ds_config is not None and self._params is not None:
            # kernel-injected path: KV-cached prefill + on-device decode loop
            from deepspeed_tpu.inference.decode import generate as kv_generate

            self._rng, sub = jax.random.split(self._rng)
            return kv_generate(
                self._ds_config,
                self._params,
                input_ids,
                max_new_tokens,
                eos_token_id=eos_token_id,
                temperature=temperature,
                rng=sub,
                top_k=top_k,
                top_p=top_p,
                pad_token_id=pad_token_id,
                telemetry=self._telemetry,
            )
        if self._params is None:
            self.init_params(jnp.asarray(input_ids))
        module = self.module

        def apply_fn(params, tokens, rng):
            return module.apply(params, tokens, rngs={"dropout": rng}, train=False)

        if not hasattr(self, "_gen_cache"):
            self._gen_cache = {}
        self._rng, sub = jax.random.split(self._rng)
        return greedy_generate(
            apply_fn,
            self._params,
            input_ids,
            max_new_tokens,
            sub,
            eos_token_id=eos_token_id,
            pad_token_id=pad_token_id,
            jit_cache=self._gen_cache,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            telemetry=self._telemetry,
        )

    # the public generate adopts _generate_impl's signature/doc — one
    # source of truth for the sampling controls
    generate = functools.wraps(_generate_impl)(generate)

    # --- paged serving --------------------------------------------------
    def compile_stats(self):
        """Per-program compile telemetry snapshot — the inference-side
        counterpart of the training engine's ``compile_stats()``: for each
        jitted program (``forward``, ``kv_prefill`` / ``kv_decode_loop`` /
        ``kv_beam_loop``, ``full_fwd_gen_step``, and the serving programs —
        ``paged_<kind>_r<rows>_w<width>`` across the ragged / decode /
        prefill / verify builders) the trace, compile, and dispatch
        counters. The serving contract under ``paged_kv.ragged`` (default):
        ≤ 2 compiled ``paged_*`` programs for a whole mixed serve and
        exactly one ``paged_ragged_*`` dispatch per scheduler step; under
        the bucketed oracle, ≤1 compile per shape bucket and one
        ``paged_decode_*`` dispatch per decode step."""
        return self._telemetry.stats()

    def analysis_report(self, programs=None, passes=None):
        """Static-analysis report over every dispatched inference program
        (or the named subset) — same contract as the training engine's
        ``analysis_report()``: donation-aliasing, dtype-promotion,
        host-transfer, and collective-schedule pass results per program,
        retrace-cause diffs, and aggregate totals (``donation_verified``,
        static collective bytes). The serving invariants become checkable
        properties: every ``paged_decode_*`` / ``paged_prefill_*`` program
        must alias its donated page buffers and contain no host callback."""
        from deepspeed_tpu.analysis import engine_analysis_report

        return engine_analysis_report(
            self._telemetry,
            self._config.analysis,
            programs=programs,
            passes=passes,
            extra_config=self._analysis_extra_config(),
        )

    def _analysis_extra_config(self):
        """Engine-declared analysis-pass inputs: with tensor-parallel
        serving armed, the TP context's declared comm schedule and sharding
        rules let the memory pass flag pjit-inserted resharding collectives
        and large weights left replicated against the layout contract."""
        srv = getattr(self._paged_server, "server", self._paged_server)
        tp = getattr(srv, "tp", None)
        if tp is not None and tp.degree > 1:
            return {
                "declared_collectives": tp.declared_collectives(),
                "sharding_rules": tp.sharding_rules(),
            }
        return None

    def _verify_program_static(self, name: str) -> None:
        from deepspeed_tpu.analysis import verify_program
        from deepspeed_tpu.utils.logging import logger

        verify_program(
            self._telemetry,
            self._config.analysis,
            name,
            logger=logger,
            extra_config=self._analysis_extra_config(),
        )

    def memory_report(self, include_programs: bool = False, enforce: bool = True):
        """Static per-chip HBM residency ledger for the inference engine:
        the dense-path param tree, the (possibly resharded / int8) serving
        weights, and the paged KV pool — per-chip bytes under each leaf's
        sharding, with the pool's host-side page tables accounted as host
        RAM (the tp serving contract: KV bytes/chip == total/tp, tables
        never on device). ``include_programs=True`` folds in per-program
        transient estimates from the analysis memory pass (one re-trace
        each). ``enforce=True`` applies ``analysis.hbm_budget_bytes`` —
        over budget raises ``HbmBudgetError`` with per-buffer attribution
        (or warns, per ``analysis.hbm_budget``)."""
        from deepspeed_tpu.analysis import MemoryLedger
        from deepspeed_tpu.utils.logging import logger

        acfg = self._config.analysis
        ledger = MemoryLedger(
            hbm_budget_bytes=getattr(acfg, "hbm_budget_bytes", None),
            mode=getattr(acfg, "hbm_budget", "raise"),
        )
        if self._params is not None:
            ledger.add_tree("params", self._params, kind="params")
        srv = getattr(self._paged_server, "server", self._paged_server)
        if srv is not None:
            sp = getattr(srv, "params", None)
            if sp is not None and sp is not self._params:
                ledger.add_tree("serving_params", sp, kind="params")
            pool = getattr(srv, "pool", None)
            if pool is not None:
                rep = pool.memory_report()
                ledger.add_persistent(
                    "kv_pages",
                    per_chip_bytes=rep["kv_bytes_per_chip"],
                    global_bytes=rep["kv_total_bytes"],
                    kind="kv_pool",
                    detail=rep,
                )
                ledger.add_persistent(
                    "kv_page_tables",
                    per_chip_bytes=rep["host_table_bytes"],
                    location="host",
                    kind="kv_pool",
                )
        if include_programs:
            try:
                rep = self.analysis_report(passes=["memory"])
                for pname, entry in rep.get("programs", {}).items():
                    est = (
                        entry.get("passes", {})
                        .get("memory", {})
                        .get("summary", {})
                        .get("estimate")
                    )
                    if est:
                        ledger.add_program(pname, est)
            except Exception as e:  # analysis failure ≠ ledger failure
                logger.warning(f"memory ledger: program estimates failed: {e}")
        if enforce:
            return ledger.enforce(logger=logger)
        return ledger.report()

    def _build_paged_server(self):
        from deepspeed_tpu.inference.scheduler import PagedServer

        if self._ds_config is None or self._params is None:
            raise NotImplementedError(
                "serve() requires the kernel-injected (KV-cached) path: build "
                "the engine with replace_with_kernel_inject or a converted "
                "model family"
            )
        pcfg = self._config.paged_kv
        if not pcfg.enabled:
            raise ValueError("paged serving is disabled (inference config paged_kv.enabled)")
        # crash-recovery journal (inference.journal): replay BEFORE the new
        # writer opens its segment, then hand the replayed state to the
        # fresh server — a restart resumes every journaled stream
        # byte-identically from its last emitted token
        journal = None
        recovered_states = None
        next_uid = 0
        jcfg = self._config.journal
        if jcfg.enabled:
            from deepspeed_tpu.inference.journal import RequestJournal

            if not jcfg.dir:
                raise ValueError("inference.journal.enabled requires journal.dir")
            recovered_states, next_uid = RequestJournal.replay(jcfg.dir)
            journal = RequestJournal(
                jcfg.dir, segment_bytes=jcfg.segment_bytes, fsync=jcfg.fsync
            )
        # multi-chip tensor-parallel serving (ISSUE 13): the ragged
        # programs run under shard_map on a model-axis mesh — weights
        # column/row-parallel per the AutoTP map, kv pages sharded on the
        # kv-head axis, host-side scheduling untouched. The serving mesh
        # is ONE tp group over the first tp_degree devices; replication
        # across groups is the fleet layer's job (inference/fleet.py).
        scfg = pcfg.sharded
        tp_degree = int(scfg.tp_degree or self._config.tensor_parallel.tp_size or 1)
        tp_ctx = None
        params = self._params
        if tp_degree > 1 and not pcfg.ragged and scfg.tp_degree == 0:
            # FOLLOW mode (sharded.tp_degree=0 defers to tensor_parallel):
            # tp_size also drives the dense AutoTP forward/generate path,
            # and tp_size>1 + the bucketed oracle was a valid combination
            # before sharded serving existed — the bucketed path simply
            # stays single-chip. (An EXPLICIT sharded.tp_degree>1 with
            # ragged=False is a contradiction and fails config validation.)
            log_dist(
                "paged_kv.ragged=False: bucketed serving stays single-chip "
                f"(tensor_parallel.tp_size={tp_degree} keeps driving the "
                "dense generate path; enable ragged or set "
                "paged_kv.sharded.tp_degree to shard serving)",
                ranks=[0],
            )
            tp_degree = 1
        if tp_degree > 1:
            from deepspeed_tpu.inference.tp import TPServing, serving_mesh

            tp_ctx = TPServing(
                mesh=serving_mesh(tp_degree),
                quantized_allreduce=scfg.quantized_allreduce,
                comm_chunks=scfg.comm_chunks,
            )
        if scfg.weight_quant_bits == 8:
            # quantize BEFORE sharding: per-output-channel scales stay
            # global, so row-parallel partial sums dequantize consistently
            from deepspeed_tpu.compression.int8 import quantize_params_int8

            params = quantize_params_int8(params)
        server = PagedServer(
            self._ds_config,
            params,
            page_size=pcfg.page_size,
            num_pages=pcfg.num_pages,
            max_slots=pcfg.max_slots,
            slot_buckets=pcfg.slot_buckets or None,
            max_seq_len=pcfg.max_seq_len,
            prefill_chunk=pcfg.prefill_chunk,
            attn_impl=pcfg.attn_impl,
            dtype=self.dtype,
            telemetry=self._telemetry,
            spec_decode=self._config.spec_decode,
            prefix_cache=pcfg.prefix_cache,
            ragged=pcfg.ragged,
            multi_step=pcfg.multi_step,
            journal=journal,
            tracer=self.tracer,
            metrics=self.metrics,
            tp=tp_ctx,
        )
        if recovered_states:
            server.recover(recovered_states, next_uid)
        if self._obs_hub.flight_recorder is not None:
            # postmortems must name the window config: a crash dump that
            # shows a serve.window span is only readable next to the armed
            # horizon (flight-recorder payloads carry this context block).
            # Written unconditionally so a server REBUILT with windows
            # disabled overwrites a stale armed-horizon claim
            self._obs_hub.flight_recorder.context["serve.multi_step"] = {
                "enable": bool(pcfg.multi_step.enable),
                "horizon": int(pcfg.multi_step.horizon),
            }
        tcfg = self._config.traffic
        if tcfg.enabled:
            # multi-tenant SLA layer (inference/traffic.py): weighted-deficit
            # + priority scheduling, queue-cap admission control, per-tenant
            # serve_stats() breakdowns — same serve()/submit()/step surface
            from deepspeed_tpu.inference.traffic import MultiTenantServer

            server = MultiTenantServer(
                server, tenants=[t.model_dump() for t in tcfg.tenants]
            )
        return server

    def serve(self, prompts, max_new_tokens=32, eos_token_id=None):
        """Continuous-batching greedy generation over the paged KV pool:
        requests are admitted/evicted every step, prompts prefill in chunks
        riding the SAME dispatch as in-flight decoders, and each step is
        ONE dispatch of the unified ragged program
        (``inference/scheduler.py``; ``paged_kv.ragged=False`` falls back
        to the bucketed per-shape programs, byte-identical streams) — or,
        with ``paged_kv.multi_step`` armed and the running set stable, ONE
        fused window of up to ``horizon`` decode rounds (host dispatch gap
        amortized to 1/N, still byte-identical). With
        ``inference.spec_decode.enable`` host-side n-gram drafts verify
        inside the same per-step dispatch (per-request spec-K), token-exact
        under greedy. Accepts a list of 1-D
        prompts (ragged — no padding to a common length) and a scalar or
        per-request ``max_new_tokens``; returns one 1-D output array per
        request in submission order. The server (and its page pool)
        persists across calls, sized by the ``paged_kv`` config section."""
        if self._paged_server is None:
            self._paged_server = self._build_paged_server()
        return self._paged_server.serve(
            prompts, max_new_tokens=max_new_tokens, eos_token_id=eos_token_id
        )

    def serve_stats(self):
        """Observability of the live paged server: scheduler counters
        (admitted, preempted, finished, prefill_chunks, decode_steps,
        spec_rounds), the multi-step window block (``window_steps``,
        ``window_horizon``, ``dispatches_per_token``,
        ``window_break_reasons``), speculation quality (``spec_accept_rate``,
        ``spec_mean_accepted_per_round``, the ``spec_accept_hist`` draft-hit
        histogram), pool occupancy/utilization, prefix-cache counters
        (``prefix`` — hit rate, CoW copies, cached pages), latency SLOs
        (``ttft_ms`` / ``tpot_ms`` p50/p99), and per-tenant breakdowns
        (``tenants`` — plus budget/goodput shares and SLA attainment when
        ``inference.traffic`` is enabled)."""
        if self._paged_server is None:
            return {}
        return self._paged_server.serve_stats()

    def observability(self, analysis: bool = True):
        """The merged observability report (ISSUE 10), inference side: the
        serving ``timeline`` (per-step admit/pack/dispatch/emit/journal
        phases + per-request lifecycle spans) and ``metrics`` next to
        ``compile`` (``compile_stats()``), ``analysis``
        (``analysis_report()``; ``analysis=False`` skips its re-compile
        cost), and ``serve`` (``serve_stats()``). Chrome-trace export and
        the flight recorder hang off ``engine.observability_hub``."""
        return self._obs_hub.report(exclude=() if analysis else ("analysis",))

    @property
    def observability_hub(self):
        return self._obs_hub

    def _zero_generate(self, input_ids, max_new_tokens, eos_token_id, pad_token_id,
                       temperature=0.0, top_k=0, top_p=1.0):
        """Decode with layer-streamed params (ZeRO-Inference); greedy or
        temperature/top-k/top-p sampled like the in-HBM paths.

        Every step re-runs the full fixed-shape forward (one compile) and
        streams all layers through HBM — the reference's capacity-first
        trade (15T params on one GPU at batch-latency cost,
        docs/_posts/2022-09-10-zero-inference.md)."""
        from deepspeed_tpu.inference.sampling import sample_logits

        tokens = np.asarray(input_ids)
        if tokens.ndim == 1:
            tokens = tokens[None, :]
        B, P = tokens.shape
        L = P + max_new_tokens
        padded = np.full((B, L), pad_token_id, dtype=tokens.dtype)
        padded[:, :P] = tokens
        finished = np.zeros(B, dtype=bool)
        cursor = P
        for cur in range(P, L):
            logits = np.asarray(
                self._param_stream.eval_forward(jnp.asarray(padded), None)
            )
            self._rng, sub = jax.random.split(self._rng)
            nxt = np.asarray(
                sample_logits(jnp.asarray(logits[:, cur - 1]), sub,
                              temperature=temperature, top_k=top_k, top_p=top_p)
            ).astype(padded.dtype)
            if eos_token_id is not None:
                # finished rows keep emitting EOS — same padding contract as
                # the in-HBM decode paths
                nxt = np.where(finished, eos_token_id, nxt)
            padded[:, cur] = nxt
            cursor = cur + 1
            if eos_token_id is not None:
                finished |= nxt == eos_token_id
                if finished.all():
                    break
        return padded[:, :cursor]
