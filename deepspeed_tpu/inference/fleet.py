"""Serving fleet: replicated paged engines behind one router.

One crash-safe ``PagedServer`` (or its ``MultiTenantServer`` SLA front) is
still a single failure domain and a single chip's capacity. This module is
the layer above: a :class:`FleetRouter` over N replicas that keeps the
repo's serving contracts — byte-identical greedy streams, journal-exact
crash recovery, SLA tenancy — while adding what "a production system"
actually needs (ROADMAP item 3; DeepSpeed-Inference, arXiv 2207.00032,
motivates the prefill/decode role split; ZeRO-Infinity, arXiv 2104.07857,
is the precedent for elastic, fault-masked capacity):

* **prefix-affinity consistent-hash routing** — each request is keyed by
  the deepest block of its prompt whose crc32 *chain key* (the
  process-portable analog of ``PagePool``'s prefix chain hash: one key
  names a whole prefix, blocks are ``page_size`` tokens) the router has
  routed before, and the key picks a replica on a consistent-hash ring.
  N requests sharing a system prompt therefore land on the SAME replica
  and pay its prefill + HBM once (that replica's prefix cache stays hot),
  while unrelated prompts spread; replicas leaving the ring move only
  their own arc of keys;
* **live request migration** — ``migrate(uid)`` extracts the request's
  exact replay state from the source (``PagedServer.extract_request``),
  re-admits it on the target via ``recover()`` (journal-seeded, so the
  move is durable), and lets the recompute-preemption machinery re-derive
  the continuation: the target re-prefills ``prompt + generated`` on the
  cold chunk grid, so the stream is **byte-identical** to one that never
  moved, and every token acked before the move is preserved verbatim
  (``fleet_stats()['migrated_token_divergence']`` counts violations — it
  must read 0). Ordering is target-journal-first: the state becomes
  durable on the target BEFORE the source journal writes its
  migrated-out record, so no crash instant leaves the request claimed by
  neither journal (a crash in between double-claims it, and adoption
  dedupes);
* **replica failure handling** — each replica steps inside its own guard:
  a :class:`~deepspeed_tpu.utils.chaos.ChaosKilled` unwinding out of a
  replica's step is that replica dying (the replica is the failure
  domain; the router is the supervisor that observes the death — chaos's
  BaseException contract protects the replica's *internal* recovery code
  from swallowing a kill, not the component above it), ordinary
  exceptions trip a per-replica circuit breaker after
  ``breaker_threshold`` consecutive failures, and ``probe()`` runs
  injectable health checks. A dead replica's live requests re-route onto
  survivors from its journal (``RequestJournal.replay``) — streams
  resume byte-identically from the last synced token — falling back to
  the router's shadow submissions (full greedy recompute, still
  byte-identical) when the replica ran without a journal;
* **elastic drain / join** — ``drain(name)`` migrates every queued and
  live request off a replica (zero acked tokens dropped) and removes it
  from service: scale-down is migration. ``join(server)`` adds capacity,
  and ``adopt_journal(dir)`` is journal-catch-up scale-up: replay an
  orphaned journal (a dead replica's, after a real ``kill -9`` restart)
  and distribute its outstanding requests over the fleet.
  ``elasticity/fleet_policy.py`` decides WHEN (watermarks + hysteresis,
  replica counts quantized through the elastic batch math) and
  ``autoscale_step`` executes it;
* **prefill/decode role split (optional)** — replicas built with
  ``role="prefill"`` take new admissions; the step the first decode token
  exists, the router migrates the request to a ``role="decode"`` replica.
  KV handoff IS migration-at-first-decode: the decode replica re-derives
  the KV it needs (shared prompts from its prefix cache), so
  disaggregation needs no device-to-device transport.

The router is **pure host code** — table lookups, crc32 hashing, journal
replay; it never imports jax (lint DS-R010 enforces this), adds zero
compiled programs (replicas with the same geometry and telemetry share
the ragged programs through the serving program cache), and its per-step
work is spans + dict bookkeeping. It exposes the same surface the load
harness drives (``submit``/``step``/``run``/``serve``/``has_work``/
``result``/``serve_stats``/``finished_log``; the ``clock`` setter installs
a virtual clock on every replica), so ``utils/loadgen.py`` replays traces
across the fleet unchanged — with ``events`` injecting mid-trace kills.

Chaos points (``utils/chaos.py``): ``fleet.replica_kill`` at the top of a
replica's turn in the step loop, ``fleet.mid_migration`` between source
extraction and target re-seed, ``fleet.mid_drain`` between two drain
migrations.
"""

from __future__ import annotations

import bisect
import zlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.inference.journal import (
    JournalCorruptError,
    JournaledRequest,
    RequestJournal,
)
from deepspeed_tpu.profiling.tracer import (
    NULL_TRACER,
    MetricsRegistry,
    percentile_summary,
)
from deepspeed_tpu.utils import chaos
from deepspeed_tpu.utils.logging import logger

# replica uid spaces: each attached replica's scheduler counter starts at a
# fresh stride, so uids are unique fleet-wide and a migrated request keeps
# its uid on the target (recover() re-admits under the original uid)
UID_STRIDE = 1 << 32

# same chain root as PagePool's prefix index — only equality matters, but
# sharing the constant keeps the two chain definitions visibly parallel
_ROOT_CHAIN = 0x9E3779B9

ACTIVE = "active"
DRAINING = "draining"
DRAINED = "drained"
DEAD = "dead"


def _crc(data: bytes, seed: int = 0) -> int:
    return zlib.crc32(data, seed & 0xFFFFFFFF) & 0xFFFFFFFF


def prefix_chain_keys(prompt, page_size: int) -> List[int]:
    """crc32 chain keys over the prompt's leading full ``page_size``-token
    blocks — key b names blocks [0..b] as a unit, exactly like the pool's
    prefix index chains, but process-portable (crc32, not ``hash()``) so a
    restarted router routes the same prompts to the same ring arcs. The
    last (partial) block never keys: it cannot be a shared cached page."""
    toks = np.ascontiguousarray(np.asarray(prompt, np.int32).reshape(-1))
    n_full = max(toks.size - 1, 0) // int(page_size)
    keys: List[int] = []
    chain = _ROOT_CHAIN
    for b in range(n_full):
        chain = _crc(toks[b * page_size : (b + 1) * page_size].tobytes(), chain)
        keys.append(chain)
    return keys


class ConsistentHashRing:
    """Classic consistent hashing: each node owns ``vnodes`` points on a
    2^32 ring; a key routes to the first node point clockwise from its
    hash. Adding/removing a node moves only that node's arcs — prefix
    affinity survives fleet resizes for every key not on a moved arc."""

    def __init__(self, vnodes: int = 32):
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []  # sorted (hash, node)

    def add(self, name: str) -> None:
        for i in range(self.vnodes):
            self._points.append((_crc(f"{name}#{i}".encode()), name))
        self._points.sort()

    def remove(self, name: str) -> None:
        self._points = [(h, n) for h, n in self._points if n != name]

    def nodes(self) -> List[str]:
        return sorted({n for _, n in self._points})

    def lookup(self, key: int, accept: Callable[[str], bool]) -> Optional[str]:
        """First acceptable node clockwise from ``key`` (wrapping)."""
        if not self._points:
            return None
        start = bisect.bisect_left(self._points, (key & 0xFFFFFFFF, ""))
        n = len(self._points)
        seen = set()
        for off in range(n):
            _, name = self._points[(start + off) % n]
            if name in seen:
                continue
            seen.add(name)
            if accept(name):
                return name
        return None


@dataclass
class ReplicaHandle:
    """One replica in the fleet: the server (a ``PagedServer`` or its
    ``MultiTenantServer`` front), its journal directory (the recovery
    source of truth when it dies), its service role, and the router's
    health bookkeeping."""

    name: str
    server: object
    journal_dir: Optional[str] = None
    role: str = "any"  # any | prefill | decode
    state: str = ACTIVE
    failures: int = 0  # consecutive step/probe failures (circuit breaker)
    uid_base: int = 0
    health_fn: Optional[Callable] = None  # injectable probe; None = liveness only

    def __post_init__(self):
        if self.role not in ("any", "prefill", "decode"):
            raise ValueError(f"replica role must be any|prefill|decode, got {self.role!r}")

    @property
    def inner(self):
        """The underlying ``PagedServer`` (unwraps a MultiTenantServer)."""
        return getattr(self.server, "server", self.server)


def _pool_geometry(handle: ReplicaHandle) -> Tuple[int, int, int, int]:
    """The pool shape that determines a replica's compiled serving
    programs — the single definition both the constructor and ``join``
    check, because the fleet's zero-new-programs guarantee rests on every
    replica sharing it exactly."""
    pool = handle.inner.pool
    return (pool.page_size, pool.num_pages, pool.max_slots, pool.max_seq_len)


class FleetRouter:
    """The fleet front door: routes, steps, migrates, and supervises N
    replicas. See the module docstring for the design; the surface is
    deliberately the serving-server surface so the engine-side callers and
    the load harness treat a fleet exactly like one big server."""

    def __init__(
        self,
        replicas: Sequence[ReplicaHandle],
        vnodes: int = 32,
        affinity: bool = True,
        breaker_threshold: int = 3,
        integrity_checks: bool = True,
        tracer=None,
        metrics: Optional[MetricsRegistry] = None,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.affinity = bool(affinity)
        self.breaker_threshold = int(breaker_threshold)
        self.integrity_checks = bool(integrity_checks)
        self.replicas: Dict[str, ReplicaHandle] = {}
        self._ring = ConsistentHashRing(vnodes)
        self._where: Dict[int, str] = {}  # outstanding uid -> replica name
        self._shadow: Dict[int, JournaledRequest] = {}  # uid -> submit state
        # acked tokens a migrated request carried: its final output must
        # reproduce them verbatim (the divergence metric's ground truth)
        self._acked: Dict[int, List[int]] = {}
        # uid -> journaled replica still holding the durable claim for a
        # request that migrated to a journal-less target (released when
        # the request finishes)
        self._claims: Dict[int, str] = {}
        self._results: Dict[int, np.ndarray] = {}
        # chain key -> owning replica, LRU-bounded: unlike the pool's
        # prefix index (bounded by page capacity) this is pure routing
        # memory, and unique-prompt traffic would otherwise grow it one
        # entry per full prompt page forever. Evicting a cold chain only
        # costs its next request a ring placement, not correctness.
        self._chains: "OrderedDict[int, str]" = OrderedDict()
        self._chains_cap = 1 << 16
        self._next_stride = 0
        # stride index -> lowest safe next uid (absolute): adopted journals
        # carry uids from a PREVIOUS fleet's strides, and a replica that
        # lands on the same stride must allocate past them or two requests
        # share a uid in the fleet-global maps
        self._uid_floor: Dict[int, int] = {}
        self._clock = None
        self.stats = {
            "routed": 0,
            "rejected": 0,
            "migrations": 0,  # cooperative migrate() moves (incl. drains)
            "role_migrations": 0,  # prefill->decode handoffs
            "rerouted": 0,  # dead-replica requests re-placed on survivors
            "replica_kills": 0,
            "drains": 0,
            "joins": 0,
            "adopted": 0,  # requests adopted from orphaned journals
            "migrated_token_divergence": 0,  # MUST stay 0
        }
        # uniform pool geometry is what lets every replica share the same
        # compiled serving programs (the gate pins fleet => 0 new programs)
        geos = {_pool_geometry(h) for h in replicas}
        if len(geos) > 1:
            raise ValueError(
                f"fleet replicas must share one pool geometry "
                f"(page_size, num_pages, max_slots, max_seq_len); got {sorted(geos)}"
            )
        self.page_size = next(iter(geos))[0]
        for h in replicas:
            self._attach(h)

    # --- membership -----------------------------------------------------
    def _attach(self, handle: ReplicaHandle) -> None:
        if handle.name in self.replicas:
            raise ValueError(f"duplicate replica name {handle.name!r}")
        handle.uid_base = self._next_stride * UID_STRIDE
        inner = handle.inner
        inner._next_uid = max(
            inner._next_uid, handle.uid_base,
            self._uid_floor.get(self._next_stride, 0),
        )
        self._next_stride += 1
        self.replicas[handle.name] = handle
        if handle.state == ACTIVE:
            self._ring.add(handle.name)
        # a replica attached with replayed state (restart): track it
        for req in list(inner._queue) + list(inner._active):
            self._where[req.uid] = handle.name
            self._shadow.setdefault(
                req.uid,
                JournaledRequest(
                    uid=req.uid, prompt=np.asarray(req.prompt, np.int32),
                    max_new_tokens=int(req.max_new_tokens),
                    eos_token_id=req.eos_token_id, tenant=req.tenant,
                ),
            )
        for uid in list(inner._results):
            self._results[uid] = inner.take_result(uid)
        if self._clock is not None:
            inner.clock = self._clock

    def join(
        self,
        server,
        name: Optional[str] = None,
        journal_dir: Optional[str] = None,
        role: str = "any",
        catchup_dir: Optional[str] = None,
    ) -> ReplicaHandle:
        """Elastic scale-up: attach a fresh replica (same pool geometry).
        With ``catchup_dir``, journal-catch-up join: an orphaned journal
        (typically a dead replica's) is replayed and its outstanding
        requests adopted across the fleet — the new capacity arrives
        already carrying the dead replica's load."""
        name = name or f"r{self._next_stride}"
        handle = ReplicaHandle(
            name=name, server=server, journal_dir=journal_dir, role=role
        )
        geo = _pool_geometry(handle)
        have = next(
            (_pool_geometry(h) for h in self.replicas.values()), None
        )
        if have is not None and geo != have:
            raise ValueError(
                f"joining replica {name!r} breaks the fleet pool geometry: "
                f"{geo} vs {have}"
            )
        self._attach(handle)
        self.stats["joins"] += 1
        self.tracer.event("fleet.join", replica=name, role=role)
        if catchup_dir:
            self.adopt_journal(catchup_dir)
        return handle

    def drain(self, name: str) -> int:
        """Elastic scale-down: stop routing to the replica, migrate every
        queued and live request off it (acked tokens ride the replay state
        verbatim — zero dropped), and remove it from service. Returns how
        many requests moved. A kill landing mid-drain (``fleet.mid_drain``
        / ``fleet.mid_migration``) is the draining replica dying: the
        router fails it and the remainder re-routes from its journal."""
        h = self.replicas[name]
        if h.state == DEAD:
            return 0
        h.state = DRAINING
        self._ring.remove(name)
        self.stats["drains"] += 1
        moved = 0
        with self.tracer.span("fleet.drain", replica=name):
            self._collect_results()
            inner = h.inner
            uids = [r.uid for r in list(inner._queue)] + [
                r.uid for r in list(inner._active)
            ]
            for uid in uids:
                try:
                    chaos.point("fleet.mid_drain", replica=name, uid=uid)
                    if self.migrate(uid):
                        moved += 1
                except chaos.ChaosKilled:
                    self.fail_replica(name, reason="killed mid-drain")
                    return moved
                except Exception:
                    # the remainder has nowhere to go (e.g. last active
                    # replica): migrate() already put the request back, so
                    # return the replica to service rather than leaving it
                    # half-drained and unroutable
                    h.state = ACTIVE
                    self._ring.add(name)
                    raise
            h.state = DRAINED
        return moved

    def fail_replica(self, name: str, reason: str = "killed") -> int:
        """Mark a replica dead and re-route its outstanding requests onto
        the survivors. Idempotent and re-entrant: a crash INSIDE the
        re-routing (``fleet.mid_migration``) leaves the remaining requests
        still mapped to the dead replica, and calling again finishes the
        job — nothing is ever lost while the journal (or the router's
        shadow) holds the state. Returns how many requests re-routed."""
        h = self.replicas[name]
        if h.state != DEAD:
            h.state = DEAD
            self._ring.remove(name)
            self.stats["replica_kills"] += 1
            self.tracer.event("fleet.replica_dead", replica=name, reason=reason)
            self.metrics.counter("fleet.replica_kills").inc()
            logger.warning(f"fleet: replica {name!r} failed ({reason}); re-routing")
        return self._reroute_from(h)

    kill_replica = fail_replica  # the chaos/test-facing name

    # --- routing --------------------------------------------------------
    def _routable(self, roles: Tuple[str, ...]) -> Callable[[str], bool]:
        def accept(name: str) -> bool:
            h = self.replicas.get(name)
            return h is not None and h.state == ACTIVE and h.role in roles

        return accept

    def _admit_roles(self) -> Tuple[str, ...]:
        """New submissions go to prefill-capable replicas when the fleet
        is role-split; an all-decode remnant still serves (degraded) so a
        prefill-tier outage never refuses the whole fleet."""
        active_roles = {
            h.role for h in self.replicas.values() if h.state == ACTIVE
        }
        if "prefill" in active_roles or "any" in active_roles:
            return ("prefill", "any")
        return ("decode",)

    def _remember_chains(self, keys: List[int], name: str) -> None:
        for k in keys:
            self._chains[k] = name
            self._chains.move_to_end(k)
        while len(self._chains) > self._chains_cap:
            self._chains.popitem(last=False)  # coldest chain out

    def _route(
        self,
        prompt,
        roles: Optional[Tuple[str, ...]] = None,
        exclude: Iterable[str] = (),
    ) -> Optional[ReplicaHandle]:
        roles = roles or self._admit_roles()
        exclude = set(exclude)
        accept = self._routable(roles)
        keys = prefix_chain_keys(prompt, self.page_size)
        if self.affinity:
            # deepest block whose chain the router has routed before goes
            # straight to its owning replica — that replica has (very
            # likely) cached the prefix; the ring only places UNSEEN
            # prefixes (and re-places chains whose owner left the fleet)
            for k in reversed(keys):
                owner = self._chains.get(k)
                if owner is not None and accept(owner) and owner not in exclude:
                    self._remember_chains(keys, owner)
                    return self.replicas[owner]
            key = keys[0] if keys else _crc(
                np.ascontiguousarray(np.asarray(prompt, np.int32)).tobytes()
            )
        else:
            # affinity off (the A/B baseline): spread on a rotating key
            key = _crc(str(self.stats["routed"] + self.stats["rerouted"]).encode())
        name = self._ring.lookup(key, lambda n: accept(n) and n not in exclude)
        if name is None:
            return None
        self._remember_chains(keys, name)
        return self.replicas[name]

    # --- request intake -------------------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        tenant: str = "default",
    ) -> Optional[int]:
        """Route and submit one request; returns the fleet-wide uid, or
        None when the owning replica's admission control rejected it."""
        with self.tracer.span("fleet.route"):
            h = self._route(prompt)
            if h is None:
                raise RuntimeError("fleet has no active replica to route to")
            uid = h.server.submit(
                prompt, max_new_tokens=max_new_tokens,
                eos_token_id=eos_token_id, tenant=tenant,
            )
        if uid is None:
            self.stats["rejected"] += 1
            return None
        self._where[uid] = h.name
        self._shadow[uid] = JournaledRequest(
            uid=uid, prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens), eos_token_id=eos_token_id,
            tenant=tenant,
        )
        self.stats["routed"] += 1
        self.metrics.counter("fleet.routed").inc()
        return uid

    # --- the fleet step -------------------------------------------------
    def step(self) -> None:
        """One scheduler round across the fleet: step every serving
        replica inside its failure guard, harvest finished results, and
        run the role-split handoffs. Each replica's step is still its own
        one-dispatch (or one-window) contract — the router adds no device
        work of any kind."""
        with self.tracer.span("fleet.step"):
            for h in list(self.replicas.values()):
                if h.state not in (ACTIVE, DRAINING):
                    continue
                if not h.inner.has_work():
                    continue
                self._step_replica(h)
            self._collect_results()
            self._role_handoffs()
        self.metrics.counter("fleet.steps").inc()

    def _step_replica(self, h: ReplicaHandle) -> None:
        try:
            chaos.point("fleet.replica_kill", replica=h.name)
            with self.tracer.span("fleet.replica_step", replica=h.name):
                h.server.step()
            h.failures = 0
        except chaos.ChaosKilled:
            # the replica is the failure domain: a kill unwinding out of
            # its step is THAT replica dying, observed by the supervisor —
            # the in-process analog of a monitor seeing a dead process.
            # (chaos's BaseException contract exists so the replica's own
            # recovery code cannot swallow a kill; the router is not the
            # replica's recovery code.)
            self.fail_replica(h.name, reason="chaos kill in step")
        except Exception as e:  # noqa: BLE001 — the breaker's whole job
            h.failures += 1
            logger.warning(
                f"fleet: replica {h.name!r} step failed "
                f"({h.failures}/{self.breaker_threshold}): {type(e).__name__}: {e}"
            )
            if h.failures >= self.breaker_threshold:
                self.fail_replica(
                    h.name, reason=f"circuit breaker: {type(e).__name__}: {e}"
                )

    def probe(self) -> Dict[str, bool]:
        """Health-probe every serving replica (the injectable
        ``health_fn``; default is pure liveness — the step guard already
        catches crashes). Consecutive failures trip the same circuit
        breaker as step failures."""
        out: Dict[str, bool] = {}
        for h in list(self.replicas.values()):
            if h.state not in (ACTIVE, DRAINING):
                continue
            try:
                ok = bool(h.health_fn(h.server)) if h.health_fn else True
            except Exception:
                ok = False
            if ok:
                h.failures = 0
            else:
                h.failures += 1
                if h.failures >= self.breaker_threshold:
                    self.fail_replica(h.name, reason="health probe circuit breaker")
            out[h.name] = ok
        return out

    def has_work(self) -> bool:
        return any(
            h.state in (ACTIVE, DRAINING) and h.inner.has_work()
            for h in self.replicas.values()
        )

    def run(self) -> Dict[int, np.ndarray]:
        while self.has_work():
            self.step()
        return self._results

    def serve(
        self,
        prompts: Sequence,
        max_new_tokens=32,
        eos_token_id: Optional[int] = None,
        tenant="default",
    ) -> List[Optional[np.ndarray]]:
        """Batch convenience mirroring the single-server fronts: scalar or
        per-request budgets, scalar or per-request tenants; rejected
        submissions return None in their slot."""
        n = len(prompts)
        if isinstance(max_new_tokens, (int, np.integer)):
            max_new_tokens = [max_new_tokens] * n
        if isinstance(tenant, str):
            tenant = [tenant] * n
        if len(max_new_tokens) != n or len(tenant) != n:
            raise ValueError(
                f"{n} prompts but {len(max_new_tokens)} max_new_tokens / "
                f"{len(tenant)} tenants"
            )
        uids = [
            self.submit(p, max_new_tokens=int(m), eos_token_id=eos_token_id,
                        tenant=t)
            for p, m, t in zip(prompts, max_new_tokens, tenant)
        ]
        self.run()
        return [None if u is None else self.take_result(u) for u in uids]

    # --- results --------------------------------------------------------
    def _collect_results(self) -> None:
        for h in self.replicas.values():
            if h.state == DEAD:
                continue
            inner = h.inner
            for uid in list(inner._results):
                self._finish_result(uid, inner.take_result(uid))

    def _finish_result(self, uid: int, out: np.ndarray) -> None:
        """Book one finished output and settle the divergence check: a
        migrated request's acked prefix must appear verbatim in the final
        stream (byte-identical migration is a contract, and this counter
        is its audit)."""
        holder = self._claims.pop(uid, None)
        if holder is not None:
            # the output is delivered: the journaled source that kept the
            # durable claim for this journal-less-target migration can
            # disclaim it now (a dead holder's journal resurrects the
            # request on adoption instead — at-least-once, deduped)
            hrep = self.replicas.get(holder)
            if hrep is not None and hrep.state != DEAD:
                hrep.inner.release_migrated_claim(uid)
        acked = self._acked.pop(uid, None)
        shadow = self._shadow.pop(uid, None)
        if acked and shadow is not None:
            p = int(np.asarray(shadow.prompt).size)
            got = np.asarray(out[p : p + len(acked)])
            want = np.asarray(acked, np.int32)
            if got.size < want.size or not np.array_equal(got, want[: got.size]):
                self.stats["migrated_token_divergence"] += 1
                logger.error(
                    f"fleet: request {uid} diverged from its acked prefix "
                    f"after migration ({want.tolist()} vs {got.tolist()})"
                )
        self._where.pop(uid, None)
        self._results[uid] = out

    def result(self, uid: int) -> Optional[np.ndarray]:
        if uid not in self._results:
            self._collect_results()
        return self._results.get(uid)

    def take_result(self, uid: int) -> Optional[np.ndarray]:
        if uid not in self._results:
            self._collect_results()
        return self._results.pop(uid, None)

    # --- migration ------------------------------------------------------
    def migrate(
        self,
        uid: int,
        target: Optional[str] = None,
        roles: Optional[Tuple[str, ...]] = None,
    ) -> bool:
        """Live-migrate one request: extract its replay state from the
        source replica, re-seed it durably on the target (journal-first),
        then retire it from the source journal. Byte-identical streams by
        the recompute contract; acked tokens audited at finish. A kill at
        ``fleet.mid_migration`` models the source dying with the state off
        its scheduler but its journal still claiming the request — callers
        that own a failure domain (the step loop, ``drain``) catch it and
        ``fail_replica`` the source, which replays the journal and loses
        nothing."""
        src_name = self._where.get(uid)
        if src_name is None:
            return False  # already finished (or never routed)
        src = self.replicas[src_name]
        if target is not None:
            # validate BEFORE extraction: a bad explicit target must be a
            # pure no-op, not a tear-off-and-restore round trip
            tgt = self.replicas[target]
            if tgt.state != ACTIVE or tgt.name == src_name:
                raise ValueError(
                    f"migration target {target!r} is not an active "
                    f"other replica"
                )
        with self.tracer.span("fleet.migrate", uid=uid, source=src_name):
            state = src.inner.extract_request(uid)
            if state is None:
                # finished between the caller's snapshot and now
                self._collect_results()
                return False
            if target is None:
                tgt = self._route(
                    state.prompt, roles=roles, exclude={src_name}
                )
                if tgt is None:
                    # no eligible target (single-replica fleet): put the
                    # state back on the source instead of stranding it off
                    # every scheduler — the stream continues
                    # byte-identically where it was, and the extraction's
                    # migration accounting is undone (nothing moved)
                    src.inner.restore_request(state)
                    raise RuntimeError(
                        f"no active replica to migrate request {uid} to"
                    )
            chaos.point("fleet.mid_migration", uid=uid, source=src_name,
                        target=tgt.name)
            self._place_states(tgt, {uid: state})
            self.stats["migrations"] += 1
            self.metrics.counter("fleet.migrations").inc()
            # source-side journal hand-off LAST: the state is durable on
            # the target before the source disclaims it. A journal-less
            # target never durably claims the request, so the source must
            # KEEP its claim — disclaiming would leave the state in
            # neither journal and a crash would lose acked tokens. The
            # retained claim rides the source's compactions and is
            # disclaimed when the request finishes (_finish_result); the
            # double-claim window it opens is the one adoption dedupes
            if tgt.inner.journal is not None:
                src.inner.finalize_migration(uid)
            elif src.inner.journal is not None:
                src.inner.retain_migrated_claim(uid, state)
                self._claims[uid] = src_name
        return True

    def _place_states(
        self,
        tgt: ReplicaHandle,
        states: Dict[int, JournaledRequest],
        migrated_in: bool = True,
    ) -> None:
        """Seed a batch of replay states onto one target replica: ONE
        ``recover()`` (one journal sync + segment scan however many
        requests arrive — failover re-routes a dead replica's whole load
        through here) and one pool assert, then the router's per-request
        bookkeeping. ``migrated_in=False`` is the adoption-after-restart
        form: the previous fleet's counters and clock died with it, so
        the target claims the submits and restamps the clock."""
        inner = tgt.inner
        inner.recover(states, 0, migrated_in=migrated_in)
        if self.integrity_checks:
            # the post-migration pool assert: adoption re-queues through
            # the normal admission path, and the target pool must be
            # internally consistent before its next dispatch
            inner.pool.integrity_check()
        for uid, state in states.items():
            self._where[uid] = tgt.name
            self._shadow.setdefault(
                uid,
                JournaledRequest(
                    uid=uid, prompt=np.asarray(state.prompt, np.int32),
                    max_new_tokens=int(state.max_new_tokens),
                    eos_token_id=state.eos_token_id, tenant=state.tenant,
                ),
            )
            if state.generated:
                self._acked[uid] = [int(t) for t in state.generated]

    def _reroute_from(self, h: ReplicaHandle) -> int:
        """Re-place every outstanding request still mapped to a dead
        replica: journal replay is the source of truth (acked tokens ride
        verbatim); the router's shadow submissions are the journal-less
        fallback (full recompute — still byte-identical under greedy)."""
        uids = [u for u, n in self._where.items() if n == h.name]
        if not uids:
            return 0
        states: Dict[int, JournaledRequest] = {}
        if h.journal_dir:
            try:
                states, _ = RequestJournal.replay(h.journal_dir)
            except JournalCorruptError as e:
                logger.error(
                    f"fleet: journal of dead replica {h.name!r} is corrupt "
                    f"({e}); falling back to shadow resubmission"
                )
                states = {}
        moved = 0
        placements: Dict[str, Dict[int, JournaledRequest]] = {}
        for uid in sorted(uids):
            st = states.get(uid) or self._shadow.get(uid)
            if st is None:
                logger.error(f"fleet: request {uid} lost with replica {h.name!r}")
                continue
            if st.done:
                self._finish_result(
                    uid,
                    np.concatenate([
                        np.asarray(st.prompt, np.int32),
                        np.asarray(st.generated, np.int32),
                    ]),
                )
                moved += 1
                continue
            tgt = self._route(st.prompt, exclude={h.name})
            if tgt is None:
                raise RuntimeError(
                    f"fleet: no surviving replica for request {uid}"
                )
            chaos.point("fleet.mid_migration", uid=uid, source=h.name,
                        target=tgt.name)
            placements.setdefault(tgt.name, {})[uid] = st
        # one batched recover per surviving target: the failover window
        # pays one journal sync + pool assert per TARGET, not per request
        # (a kill during the routing loop above placed nothing — every
        # request is still mapped to the dead replica and the re-entrant
        # call re-routes them; a kill between targets leaves the placed
        # batch placed and the rest recoverable, same contract as before)
        for tname in sorted(placements):
            batch = placements[tname]
            self._place_states(self.replicas[tname], batch)
            self.stats["rerouted"] += len(batch)
            moved += len(batch)
        return moved

    def adopt_journal(self, directory: str) -> int:
        """Journal-catch-up: replay an orphaned journal directory (a dead
        replica's, after a process-level ``kill -9`` and restart) and
        place its outstanding requests across the fleet. Requests the
        fleet already tracks are skipped — the live copy (seeded from the
        target journal during a migration whose source-side retirement
        the crash ate) always carries at least as many acked tokens as
        the stale claim, so dedup keeps the superset."""
        states, next_uid = RequestJournal.replay(directory)
        # adopted uids come from a previous fleet's stride space: raise the
        # per-stride allocation floor past them (and past the dead server's
        # own counter) so no current or future replica on the same stride
        # hands out a uid the fleet already tracks
        floors: Dict[int, int] = {}
        for uid in states:
            s = uid // UID_STRIDE
            floors[s] = max(floors.get(s, 0), uid + 1)
        if next_uid > 0:
            s = (next_uid - 1) // UID_STRIDE
            floors[s] = max(floors.get(s, 0), next_uid)
        for s, floor in floors.items():
            self._uid_floor[s] = max(self._uid_floor.get(s, 0), floor)
        for h in self.replicas.values():
            s = h.uid_base // UID_STRIDE
            if s in floors:
                h.inner._next_uid = max(h.inner._next_uid, floors[s])
        adopted = 0
        placements: Dict[str, Dict[int, JournaledRequest]] = {}
        for uid in sorted(states):
            if uid in self._where or uid in self._results:
                continue  # double-claim from a mid-migration crash: live copy wins
            st = states[uid]
            if st.done:
                self._finish_result(
                    uid,
                    np.concatenate([
                        np.asarray(st.prompt, np.int32),
                        np.asarray(st.generated, np.int32),
                    ]),
                )
                adopted += 1
                continue
            tgt = self._route(st.prompt)
            if tgt is None:
                raise RuntimeError("fleet: no active replica to adopt into")
            placements.setdefault(tgt.name, {})[uid] = st
        for tname in sorted(placements):
            # migrated_in=False: the previous fleet died with its counters
            # and clock — the adopting replica claims the submits and the
            # journaled timestamps are restamped against the live clock
            self._place_states(
                self.replicas[tname], placements[tname], migrated_in=False
            )
            adopted += len(placements[tname])
        self.stats["adopted"] += adopted
        return adopted

    # --- prefill/decode role split --------------------------------------
    def _role_handoffs(self) -> None:
        """Migration-at-first-decode: the step a request on a prefill-role
        replica holds its first decode token, hand it to a decode replica.
        The KV handoff is the migration itself — the decode replica
        re-derives (or prefix-attaches) the KV it needs."""
        decode_targets = any(
            h.state == ACTIVE and h.role in ("decode", "any")
            for h in self.replicas.values()
        )
        if not decode_targets:
            return
        for h in list(self.replicas.values()):
            if h.state != ACTIVE or h.role != "prefill":
                continue
            ready = [
                r.uid
                for r in list(h.inner._active)
                if r.pending is not None and not r.done
            ]
            for uid in ready:
                try:
                    if self.migrate(uid, roles=("decode", "any")):
                        self.stats["role_migrations"] += 1
                except chaos.ChaosKilled:
                    self.fail_replica(h.name, reason="killed mid-handoff")
                    break

    # --- elasticity -----------------------------------------------------
    def autoscale_step(self, policy, spawn: Callable[[], object], step: int) -> int:
        """Drive an ``elasticity.FleetResizePolicy``: compute the backlog,
        ask for a target size, then drain the least-loaded replicas (scale
        down) or ``spawn()`` + ``join`` fresh ones (scale up). Returns the
        signed size delta actually applied."""
        active = [h for h in self.replicas.values() if h.state == ACTIVE]
        backlog = sum(
            h.inner.queued_count() + h.inner.live_count() for h in active
        )
        target = policy.decide(backlog=backlog, n_active=len(active), step=step)
        delta = target - len(active)
        if delta > 0:
            for _ in range(delta):
                self.join(spawn())
        elif delta < 0:
            by_load = sorted(
                active,
                key=lambda h: h.inner.queued_count() + h.inner.live_count(),
            )
            for h in by_load[: -delta]:
                self.drain(h.name)
        return delta

    # --- observability ---------------------------------------------------
    @property
    def clock(self):
        return self._clock

    @clock.setter
    def clock(self, fn) -> None:
        # the load harness installs its virtual clock through this setter
        # (it treats the router as the innermost server); every replica's
        # TTFT/TPOT stamps must live on the same axis
        self._clock = fn
        for h in self.replicas.values():
            h.inner.clock = fn

    @property
    def tenants(self) -> Dict:
        """Merged tenant specs across MultiTenantServer replicas (the load
        harness reads weights/targets for goodput accounting)."""
        merged: Dict = {}
        for h in self.replicas.values():
            merged.update(getattr(h.server, "tenants", {}) or {})
        return merged

    def finished_log(self) -> List:
        out: List = []
        for h in self.replicas.values():
            try:
                out.extend(h.server.finished_log())
            except Exception:
                pass  # an unresponsive dead replica drops only its history
        return out

    _percentiles = staticmethod(percentile_summary)

    def fleet_stats(self) -> Dict:
        """The router's own block: counters, per-replica state/role/load,
        and ring membership. ``serve_stats()`` embeds it under ``fleet``;
        attach it to an ``ObservabilityHub`` via ``attach_observability``
        for the merged ``observability()`` report."""
        reps = {}
        for name, h in self.replicas.items():
            inner = h.inner
            reps[name] = {
                "state": h.state,
                "role": h.role,
                "failures": h.failures,
                "uid_base": h.uid_base,
                "journal_dir": h.journal_dir,
                "queued": inner.queued_count() if h.state != DEAD else None,
                "live": inner.live_count() if h.state != DEAD else None,
            }
        return {
            **self.stats,
            "n_replicas": len(self.replicas),
            "n_active": sum(
                1 for h in self.replicas.values() if h.state == ACTIVE
            ),
            "ring_nodes": self._ring.nodes(),
            "outstanding": len(self._where),
            "chains_tracked": len(self._chains),
            "replicas": reps,
        }

    def attach_observability(self, hub) -> None:
        """Register the fleet as a source on an ``ObservabilityHub`` so
        ``observability()`` reports carry the router block + per-replica
        serving stats next to the timeline and metrics."""
        hub.add_source("fleet", self.serve_stats)

    def serve_stats(self) -> Dict:
        """Fleet-merged serving stats, shaped like one server's: summed
        scheduler/pool/speculation counters, TTFT/TPOT percentiles
        recomputed over every replica's finished requests, per-tenant
        breakdowns merged the same way, a merged ``prefix`` block with the
        fleet-wide hit rate, per-replica blocks under ``replicas``, and
        the router's own block under ``fleet``. Dead replicas' counters
        stay in the merge (their served work happened — dropping it would
        make the counters disagree with ``finished_log`` and the replay
        report's goodput); an in-process dead replica still answers from
        host state, and one that cannot is skipped."""
        per: Dict[str, Dict] = {}
        for name, h in self.replicas.items():
            try:
                per[name] = h.server.serve_stats()
            except Exception:
                continue  # unresponsive dead replica: history unavailable
        merged: Dict = {}
        skip = {
            "ttft_ms", "tpot_ms", "tenants", "prefix", "window_break_reasons",
            "spec_accept_hist", "dispatches_per_token", "spec_accept_rate",
            "spec_mean_accepted_per_round", "pool_utilization",
            "window_horizon",
        }
        for rep in per.values():
            for k, v in rep.items():
                if k in skip or not isinstance(v, (int, float)):
                    continue
                merged[k] = merged.get(k, 0) + v
        merged["dispatches_per_token"] = (
            merged.get("dispatches", 0) / merged["emitted_tokens"]
            if merged.get("emitted_tokens")
            else 0.0
        )
        # latency percentiles recomputed from the union of finished
        # requests (per-replica percentiles cannot merge)
        logs = self.finished_log()
        merged["ttft_ms"] = self._percentiles([t for _, t, _, _ in logs])
        merged["tpot_ms"] = self._percentiles(
            [t for _, _, t, _ in logs if t is not None]
        )
        tenants: Dict[str, Dict] = {}
        for rep in per.values():
            for tname, rec in rep.get("tenants", {}).items():
                agg = tenants.setdefault(
                    tname, {"submitted": 0, "finished": 0, "tokens": 0}
                )
                for k in ("submitted", "finished", "tokens", "rejected"):
                    if k in rec:
                        agg[k] = agg.get(k, 0) + rec[k]
        for tname, agg in tenants.items():
            agg["ttft_ms"] = self._percentiles(
                [t for tn, t, _, _ in logs if tn == tname]
            )
            agg["tpot_ms"] = self._percentiles(
                [t for tn, _, t, _ in logs if tn == tname and t is not None]
            )
        merged["tenants"] = tenants
        prefix: Dict = {}
        for rep in per.values():
            for k, v in rep.get("prefix", {}).items():
                if isinstance(v, (int, float)) and k != "prefix_hit_rate":
                    prefix[k] = prefix.get(k, 0) + v
        q = prefix.get("prefix_query_tokens", 0)
        prefix["prefix_hit_rate"] = (
            prefix.get("prefix_hit_tokens", 0) / q if q else 0.0
        )
        merged["prefix"] = prefix
        merged["replicas"] = per
        merged["fleet"] = self.fleet_stats()
        return merged
