"""Greedy decoding with a single compiled program.

The naive loop regrows the token array each step, recompiling per length.
Here the sequence is padded once to ``prompt_len + max_new_tokens`` and a
jitted step reads the logits at a *traced* cursor and writes the next token
in place (``dynamic_update_slice``), so XLA compiles exactly one program per
(batch, max_len) bucket. Causality makes the padding harmless: positions
≥ cursor cannot influence the logits at cursor-1 in a causal model.

This is the interim decode path; the paged KV-cache attention kernel replaces
the full-sequence forward for long generations.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def greedy_generate(
    apply_fn: Callable,  # (params, tokens[B,L], rng) -> logits[B,L,V]
    params,
    input_ids,
    max_new_tokens: int,
    rng,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    jit_cache: Optional[dict] = None,
):
    tokens = jnp.asarray(input_ids)
    if tokens.ndim == 1:
        tokens = tokens[None, :]
    batch, prompt_len = tokens.shape
    max_len = prompt_len + max_new_tokens
    padded = jnp.full((batch, max_len), pad_token_id, dtype=tokens.dtype)
    padded = jax.lax.dynamic_update_slice(padded, tokens, (0, 0))

    cache_key = ("greedy_step", batch, max_len)
    if jit_cache is not None and cache_key in jit_cache:
        step = jit_cache[cache_key]
    else:

        def _step(params, padded, cursor, rng):
            logits = apply_fn(params, padded, rng)
            last = jax.lax.dynamic_index_in_dim(logits, cursor - 1, axis=1, keepdims=False)
            next_tok = jnp.argmax(last, axis=-1).astype(padded.dtype)
            out = jax.lax.dynamic_update_slice(padded, next_tok[:, None], (0, cursor))
            return out, next_tok

        step = jax.jit(_step, donate_argnums=(1,))
        if jit_cache is not None:
            jit_cache[cache_key] = step

    cursor = prompt_len
    for _ in range(max_new_tokens):
        rng, sub = jax.random.split(rng)
        padded, next_tok = step(params, padded, jnp.int32(cursor), sub)
        cursor += 1
        if eos_token_id is not None and bool(np.all(jax.device_get(next_tok) == eos_token_id)):
            break
    return padded[:, :cursor]
