"""Full-forward generation fallback (any apply_fn) with one compiled step.

The naive loop regrows the token array each step, recompiling per length.
Here the sequence is padded once to ``prompt_len + max_new_tokens`` and a
jitted step reads the logits at a *traced* cursor, samples (greedy /
temperature / top-k / top-p), and writes the next token in place
(``dynamic_update_slice``), so XLA compiles exactly one program per
(batch, max_len, sampling-config) bucket. Causality makes the padding
harmless: positions ≥ cursor cannot influence the logits at cursor-1.

EOS tracking stays on device; the host only fetches the all-finished
scalar every ``eos_check_every`` steps (a per-token ``device_get`` would
serialize the loop on the host link).

This is the O(steps × full forward) fallback for arbitrary models; the
flagship ``TransformerLM`` layout takes the KV-cached single-program path
in ``inference/decode.py`` instead.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp


def greedy_generate(
    apply_fn: Callable,  # (params, tokens[B,L], rng) -> logits[B,L,V]
    params,
    input_ids,
    max_new_tokens: int,
    rng,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    jit_cache: Optional[dict] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_check_every: int = 8,
    telemetry=None,
):
    from deepspeed_tpu.inference.sampling import sample_logits

    tokens = jnp.asarray(input_ids)
    if tokens.ndim == 1:
        tokens = tokens[None, :]
    batch, prompt_len = tokens.shape
    max_len = prompt_len + max_new_tokens
    padded = jnp.full((batch, max_len), pad_token_id, dtype=tokens.dtype)
    padded = jax.lax.dynamic_update_slice(padded, tokens, (0, 0))

    cache_key = (
        "gen_step", batch, max_len, eos_token_id,
        float(temperature), int(top_k), float(top_p),
    )
    if jit_cache is not None and cache_key in jit_cache:
        step = jit_cache[cache_key]
    else:
        sample = functools.partial(
            sample_logits, temperature=temperature, top_k=top_k, top_p=top_p
        )

        def _step(params, padded, cursor, rng, finished):
            logits = apply_fn(params, padded, rng)
            last = jax.lax.dynamic_index_in_dim(logits, cursor - 1, axis=1, keepdims=False)
            next_tok = sample(last, rng).astype(padded.dtype)
            if eos_token_id is not None:
                next_tok = jnp.where(
                    finished, jnp.asarray(eos_token_id, padded.dtype), next_tok
                )
                finished = finished | (next_tok == eos_token_id)
            out = jax.lax.dynamic_update_slice(padded, next_tok[:, None], (0, cursor))
            return out, finished, jnp.all(finished)

        if telemetry is None:
            step = jax.jit(_step, donate_argnums=(1,))
        else:
            step = telemetry.instrument("full_fwd_gen_step", _step, donate_argnums=(1,))
        if jit_cache is not None:
            jit_cache[cache_key] = step

    import numpy as np

    cursor = prompt_len
    finished = jnp.zeros((batch,), bool)
    for i in range(max_new_tokens):
        rng, sub = jax.random.split(rng)
        padded, finished, all_done = step(params, padded, jnp.int32(cursor), sub, finished)
        cursor += 1
        if eos_token_id is not None and (
            (i + 1) % eos_check_every == 0 or i == max_new_tokens - 1
        ):
            if bool(jax.device_get(all_done)):
                break
    if eos_token_id is not None:
        # trim the up-to-(eos_check_every-1) trailing EOS columns emitted
        # between the last real token and the host check, so the returned
        # length matches the KV-cached path exactly (one emit past each
        # row's first EOS, nothing more)
        emitted = np.asarray(jax.device_get(padded[:, prompt_len:cursor]))
        hit = emitted == eos_token_id
        last = hit.argmax(1)  # first EOS per row (0 if none)
        per_row = np.where(hit.any(1), last + 1, emitted.shape[1])
        cursor = prompt_len + int(per_row.max())
    return padded[:, :cursor]
