"""Pre-sharded (model-parallel) inference checkpoints.

Counterpart of the reference's ``save_mp_checkpoint_path`` flow
(``deepspeed/inference/engine.py:406`` writes per-tp-rank shard files plus a
``ds_inference_config.json`` manifest; ``module_inject/load_checkpoint.py``
consumes them so a tp_size-way serving fleet loads only its own slice
instead of re-sharding a monolithic checkpoint at startup).

TPU-native layout: one ``{tag}_non-tp.npz`` with every replicated leaf, and
``{tag}_tp_{rank:02d}.npz`` files each holding rank's slice of every
model-axis-sharded leaf (sliced along the dim its PartitionSpec marks
'model'). The manifest records tp_size, the file list, and the concat dim
per sharded leaf, so loading is layout-driven — no model knowledge needed.

Param trees here are nested dicts of arrays (the model families' layout);
paths are ``a/b/c`` keys from ``tensor_fragment._flatten_with_paths``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Tuple

import numpy as np

from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths

MANIFEST_NAME = "ds_inference_config.json"


def _model_dim(spec) -> int | None:
    """Dim index carrying the 'model' axis in a PartitionSpec, else None."""
    if spec is None:
        return None
    for i, entry in enumerate(tuple(spec)):
        axes = entry if isinstance(entry, tuple) else (entry,)
        if "model" in [a for a in axes if a is not None]:
            return i
    return None


def save_mp_checkpoint(
    params: Dict[str, Any],
    specs: Dict[str, Any],
    save_path: str,
    tag: str = "ds-inference",
    tp_size: int = 1,
    version: str = "0.1.0",
) -> str:
    """Write the sharded layout + manifest; returns the manifest path.

    ``specs`` is a pytree of PartitionSpecs congruent with ``params`` (or
    None leaves for replicated). Leaves whose spec names the 'model' axis
    are split into ``tp_size`` equal slices along that dim.
    """
    import jax

    os.makedirs(save_path, exist_ok=True)

    def to_host(h):
        # npz has no bf16/fp16-extension story: widen floats to f32 (a
        # lossless embedding for bf16/fp16) and record the original dtype
        if h.dtype.kind not in "iub" and h.dtype != np.float64:
            return h.astype(np.float32)
        return h

    def check_dict_tree(t, where="params"):
        if isinstance(t, dict):
            for v in t.values():
                check_dict_tree(v, where)
        elif isinstance(t, (list, tuple)):
            # _unflatten rebuilds every level as a dict: sequences would not
            # round-trip structurally — refuse up front
            raise ValueError(
                f"save_mp_checkpoint requires a nested-dict {where} tree; "
                "lists/tuples of weights do not round-trip through the "
                "path-keyed npz layout"
            )

    check_dict_tree(params)
    flat_orig = {
        p: np.asarray(jax.device_get(v)) for p, v in _flatten_with_paths(params).items()
    }
    dtypes = {p: str(v.dtype) for p, v in flat_orig.items()}
    flat_p = {p: to_host(v) for p, v in flat_orig.items()}
    flat_s = _flatten_with_paths(specs) if specs is not None else {}

    non_tp: Dict[str, np.ndarray] = {}
    tp_files: list[Dict[str, np.ndarray]] = [dict() for _ in range(tp_size)]
    shard_dims: Dict[str, int] = {}
    for path, leaf in flat_p.items():
        dim = _model_dim(flat_s.get(path))
        if dim is None or tp_size <= 1 or leaf.shape[dim] % tp_size != 0:
            non_tp[path] = leaf
            continue
        shard_dims[path] = dim
        for rank, piece in enumerate(np.split(leaf, tp_size, axis=dim)):
            tp_files[rank][path] = piece

    # '/' is not legal inside npz member names on all loaders; escape it
    def k(path):
        return path.replace("/", "|")

    non_tp_name = f"{tag}_non-tp.npz"
    np.savez(os.path.join(save_path, non_tp_name), **{k(p): v for p, v in non_tp.items()})
    tp_names = []
    for rank in range(tp_size):
        name = f"{tag}_tp_{rank:02d}.npz"
        np.savez(os.path.join(save_path, name), **{k(p): v for p, v in tp_files[rank].items()})
        tp_names.append(name)

    manifest = {
        "type": "ds_model",
        "version": version,
        "parallelization": "tp",
        "tp_size": tp_size,
        "base_dir": ".",
        "non_tp": non_tp_name,
        "tp": tp_names,
        "shard_dims": shard_dims,
        "dtypes": dtypes,
    }
    from deepspeed_tpu.runtime.checkpoint_engine.atomic import atomic_write_text

    mpath = os.path.join(save_path, MANIFEST_NAME)
    atomic_write_text(mpath, json.dumps(manifest, indent=2))
    return mpath


def is_mp_checkpoint(path: str) -> bool:
    """True only for OUR manifest layout — a readable json carrying the
    ds_model/non_tp markers — so reference-style descriptor jsons fall
    through to the other loaders instead of KeyError-ing here."""
    if os.path.isfile(path) and path.endswith(".json"):
        mpath = path
    elif os.path.isdir(path) and os.path.isfile(os.path.join(path, MANIFEST_NAME)):
        mpath = os.path.join(path, MANIFEST_NAME)
    else:
        return False
    try:
        with open(mpath) as f:
            m = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    return isinstance(m, dict) and m.get("type") == "ds_model" and "non_tp" in m


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for path, leaf in flat.items():
        parts = path.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = leaf
    return tree


def load_mp_checkpoint(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Assemble the full param tree from the sharded layout. Returns
    (params, manifest). ``path`` is the manifest file or its directory."""
    mpath = path if os.path.isfile(path) else os.path.join(path, MANIFEST_NAME)
    with open(mpath) as f:
        manifest = json.load(f)
    base = os.path.join(os.path.dirname(mpath), manifest.get("base_dir", "."))

    def load_npz(name):
        with np.load(os.path.join(base, name)) as z:
            return {key.replace("|", "/"): z[key] for key in z.files}

    flat = load_npz(manifest["non_tp"])
    tp_flats = [load_npz(name) for name in manifest["tp"]]
    for path_key, dim in manifest["shard_dims"].items():
        flat[path_key] = np.concatenate([tf[path_key] for tf in tp_flats], axis=dim)
    dtypes = manifest.get("dtypes", {})
    if dtypes:
        import ml_dtypes  # jax dependency: carries bfloat16 for numpy

        for path_key, name in dtypes.items():
            if path_key in flat and str(flat[path_key].dtype) != name:
                flat[path_key] = flat[path_key].astype(np.dtype(getattr(ml_dtypes, name, name)))
    return _unflatten(flat), manifest
