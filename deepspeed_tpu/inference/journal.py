"""Append-only request journal for serving crash recovery.

A ``PagedServer`` crash today drops every live stream. The journal makes a
restart a *resume*: every admitted request and every emitted token is
appended to an on-disk log, and on restart the server replays it — each
unfinished request is re-submitted with its journaled emissions pre-seeded,
so its re-prefill (nearly free under prefix caching for shared prompts)
re-derives the exact greedy continuation and the stream resumes
**byte-identically** from its last emitted token. This is the same
machinery that makes recompute-preemption invisible, driven from disk.

Layout: numbered segments under the journal directory.

* the ACTIVE segment (``seg_<n>.open``) takes appends; records are
  buffered in-process and flushed (+``fsync``) once per scheduler step via
  ``sync()`` — one durability point per dispatch, not per token;
* at ``segment_bytes`` the active segment is SEALED: fsynced, then
  atomically renamed to ``seg_<n>.jrnl``. Sealed segments are immutable
  and fully valid by construction;
* each record is one line — ``<crc32:08x> <compact-json>`` — so torn tails
  are *detectable*: replay accepts a torn record only at the very tail of
  the newest segment (the instant the crash happened) and raises
  :class:`JournalCorruptError` anywhere else (a bad record in a sealed
  segment, or garbage with valid records after it, is corruption, not a
  crash artifact).

Record types: ``s`` submit (uid, prompt, budget, eos, tenant, and — for
recovery re-submits — the tokens already emitted), ``e`` emit (uid, token),
``f`` finish (uid), ``m`` migrated-out (uid — the request now lives in
ANOTHER replica's journal, so replaying this one must not resurrect it;
the fleet router writes it after a live migration lands on the target).
A later ``s`` for the same uid replaces the earlier state, which is how
recovery compacts: the restarted server journals one seeded submit per
live request into a fresh segment, so the chain stays replayable from any
point without rewriting history. ``begin_compaction()`` is the same move
for a LIVE server (fleet migration/drain): seal, re-seed the current
state into a fresh segment, then ``retire_older_segments()`` — journal
growth stays bounded however many requests migrate through.
"""

from __future__ import annotations

import json
import os
import re
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine.atomic import fsync_dir
from deepspeed_tpu.utils import chaos
from deepspeed_tpu.utils.logging import logger

_SEG_SEALED = re.compile(r"^seg_(\d{6})\.jrnl$")
_SEG_OPEN = re.compile(r"^seg_(\d{6})\.open$")


class JournalCorruptError(RuntimeError):
    """The journal is damaged beyond what a crash can explain: a sealed
    segment fails its CRC, or valid records follow a broken one."""


@dataclass
class JournaledRequest:
    """One request's replayed state."""

    uid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int]
    tenant: str
    generated: List[int] = field(default_factory=list)
    finished: bool = False
    # server-clock timestamps, meaningful only within one clock domain (a
    # live fleet's migrations); a fresh process ignores them — its clock
    # restarted, so preserved stamps would corrupt TTFT
    t_submit: Optional[float] = None
    t_first: Optional[float] = None

    @property
    def done(self) -> bool:
        """Finished explicitly, or implicitly (the crash ate the finish
        record but the journaled emissions already hit the budget/EOS)."""
        if self.finished:
            return True
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (
            self.eos_token_id is not None
            and bool(self.generated)
            and self.generated[-1] == self.eos_token_id
        )


def _encode(rec: dict) -> bytes:
    payload = json.dumps(rec, separators=(",", ":"), sort_keys=True)
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return f"{crc:08x} {payload}\n".encode("utf-8")


def _decode(line: bytes) -> Optional[dict]:
    """The record, or None when the line is torn/corrupt."""
    try:
        text = line.decode("utf-8")
        crc_hex, payload = text.split(" ", 1)
        payload = payload.rstrip("\n")
        if len(crc_hex) != 8:
            return None
        if int(crc_hex, 16) != (zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF):
            return None
        return json.loads(payload)
    except (ValueError, UnicodeDecodeError):
        return None


class RequestJournal:
    """Writer half. Construct one per live server; ``replay()`` (static)
    reads a directory without touching it."""

    def __init__(self, directory: str, segment_bytes: int = 1 << 20, fsync: bool = True):
        self.dir = os.path.abspath(directory)
        self.segment_bytes = int(segment_bytes)
        self.fsync = bool(fsync)
        os.makedirs(self.dir, exist_ok=True)
        self._seg_index = self._next_segment_index()
        # retirement boundary: everything below the index this writer
        # STARTED at predates this server's lifetime (the compaction may
        # itself span/seal several segments at or above it — those must
        # survive retirement)
        self._first_seg_index = self._seg_index
        self._fh = None
        self._buffer: List[bytes] = []
        self.records_written = 0
        self.segments_sealed = 0

    # --- writing ---------------------------------------------------------
    def append_submit(
        self,
        uid: int,
        prompt: np.ndarray,
        max_new_tokens: int,
        eos_token_id: Optional[int],
        tenant: str,
        generated: Optional[List[int]] = None,
        t_submit: Optional[float] = None,
        t_first: Optional[float] = None,
    ) -> None:
        rec = {
            "t": "s",
            "uid": int(uid),
            "prompt": np.asarray(prompt, np.int32).tolist(),
            "max": int(max_new_tokens),
            "eos": None if eos_token_id is None else int(eos_token_id),
            "tenant": str(tenant),
        }
        if generated:
            rec["gen"] = [int(t) for t in generated]
        if t_submit is not None:
            rec["ts"] = float(t_submit)
        if t_first is not None:
            rec["tf"] = float(t_first)
        self._buffer.append(_encode(rec))

    def append_emit(self, uid: int, token: int) -> None:
        self._buffer.append(_encode({"t": "e", "uid": int(uid), "tok": int(token)}))

    def append_first_token(self, uid: int, t_first: float) -> None:
        """One-time stamp of the request's first emission (one record per
        request, not per token): replay preserves TTFT for requests
        re-routed mid-stream — without it, a dead replica's mid-stream
        requests would recompute TTFT from their post-kill re-emission,
        overstating the very latency the fleet bench reports."""
        self._buffer.append(
            _encode({"t": "t", "uid": int(uid), "tf": float(t_first)})
        )

    def append_finish(self, uid: int) -> None:
        self._buffer.append(_encode({"t": "f", "uid": int(uid)}))

    def append_migrate(self, uid: int) -> None:
        """The request migrated to another replica: replaying THIS journal
        must no longer produce it (its authoritative state — including
        every journaled emission — was re-seeded into the target replica's
        journal before this record is written, so no crash window loses
        it; a crash BETWEEN the two journals double-claims the uid and the
        fleet router dedupes on adoption)."""
        self._buffer.append(_encode({"t": "m", "uid": int(uid)}))

    def sync(self) -> None:
        """Flush buffered records to the active segment and make them
        durable — called once per scheduler step. Rotates (seals) the
        segment past ``segment_bytes``."""
        if not self._buffer:
            return
        fh = self._ensure_open()
        data = b"".join(self._buffer)
        self.records_written += len(self._buffer)
        self._buffer.clear()
        fh.write(data)
        fh.flush()
        if self.fsync:
            os.fsync(fh.fileno())
        chaos.point("journal.append", path=fh.name)
        if fh.tell() >= self.segment_bytes:
            self._seal()

    def close(self) -> None:
        self.sync()
        if self._fh is not None:
            self._seal()

    def begin_compaction(self) -> None:
        """Seal the active segment and move the retirement boundary past
        every segment written so far. The caller then re-journals the
        server's FULL current state (seeded submits for live requests,
        submit+finish for unclaimed results) and ``sync()``s — the fresh
        segment alone replays to the same state — after which
        ``retire_older_segments()`` drops all pre-compaction segments.
        This is the live-server form of the restart-time compaction
        ``PagedServer.recover`` performs, used after fleet migrations so
        the source journal never accumulates records for requests that
        now live elsewhere."""
        self.sync()
        if self._fh is not None:
            self._seal()
        self._first_seg_index = self._seg_index

    def retire_older_segments(self) -> int:
        """Delete every segment from BEFORE this writer's lifetime. Call
        ONLY after a full compaction has been synced through this writer
        (recovery re-journals every live request as a seeded submit AND
        every finished result, so the pre-restart segments are fully
        superseded) — this is what bounds journal growth across repeated
        crash/recover cycles. The boundary is the index the writer STARTED
        at, so a compaction large enough to seal its own segment(s) is
        never retired with the garbage. Returns the number removed."""
        removed = 0
        for path in self.segments(self.dir):
            name = os.path.basename(path)
            m = _SEG_SEALED.match(name) or _SEG_OPEN.match(name)
            if m and int(m.group(1)) < self._first_seg_index:
                os.remove(path)
                removed += 1
        if removed:
            fsync_dir(self.dir)
        return removed

    # --- internals -------------------------------------------------------
    def _open_path(self) -> str:
        return os.path.join(self.dir, f"seg_{self._seg_index:06d}.open")

    def _ensure_open(self):
        if self._fh is None:
            self._fh = open(self._open_path(), "ab")
        return self._fh

    def _seal(self) -> None:
        """Atomically promote the active segment to an immutable sealed
        one. The data is fsynced here UNCONDITIONALLY (one fsync per
        segment, even under ``fsync=False``): a sealed segment claims
        full validity, and replay treats CRC damage inside one as
        corruption — so its bytes must actually be on disk before the
        rename makes that claim."""
        fh, self._fh = self._fh, None
        path = fh.name
        try:
            os.fsync(fh.fileno())
        except OSError:
            pass
        fh.close()
        sealed = os.path.join(self.dir, f"seg_{self._seg_index:06d}.jrnl")
        os.replace(path, sealed)
        fsync_dir(self.dir)
        self._seg_index += 1
        self.segments_sealed += 1

    def _next_segment_index(self) -> int:
        idx = -1
        for name in os.listdir(self.dir):
            m = _SEG_SEALED.match(name) or _SEG_OPEN.match(name)
            if m:
                idx = max(idx, int(m.group(1)))
        return idx + 1

    # --- replay ----------------------------------------------------------
    @staticmethod
    def segments(directory: str) -> List[str]:
        """All segment paths in append order (sealed and open interleave by
        index; an index with both is the impossible case a crash during
        seal cannot produce — ``os.replace`` is atomic — and is rejected)."""
        directory = os.path.abspath(directory)
        if not os.path.isdir(directory):
            return []
        by_index: Dict[int, str] = {}
        for name in sorted(os.listdir(directory)):
            m = _SEG_SEALED.match(name) or _SEG_OPEN.match(name)
            if not m:
                continue
            idx = int(m.group(1))
            if idx in by_index:
                raise JournalCorruptError(
                    f"journal {directory}: segment {idx} exists both sealed "
                    f"and open ({by_index[idx]} vs {name})"
                )
            by_index[idx] = os.path.join(directory, name)
        return [by_index[i] for i in sorted(by_index)]

    @staticmethod
    def replay(directory: str) -> Tuple[Dict[int, JournaledRequest], int]:
        """Rebuild request state from the journal: ``(states, next_uid)``.

        Tolerates exactly the damage crashes can cause — torn TAILS of
        unsealed (``.open``) segments (dropped, with a log line; repeated
        crash/recover cycles leave one per crash). Anything else raises
        :class:`JournalCorruptError`."""
        states: Dict[int, JournaledRequest] = {}
        next_uid = 0
        seg_paths = RequestJournal.segments(directory)
        for path in seg_paths:
            sealed = path.endswith(".jrnl")
            with open(path, "rb") as f:
                lines = f.readlines()
            bad_at = None
            records = []
            for li, line in enumerate(lines):
                rec = _decode(line)
                if rec is None:
                    bad_at = li
                    break
                records.append(rec)
            if bad_at is not None:
                # a torn TAIL of any UNSEALED segment is a crash artifact:
                # each crash leaves its .open segment torn in place and the
                # restarted writer opens the next index, so several torn
                # .open tails can legitimately coexist after repeated
                # crashes. Sealed segments are immutable-by-construction and
                # valid records after a broken one cannot come from a tear.
                torn_tail = (
                    not sealed
                    and all(_decode(l) is None for l in lines[bad_at:])
                )
                if not torn_tail:
                    raise JournalCorruptError(
                        f"journal segment {path}: record {bad_at} fails its "
                        "CRC"
                        + (
                            " inside a sealed segment"
                            if sealed
                            else " with valid records after it"
                        )
                        + " — this is corruption, not a torn crash tail"
                    )
                dropped = len(lines) - bad_at
                logger.warning(
                    f"journal {path}: dropping {dropped} torn tail record(s) "
                    "(crash mid-append)"
                )
            for rec in records:
                uid = int(rec["uid"])
                next_uid = max(next_uid, uid + 1)
                if rec["t"] == "s":
                    states[uid] = JournaledRequest(
                        uid=uid,
                        prompt=np.asarray(rec["prompt"], np.int32),
                        max_new_tokens=int(rec["max"]),
                        eos_token_id=rec.get("eos"),
                        tenant=rec.get("tenant", "default"),
                        generated=[int(t) for t in rec.get("gen", [])],
                        t_submit=rec.get("ts"),
                        t_first=rec.get("tf"),
                    )
                elif rec["t"] == "e":
                    if uid in states:
                        states[uid].generated.append(int(rec["tok"]))
                elif rec["t"] == "t":
                    if uid in states:
                        states[uid].t_first = rec.get("tf")
                elif rec["t"] == "f":
                    if uid in states:
                        states[uid].finished = True
                elif rec["t"] == "m":
                    # migrated out: the target replica's journal owns the
                    # request now — replaying this one must not clone it
                    states.pop(uid, None)
        return states, next_uid

    @staticmethod
    def has_records(directory: str) -> bool:
        try:
            return bool(RequestJournal.segments(directory))
        except JournalCorruptError:
            return True
