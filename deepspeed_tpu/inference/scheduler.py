"""Continuous-batching scheduler over the paged KV pool.

The dense decode path (``inference/decode.py:generate``) runs fixed-shape
lockstep batches: every sequence prefills together, decodes together, and
the whole batch holds its HBM until the longest row finishes. This module
replaces that with request-level scheduling (DeepSpeed-Inference / Orca /
vLLM style):

* requests are **admitted** whenever a slot and enough pages exist, and
  **evicted** the step they finish — cache HBM tracks live tokens;
* prompts prefill in fixed-size **chunks interleaved with decode steps**,
  so a long prompt never stalls tokens already streaming;
* when the pool runs dry the **youngest** running request is preempted
  (pages freed, request requeued); greedy decoding makes its recomputed
  continuation token-exact, so preemption is invisible in the output;
* with **speculative decoding** enabled, each round first asks a host-side
  ``Drafter`` (``inference/spec_decode.py``) for up to K plausible next
  tokens per running request, then verifies drafts + bonus token in ONE
  dispatch of a (bucket, K)-shaped program — the accepted prefix advances
  ``mean accepted + 1`` tokens per dispatch, the rejected tail's pages roll
  back to the free list, and greedy outputs stay byte-identical to
  speculation-off serving (the verify program argmax-compares in-program);
* with **prefix caching** enabled the pool's hash-of-block index
  (``inference/kv_pool.py``) is consulted at admission: the longest cached
  full-page prefix of the request's context attaches by reference (its KV
  pays nothing), prefill resumes after it realigned to the cold-prefill
  chunk grid (so every position is computed by the same (chunk, row)
  geometry — byte-identical streams), and each newly filled full page is
  published back to the index;
* in **ragged** mode (the default) every scheduler step is ONE dispatch
  of the unified ``build_ragged_step`` program: prefill chunks, pending
  decode tokens, and drafted verify rows pack into a single
  ``[max_slots, W]`` window whose per-row ``(kv_len, q_len)`` metadata
  ride in as arrays (Ragged Paged Attention, arXiv 2604.15464) — so
  chunked prefill COEXISTS with decoding instead of stealing steps,
  spec-K varies per request, and shifting the mix never retraces. Total
  compiled serving programs is ≤ 2 (the narrow decode/verify width plus
  the chunk-covering mixed width), vs the bucketed matrix's dozens;
* with **multi-step windows** armed (``inference.paged_kv.multi_step``)
  a step whose running set is STABLE — nothing queued, nothing
  prefilling, no drafts, no preemption pressure — dispatches ONE fused
  program of up to ``horizon`` plain-decode rounds
  (``decode.py:build_ragged_multistep``): per-row EOS/budget stopping
  masks freeze finished rows in-program (trash-page writes), the page
  table rides in pre-reserved for the whole window's growth, and the
  host pays its dispatch gap, packing, emit, and journal sync once per
  window instead of once per token (dispatches/token → 1/horizon). Any
  scheduling event breaks back to the single-step path — streams stay
  byte-identical, and ``window_break_reasons`` names every break;
* in **bucketed** mode (``ragged=False`` — kept as the token-exactness
  oracle) compiled-program count is bounded by the **slot-count buckets**
  (× the **spec lengths** when speculating): each round dispatches ONE
  program shaped to the smallest bucket covering the running set, and
  each prompt chunk one fixed-chunk prefill program. Steady state is one
  dispatch per round, ≤1 compile per (bucket[, spec length]) — enforced
  by the serving tests via the engine's compile telemetry. Greedy streams
  are byte-identical across the two modes. Prefix sharing adds zero
  dispatches and zero programs in either: attach/register are host-side
  table and hash work;
* admission order and preemption victims are delegated to a
  ``SchedulingPolicy`` (default: FIFO admission, youngest-first
  preemption — the original behavior). ``inference/traffic.py`` layers
  SLA-aware multi-tenant scheduling on the same hooks.

``InferenceEngine.serve()`` (``inference/engine.py``) owns a ``PagedServer``
configured from the ``inference.paged_kv`` + ``inference.spec_decode`` (+
``inference.traffic``) knobs.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.inference.decode import (
    build_paged_decode_step,
    build_paged_prefill,
    build_paged_verify_step,
    build_ragged_multistep,
    build_ragged_step,
)
from deepspeed_tpu.inference.journal import JournaledRequest, RequestJournal
from deepspeed_tpu.inference.kv_pool import PagePool
from deepspeed_tpu.inference.spec_decode import Drafter, NGramDrafter
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.tracer import (
    NULL_TRACER,
    MetricsRegistry,
    percentile_summary,
)
from deepspeed_tpu.utils import chaos


def _spec_knob(spec, name, default):
    """Read a knob off a SpecDecodeConfig, a plain dict, or None."""
    if spec is None:
        return default
    if isinstance(spec, dict):
        return spec.get(name, default)
    return getattr(spec, name, default)


def compiled_serving_programs(compile_stats: Dict) -> int:
    """Count the serving programs a telemetry snapshot saw compile: every
    ``paged_*`` entry (the unified ``paged_<kind>_r<rows>_w<width>`` naming
    across the decode/prefill/verify/ragged/multistep builders) with at
    least one cold dispatch. The ragged compile-budget gate asserts this
    ≤ 2 for a full mixed serve — ≤ 4 with a multi-step window horizon
    armed; ``bench.py`` records it as ``compiled_programs``."""
    return sum(
        1
        for name, rec in compile_stats.items()
        if name.startswith("paged_") and rec.get("compiles", 0) > 0
    )


class SchedulingPolicy:
    """Admission-order / preemption-victim policy for ``PagedServer``.

    The defaults reproduce the original single-policy behavior: FIFO
    admission (head of the queue or nothing — no head-of-line bypass) and
    youngest-first recompute preemption. ``inference/traffic.py``'s
    ``SLAPolicy`` overrides these with per-tenant budget/priority
    scheduling; the ``on_*`` hooks feed it the accounting."""

    def next_admission(
        self, queue: Sequence["Request"], server: "PagedServer"
    ) -> Optional["Request"]:
        return queue[0] if queue else None

    def preemption_victim(
        self,
        candidates: Sequence["Request"],
        server: "PagedServer",
        for_req: Optional["Request"] = None,
    ) -> "Request":
        return candidates[-1]  # latest admission

    def on_admit(self, req: "Request", server: "PagedServer") -> None:
        pass

    def on_emit(self, req: "Request", server: "PagedServer") -> None:
        pass

    def on_finish(self, req: "Request", server: "PagedServer") -> None:
        pass


class YoungestFirstPolicy(SchedulingPolicy):
    """The original policy, by its name."""


@dataclass
class Request:
    """One generation request moving through the scheduler."""

    uid: int
    prompt: np.ndarray  # [Lp] int32, immutable
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    tenant: str = "default"
    generated: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    consumed: int = 0  # prefill progress over context()
    pending: Optional[int] = None  # sampled but not yet written token
    done: bool = False
    admissions: int = 0  # > 1 means the request was preempted and resumed
    prefix_cached: int = 0  # context tokens attached from the prefix index
    spec_drafted: int = 0  # draft tokens this request sent to verification
    spec_accepted: int = 0  # draft tokens accepted for this request
    t_submit: float = 0.0  # server-clock timestamps for TTFT / TPOT
    t_first: Optional[float] = None
    t_finish: Optional[float] = None
    # capacity-doubling context buffer: context() sits on the serving hot
    # path (drafting reads it every speculative round), so appending the
    # newly emitted tokens must not re-concatenate the whole history
    _ctx_buf: Optional[np.ndarray] = field(default=None, repr=False)
    _ctx_len: int = field(default=0, repr=False)

    def context(self) -> np.ndarray:
        """Tokens to (re)compute on admission: the prompt plus everything
        already emitted — after a preemption the resumed prefill re-derives
        the exact greedy continuation. Returns a read-only view; amortized
        cost is O(tokens emitted since the last call)."""
        n = self.prompt.size + len(self.generated)
        buf = self._ctx_buf
        if buf is None or buf.size < n:
            grown = np.empty(max(16, 2 * n), np.int32)
            grown[: self.prompt.size] = self.prompt
            grown[self.prompt.size : n] = self.generated
            self._ctx_buf = buf = grown
        elif self._ctx_len < n:
            buf[self._ctx_len : n] = self.generated[self._ctx_len - self.prompt.size :]
        self._ctx_len = n
        view = buf[:n]
        view.flags.writeable = False  # a mutating Drafter must not corrupt
        return view                   # the re-prefill source after preemption

    def output(self) -> np.ndarray:
        return self.context().copy()


def _default_buckets(max_slots: int) -> List[int]:
    """Powers of two up to and including max_slots."""
    buckets, b = [], 1
    while b < max_slots:
        buckets.append(b)
        b *= 2
    buckets.append(max_slots)
    return sorted(set(buckets))


class PagedServer:
    """Owns the page pool, the per-bucket compiled programs, and the
    admit → prefill-chunk → decode-step loop."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        page_size: int = 16,
        num_pages: int = 0,
        max_slots: int = 8,
        slot_buckets: Optional[Sequence[int]] = None,
        max_seq_len: int = 0,
        prefill_chunk: int = 32,
        attn_impl: str = "auto",
        dtype=None,
        telemetry=None,
        spec_decode=None,
        drafter: Optional[Drafter] = None,
        prefix_cache: bool = False,
        policy: Optional[SchedulingPolicy] = None,
        clock=None,
        ragged: bool = True,
        multi_step=None,
        journal: Optional[RequestJournal] = None,
        tracer=None,
        metrics: Optional[MetricsRegistry] = None,
        tp=None,
    ):
        self.cfg = cfg
        # tensor-parallel serving (inference/tp.py:TPServing): the SAME
        # ragged programs run under shard_map on the mesh — weights
        # column/row-parallel, kv pages sharded on the kv-head axis, page
        # TABLES (and every other host structure: queues, prefix index,
        # journal, fleet routing) replicated and untouched. Requires the
        # ragged path: the bucketed oracle stays single-chip by contract.
        self.tp = tp
        # MoE serving (ISSUE 20): the per-layer "moe" subtree routes inside
        # the same paged programs (decode.py:_moe_ffn) — but only when the
        # expert stack scans with the layers. Interleaved dense/MoE stacks
        # (moe_layer_freq > 1) keep expert params OUTSIDE params["layers"],
        # which the scanned serving body cannot see; and expert placement is
        # the 'expert' mesh axis, not a TP weight split.
        is_moe = isinstance(params, dict) and (
            "moe" in params.get("layers", {}) or "moe_layers" in params
        )
        if is_moe and "moe_layers" in params:
            raise NotImplementedError(
                "paged serving supports MoE only with moe_layer_freq == 1 "
                "(a scanned [L, E, ...] expert stack); interleaved "
                "dense/MoE stacks keep experts outside the layer scan"
            )
        if is_moe and tp is not None and tp.degree > 1:
            raise NotImplementedError(
                "tensor-parallel MoE serving is not supported: expert "
                "placement is the 'expert' mesh axis, not a TP weight split"
            )
        if tp is not None:
            if not ragged:
                raise ValueError(
                    "tensor-parallel serving runs the ragged path: enable "
                    "paged_kv.ragged (the bucketed oracle is single-chip)"
                )
            if tp.degree > 1:
                tp.validate_cfg(cfg)
            params = tp.shard_params(cfg, params)
        self.params = params
        # unified tracing (profiling/tracer.py): per-step phase spans
        # (admit / pack / dispatch / emit / journal_sync) and per-request
        # lifecycle spans (submit → admit → first_token → finish, with
        # tenant / prefix-hit / spec-accept attributes). Host-side only —
        # the step's device work stays one enqueue + one budgeted fetch.
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.prefill_chunk = int(prefill_chunk)
        self.attn_impl = attn_impl
        self.telemetry = telemetry
        self.prefix_cache = bool(prefix_cache)
        # ragged (default): every step is ONE dispatch of the unified
        # build_ragged_step program — mixed prefill/decode/verify rows,
        # per-row (kv_len, q_len) metadata, ≤2 compiled programs total.
        # ragged=False keeps the bucketed per-shape programs as the
        # token-exactness oracle.
        self.ragged = bool(ragged)
        # multi-step windows (inference.paged_kv.multi_step): when the
        # running set is STABLE — nothing queued, nothing prefilling, no
        # drafts, no preemption pressure — a step dispatches ONE fused
        # program of `horizon` plain-decode rounds (decode.py:
        # build_ragged_multistep), paying the host dispatch gap, packing,
        # and journal sync once per window instead of once per token. Any
        # scheduling event falls back to the single-step ragged path, so
        # prefix cache, CoW, SLA tenancy, spec decode, and the journal
        # ride unchanged and streams stay byte-identical.
        self.ms_enable = bool(_spec_knob(multi_step, "enable", False))
        self.ms_horizon = int(_spec_knob(multi_step, "horizon", 8))
        if self.ms_enable and not self.ragged:
            raise ValueError(
                "multi_step windows run over the ragged serving path: "
                "enable paged_kv.ragged (or disable paged_kv.multi_step)"
            )
        if self.ms_enable and self.ms_horizon < 2:
            raise ValueError(
                f"multi_step.horizon must be >= 2 (1 is the single-step "
                f"path), got {self.ms_horizon}"
            )
        # drafts handed from a failed window-eligibility probe to the
        # single-step fallback, so a (possibly stateful) Drafter is asked
        # at most once per scheduler step
        self._predrafts: Optional[Dict[int, np.ndarray]] = None
        self.policy = policy or YoungestFirstPolicy()
        # crash-recovery journal (inference/journal.py): admissions and
        # emitted tokens are appended per event and made durable ONCE per
        # scheduler step (journal.sync() at the end of step()); restart
        # replays it via recover() and every stream resumes byte-identically
        self.journal = journal
        # injectable clock: TTFT/TPOT stamps and the load harness's virtual
        # time both read it (default: wall)
        self.clock = clock or time.perf_counter
        # speculation: a SpecDecodeConfig / dict of knobs, or an explicit
        # Drafter instance (tests inject oracles this way) — either enables
        self.max_draft = int(_spec_knob(spec_decode, "max_draft", 4))
        lens = [int(l) for l in (_spec_knob(spec_decode, "spec_lens", None) or [])]
        self.spec_lens = sorted(set(lens)) or [self.max_draft]
        if drafter is None and _spec_knob(spec_decode, "enable", False):
            drafter = NGramDrafter(
                ngram_order=int(_spec_knob(spec_decode, "ngram_order", 3))
            )
        self.drafter = drafter
        if self.drafter is not None and (
            self.max_draft < 1 or any(l < 1 for l in self.spec_lens)
        ):
            raise ValueError(
                f"speculation needs max_draft >= 1 and spec_lens >= 1, got "
                f"max_draft={self.max_draft} spec_lens={self.spec_lens}"
            )
        if self.drafter is not None and attn_impl == "auto":
            from deepspeed_tpu.utils.logging import logger

            # byte-identical spec-on/spec-off streams are guaranteed when
            # decode and verify score through one backend; "auto" on TPU
            # mixes the Pallas decode kernel with XLA verify scoring, where
            # an argmax near-tie could in principle resolve differently
            logger.warning(
                "speculative serving with attn_impl='auto': greedy streams "
                "are exact per attention backend; pin attn_impl='xla' for a "
                "strict byte-identical guarantee vs speculation-off serving"
            )
        # drafts are clamped to the widest compiled verify program
        # (bucketed) / the decode-row window width (ragged)
        self._draft_cap = min(self.max_draft, self.spec_lens[-1])
        # the two ragged widths: decode/verify rows need 1 + draft_cap
        # slots, prefill chunks need prefill_chunk — a step dispatches the
        # narrow program unless it carries a chunk row, so total compiled
        # serving programs is ≤ 2 regardless of traffic
        self._ragged_w_decode = (self._draft_cap + 1) if self.drafter is not None else 1
        self._ragged_w_mixed = max(self.prefill_chunk, self._ragged_w_decode)
        max_seq = int(max_seq_len or cfg.max_seq_len)
        if num_pages <= 0:
            # worst-case sizing: every slot at max length, plus the trash
            # page — no preemption can ever trigger. Shrink num_pages to
            # oversubscribe HBM and trade it for preemptions.
            num_pages = max_slots * (-(-max_seq // page_size)) + 1
        self.pool = PagePool(
            cfg, num_pages, page_size, max_slots,
            max_seq_len=max_seq, dtype=dtype,
            kv_sharding=None if tp is None else tp.kv_sharding,
        )
        buckets = sorted(set(int(b) for b in (slot_buckets or _default_buckets(max_slots))))
        if buckets[-1] < max_slots:
            buckets.append(max_slots)
        if any(b < 1 for b in buckets):
            raise ValueError(f"slot buckets must be >= 1, got {buckets}")
        self.buckets = buckets
        self._queue: deque[Request] = deque()
        self._active: List[Request] = []  # admission order (oldest first)
        self._results: Dict[int, np.ndarray] = {}
        self._next_uid = 0
        # per-tenant serving observability (created lazily per tenant name):
        # request counters, emitted tokens, and bounded TTFT/TPOT samples
        self._tenant_stats: Dict[str, Dict] = {}
        # (tenant, ttft_ms, tpot_ms|None, n_tokens) per finished request —
        # the load harness derives SLA goodput from this
        self._finished_log: deque = deque(maxlen=65536)
        # migrated-out records appended since the last full compaction —
        # the journal's garbage counter (see finalize_migration)
        self._migrated_since_compact = 0
        # requests that migrated to a JOURNAL-LESS replica: THIS journal
        # keeps their only durable claim (state as of the migration) until
        # the fleet reports them finished — see retain_migrated_claim
        self._foreign_claims: Dict[int, "JournaledRequest"] = {}
        self.stats = {
            "admitted": 0,
            "preempted": 0,
            "finished": 0,
            "recovered": 0,  # live requests rebuilt from the journal
            "migrated_out": 0,  # live requests extracted for fleet migration
            "migrated_in": 0,  # live requests adopted from another replica
            "journal_compactions": 0,  # full-state rewrites (amortized)
            "prefix_cached_tokens": 0,  # context tokens attached, not prefilled
            "prefill_chunks": 0,
            # ragged mode: every scheduler step is ONE ragged dispatch;
            # decode_steps / spec_rounds then count the dispatches that
            # carried plain-decode / drafted rows (a mixed dispatch can
            # count as both)
            "ragged_steps": 0,
            # multi-step windows: one fused horizon-round dispatch each;
            # `dispatches` counts EVERY serving dispatch (windows, ragged
            # steps, bucketed prefill/decode/verify) and `emitted_tokens`
            # every generated token, so dispatches_per_token is derivable
            "window_steps": 0,
            "dispatches": 0,
            "emitted_tokens": 0,
            # why a window could not form (admission pending, a row mid
            # prefill, drafts proposed, page-pool reservation pressure) or
            # ended before its horizon (EOS / token budget) — the
            # steady-state postmortem counters. "pool" and "budget" need
            # OPPOSITE remediations (grow the pool vs lower the horizon),
            # so they are never folded together
            "window_break_reasons": {
                "admission": 0, "prefill": 0, "draft": 0, "eos": 0,
                "budget": 0, "pool": 0,
            },
            "decode_steps": 0,  # plain (non-speculative) decode dispatches
            "spec_rounds": 0,  # verify dispatches (one per speculative round)
            "spec_drafted": 0,  # draft tokens sent to verification
            "spec_accepted": 0,  # draft tokens accepted
            # draft-hit histogram: accept_hist[n] counts (request, round)
            # pairs whose accepted prefix was exactly n drafts long
            "spec_accept_hist": [0] * (self._draft_cap + 1),
        }

    # --- request intake -------------------------------------------------
    def _tenant(self, name: str) -> Dict:
        ts = self._tenant_stats.get(name)
        if ts is None:
            ts = self._tenant_stats[name] = {
                "submitted": 0,
                "finished": 0,
                "tokens": 0,
                "ttft_ms": deque(maxlen=4096),
                "tpot_ms": deque(maxlen=4096),
            }
        return ts

    def queued_count(self, tenant: Optional[str] = None) -> int:
        if tenant is None:
            return len(self._queue)
        return sum(1 for r in self._queue if r.tenant == tenant)

    def live_count(self, tenant: Optional[str] = None) -> int:
        if tenant is None:
            return len(self._active)
        return sum(1 for r in self._active if r.tenant == tenant)

    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        tenant: str = "default",
    ) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + int(max_new_tokens)
        if total > self.pool.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} exceeds "
                f"the serving max_seq_len {self.pool.max_seq_len}"
            )
        if self.pool.pages_for(total) > self.pool.num_pages - 1:
            raise ValueError(
                f"request needs {self.pool.pages_for(total)} pages but the pool "
                f"holds {self.pool.num_pages - 1} allocatable"
            )
        uid = self._next_uid
        self._next_uid += 1
        now = self.clock()
        self._queue.append(
            Request(uid=uid, prompt=prompt, max_new_tokens=int(max_new_tokens),
                    eos_token_id=eos_token_id, tenant=tenant,
                    t_submit=now)
        )
        self._tenant(tenant)["submitted"] += 1
        # the request's lifecycle span opens at submit (queue wait included,
        # matching the TTFT definition) and closes at finish
        self.tracer.begin_async("request", uid, f"req{uid}", tenant=tenant)
        if self.journal is not None:
            self.journal.append_submit(
                uid, prompt, int(max_new_tokens), eos_token_id, tenant,
                t_submit=now,
            )
            # admissions are durable at submit time, not at the next step:
            # a request accepted then crashed-on must survive the restart
            self.journal.sync()
        return uid

    def recover(
        self,
        states: Dict[int, "JournaledRequest"],
        next_uid: int = 0,
        migrated_in: bool = False,
    ) -> int:
        """Rebuild the server from replayed journal state (a restart after
        a crash). Finished requests land directly in the results map (their
        output is fully journaled); every live request is re-queued with
        its journaled emissions pre-seeded, so its re-admission prefills
        ``prompt + generated`` on the cold chunk grid — the exact machinery
        that makes recompute-preemption invisible — and the stream resumes
        **byte-identically** from its last emitted token. Prefix caching
        (when on) makes re-prefill of shared prompts nearly free. Every
        replayed request — live ones as seeded submit records, finished
        ones as seeded submit+finish — is re-journaled into the fresh
        segment, which then alone replays to the same state, so the
        superseded pre-crash segments are retired (journal growth stays
        bounded across crash/recover cycles). Returns the number of live
        requests recovered.

        ``migrated_in=True`` is the LIVE-fleet form (this server is a
        migration/re-route target in a running fleet): the requests'
        original ``t_submit``/``t_first`` stamps are preserved — the fleet
        shares one clock, and resetting them would erase pre-move queue
        wait from TTFT, flattering exactly the requests a kill hurt — and
        the tenant ``submitted``/``recovered`` counters are NOT bumped
        (the source replica already counted them and stays in the merged
        stats); inbound moves count under ``stats['migrated_in']``. The
        default is the fresh-process form: stamps restart with the clock
        and the counters are this server's to claim."""
        recovered = 0
        for uid in sorted(states):
            st = states[uid]
            if st.done:
                out = np.concatenate(
                    [np.asarray(st.prompt, np.int32),
                     np.asarray(st.generated, np.int32)]
                )
                self._results[uid] = out
                if self.journal is not None:
                    # finished results ride the compacted segment too, so
                    # the pre-crash segments become fully superseded and
                    # retire_older_segments below can drop them
                    self.journal.append_submit(
                        uid, st.prompt, st.max_new_tokens, st.eos_token_id,
                        st.tenant, generated=st.generated,
                    )
                    self.journal.append_finish(uid)
                continue
            req = Request(
                uid=uid, prompt=np.asarray(st.prompt, np.int32),
                max_new_tokens=int(st.max_new_tokens),
                eos_token_id=st.eos_token_id, tenant=st.tenant,
                generated=[int(t) for t in st.generated],
                t_submit=(
                    st.t_submit
                    if migrated_in and st.t_submit is not None
                    else self.clock()
                ),
                t_first=st.t_first if migrated_in else None,
            )
            self._queue.append(req)
            # re-open the request's lifecycle span on THIS timeline —
            # extraction (or the crash) closed/lost the previous one, and
            # _finish will end this span when the stream completes
            self.tracer.begin_async("request", uid, f"req{uid}", tenant=st.tenant)
            if not migrated_in:
                self._tenant(st.tenant)["submitted"] += 1
            if self.journal is not None:
                # re-seed with the Request's OWN stamps (not st's): they are
                # consistent with this server's clock domain whichever path
                # built the request
                self.journal.append_submit(
                    uid, st.prompt, st.max_new_tokens, st.eos_token_id,
                    st.tenant, generated=st.generated,
                    t_submit=req.t_submit, t_first=req.t_first,
                )
            recovered += 1
        self._next_uid = max(self._next_uid, int(next_uid))
        self.stats["migrated_in" if migrated_in else "recovered"] += recovered
        if self.journal is not None:
            # the compaction (seeded submits + finished results) is durable
            # before the superseded pre-crash segments are dropped — this
            # bounds journal growth across repeated crash/recover cycles
            self.journal.sync()
            self.journal.retire_older_segments()
        return recovered

    def extract_request(self, uid: int) -> Optional["JournaledRequest"]:
        """Remove a live or queued request from THIS server and return its
        replay state — the source half of a fleet migration
        (``inference/fleet.py``): the target re-admits the state via
        ``recover()``, re-prefills ``prompt + generated`` on the cold
        chunk grid (the recompute-preemption machinery, ~free for shared
        prompts under prefix caching), and the stream continues
        byte-identically from its last emitted token. No journal record
        is written here — the request's journal hand-off happens in
        ``finalize_migration`` AFTER the target has durably re-seeded it,
        so no crash instant leaves the request claimed by neither
        journal. Returns None when the uid is not live here (already
        finished or never admitted)."""
        req = next((r for r in self._active if r.uid == uid), None)
        if req is not None:
            self.pool.free_slot(req.slot)
            req.slot = None
            req.pending = None
            req.consumed = 0
            self._active.remove(req)
        else:
            req = next((r for r in self._queue if r.uid == uid), None)
            if req is None:
                return None
            self._queue.remove(req)
        if self.drafter is not None:
            self.drafter.drop(uid)
        self.stats["migrated_out"] += 1
        if self.tracer.enabled:
            # close the request's lifecycle span on this timeline — the
            # target replica's timeline picks the request up at recover
            self.tracer.end_async(
                "request", uid, f"req{uid}", migrated=True,
                tokens=len(req.generated),
            )
        return JournaledRequest(
            uid=req.uid,
            prompt=np.asarray(req.prompt, np.int32),
            max_new_tokens=int(req.max_new_tokens),
            eos_token_id=req.eos_token_id,
            tenant=req.tenant,
            generated=[int(t) for t in req.generated],
            t_submit=req.t_submit,
            t_first=req.t_first,
        )

    def restore_request(self, state: "JournaledRequest") -> None:
        """Inverse of ``extract_request`` for a migration that found no
        target: re-queue the state on THIS server (stamps preserved — the
        clock never changed) and undo the extraction's migration
        accounting, since nothing actually moved."""
        self.recover({state.uid: state}, 0, migrated_in=True)
        self.stats["migrated_out"] -= 1
        self.stats["migrated_in"] -= 1

    def retain_migrated_claim(self, uid: int, state: "JournaledRequest") -> None:
        """The request migrated to a JOURNAL-LESS target, which can never
        durably claim it — so THIS journal must keep the claim (state as
        of the migration) or a crash finds the request in neither journal
        and its acked tokens are lost. The claim rides every compaction
        until ``release_migrated_claim``; tokens the target emits after
        the move were never durable anywhere, which is what running a
        journal-less replica means."""
        if self.journal is None:
            return
        self._foreign_claims[uid] = state

    def release_migrated_claim(self, uid: int) -> None:
        """The migrated-away request finished and its output was
        delivered: disclaim it (durability no longer matters once the
        caller holds the bytes), so a later replay cannot resurrect it."""
        if self._foreign_claims.pop(uid, None) is None:
            return
        if self.journal is not None:
            self.journal.append_migrate(uid)
            self.journal.sync()
            self._migrated_since_compact += 1
            self._maybe_compact_migrated()

    def _maybe_compact_migrated(self) -> None:
        """Compact when migrated-out garbage outweighs the live state
        still worth rewriting — the shared trigger for BOTH disclaim
        paths (finalize_migration and release_migrated_claim), so journal
        growth stays bounded even when every migration flows through
        journal-less targets."""
        if self._migrated_since_compact > len(self._queue) + len(self._active):
            self.compact_journal()

    def finalize_migration(self, uid: int) -> None:
        """Source-side journal hand-off after a migration landed on the
        target: append the migrated-out record (durable immediately — the
        source must not resurrect the request on a later replay), then
        compact only when the migrated-out garbage outweighs the live
        state still worth rewriting. A drain of N requests therefore pays
        O(N) total journal I/O (compactions at the halving points plus one
        final at empty, which is also what keeps the drained journal at
        ≤1 segment) instead of N full-state rewrites — and a single
        rebalancing move off a busy replica costs one record + sync, not
        a rewrite of every resident request."""
        if self.journal is None:
            return
        self.journal.append_migrate(uid)
        self.journal.sync()
        self._migrated_since_compact += 1
        self._maybe_compact_migrated()

    def compact_journal(self) -> int:
        """Re-seed this server's FULL current state into a fresh journal
        segment and retire every older one (``journal.begin_compaction``):
        live requests as seeded submits, unclaimed finished results as
        byte-preserving submit+finish records (their original
        prompt/budget split is gone at finish — the replayed result is the
        output array verbatim, which is all a result needs). The live-
        server form of the compaction ``recover()`` performs on restart;
        ``finalize_migration`` triggers it when migrated-out garbage
        outweighs live state, so journal growth stays bounded. Returns
        the number of segments retired."""
        if self.journal is None:
            return 0
        self._migrated_since_compact = 0
        self.stats["journal_compactions"] += 1
        self.journal.begin_compaction()
        for st in self._foreign_claims.values():
            # claims held for requests living on journal-less replicas
            # survive the rewrite — dropping them here would silently
            # break the neither-journal-loses-it invariant
            self.journal.append_submit(
                st.uid, st.prompt, st.max_new_tokens, st.eos_token_id,
                st.tenant, generated=st.generated,
                t_submit=st.t_submit, t_first=st.t_first,
            )
        for req in list(self._queue) + list(self._active):
            self.journal.append_submit(
                req.uid, req.prompt, req.max_new_tokens, req.eos_token_id,
                req.tenant, generated=req.generated,
                t_submit=req.t_submit, t_first=req.t_first,
            )
        for uid, out in self._results.items():
            self.journal.append_submit(uid, out, 1, None, "default")
            self.journal.append_finish(uid)
        self.journal.sync()
        return self.journal.retire_older_segments()

    def has_work(self) -> bool:
        return bool(self._queue or self._active)

    def result(self, uid: int) -> Optional[np.ndarray]:
        return self._results.get(uid)

    def take_result(self, uid: int) -> Optional[np.ndarray]:
        """Pop a finished output: a long-lived server must not retain every
        output ever generated (both ``serve()`` fronts drain through this)."""
        return self._results.pop(uid, None)

    # --- one scheduler iteration ---------------------------------------
    def step(self) -> None:
        """Admit what fits, then run the round's device work: in ragged
        mode ONE dispatch covering every active row's next tokens (prefill
        chunks, pending decodes, and drafted verifies together) — or, with
        ``multi_step`` armed and the running set stable, ONE fused window
        of ``horizon`` plain-decode rounds; in bucketed mode one prefill
        dispatch per chunk followed by one decode/verify dispatch over the
        running set."""
        with self.tracer.span("serve.step"):
            with self.tracer.span("serve.admit"):
                self._admit()
            if self.ragged:
                if not (self.ms_enable and self._ragged_window()):
                    self._ragged_step(drafts=self._take_predrafts())
            else:
                with self.tracer.span("serve.prefill"):
                    self._prefill_step()
                with self.tracer.span("serve.decode"):
                    self._decode_step()
            # the round's device work and emissions happened; the chaos
            # point models dying BEFORE the journal flush — the un-synced
            # tokens are re-derived identically on recovery (greedy
            # re-prefill). A ChaosKilled unwinds through the open spans
            # (the flight recorder saw them as open at dump time).
            chaos.point("serve.mid_step")
            if self.journal is not None:
                with self.tracer.span("serve.journal_sync"):
                    self.journal.sync()
        self.metrics.counter("serve.steps").inc()

    def run(self) -> Dict[int, np.ndarray]:
        while self.has_work():
            self.step()
        return self._results

    def serve(
        self,
        prompts: Sequence,
        max_new_tokens=32,
        eos_token_id: Optional[int] = None,
        tenant: str = "default",
    ) -> List[np.ndarray]:
        """Submit a batch (scalar or per-request ``max_new_tokens``), run to
        completion, return outputs in submission order."""
        if isinstance(max_new_tokens, (int, np.integer)):
            max_new_tokens = [max_new_tokens] * len(prompts)
        if len(max_new_tokens) != len(prompts):
            raise ValueError(
                f"{len(prompts)} prompts but {len(max_new_tokens)} max_new_tokens"
            )
        uids = [
            self.submit(p, max_new_tokens=int(n), eos_token_id=eos_token_id,
                        tenant=tenant)
            for p, n in zip(prompts, max_new_tokens)
        ]
        self.run()
        return [self.take_result(u) for u in uids]

    # --- phases ---------------------------------------------------------
    def _admit(self) -> None:
        while self._queue:
            # the deque is handed to the policy directly (policies iterate /
            # peek, never mutate); the FIFO default peeks [0] so the common
            # path stays O(1) via popleft below
            req = self.policy.next_admission(self._queue, self)
            if req is None:
                break
            ctx = req.context()
            # reserve the whole context plus the first decode write so a
            # prefill can never die halfway through its own prompt; with
            # prefix caching the pool first attaches the longest indexed
            # prefix of the context by reference (match is capped to
            # ctx.size - 1, so at least one token always prefills and the
            # first output token has logits to come from)
            slot = self.pool.alloc_slot(
                ctx.size + 1,
                prefix_tokens=ctx if self.prefix_cache else None,
            )
            if slot is None:
                break
            if self._queue[0] is req:
                self._queue.popleft()
            else:
                self._queue.remove(req)
            req.slot = slot
            cached = int(self.pool.seq_lens[slot])
            req.consumed = cached
            req.prefix_cached = cached
            self.stats["prefix_cached_tokens"] += cached
            req.pending = None
            req.admissions += 1
            self._active.append(req)
            self.stats["admitted"] += 1
            self.tracer.instant_async(
                "request", req.uid, "admit",
                slot=slot, prefix_cached=cached, admissions=req.admissions,
            )
            self.policy.on_admit(req, self)

    def _next_chunk_len(self, req: "Request", ctx_size: int) -> int:
        """Tokens the request's next prefill chunk covers. A prefix attach
        that landed mid chunk-grid realigns to the cold-prefill chunk
        boundaries, so every position is computed by the same (chunk, row)
        geometry as sharing-off serving — byte-identical streams by
        construction."""
        C = self.prefill_chunk
        start = req.consumed
        real = min(C, ctx_size - start)
        if start % C:
            real = min(real, C - start % C)
        return real

    def _prefill_step(self) -> None:
        C = self.prefill_chunk
        prefill = build_paged_prefill(
            self.cfg, C, self.pool.page_size, attn_impl=self.attn_impl,
            telemetry=self.telemetry,
        )
        for req in [r for r in self._active if r.pending is None and not r.done]:
            ctx = req.context()
            start = req.consumed
            real = self._next_chunk_len(req, ctx.size)
            if not self.pool.prepare_write(req.slot, start + real):
                # unreachable: admission pre-reserved the whole context and
                # prefill never writes into attached (shared) pages
                raise RuntimeError(
                    f"prefill write barrier failed for slot {req.slot} "
                    f"({start}..{start + real})"
                )
            chunk = np.zeros((1, C), np.int32)
            chunk[0, :real] = ctx[start : start + real]
            pt, _ = self.pool.rows([req.slot])
            tok, new_k, new_v = prefill(
                self.params, chunk, self.pool.cache.k_pages, self.pool.cache.v_pages,
                pt, np.asarray([start], np.int32), np.int32(real - 1),
            )
            self.pool.set_cache(new_k, new_v)
            self.stats["dispatches"] += 1
            self.pool.advance(req.slot, real)
            req.consumed = start + real
            if self.prefix_cache:
                self.pool.register_prefix(req.slot, ctx, req.consumed)
            self.stats["prefill_chunks"] += 1
            if req.consumed == ctx.size:
                # the chunk's single host fetch: the first generated token
                self._emit(req, int(np.asarray(tok)[0]))  # lint: allow(DS-R005)

    def _decode_step(self) -> None:
        running = [r for r in self._active if r.pending is not None and not r.done]
        if not running:
            return
        if self.drafter is not None:
            drafts = self._propose_drafts(running)
            if any(d.size for d in drafts.values()):
                self._verify_round(running, drafts)
                return
            # nothing drafted anywhere: a verify dispatch would only carry
            # dead slots — fall through to the plain one-token program
        self._plain_decode_step(running)

    # --- the ragged one-program step -------------------------------------
    def _take_predrafts(self) -> Optional[Dict[int, np.ndarray]]:
        """Drafts a failed window probe already proposed this step (the
        Drafter is asked at most once per step — it may be stateful)."""
        drafts, self._predrafts = self._predrafts, None
        return drafts

    def _ragged_step(self, drafts: Optional[Dict[int, np.ndarray]] = None) -> None:
        """ONE dispatch for the whole round: every active row contributes
        its next tokens — a prefill chunk, the pending decode token, or the
        pending token plus host-side drafts — packed into a single
        ``[max_slots, W]`` window whose per-row ``(kv_len, q_len)`` metadata
        ride in as arrays. A chunk row no longer steals a step from
        decoders (they share the dispatch), spec-K varies freely per row,
        and only the WIDTH can differ between steps (narrow decode/verify
        vs chunk-covering mixed), bounding compiled programs at 2."""
        rows = [r for r in self._active if not r.done]
        if not rows:
            return
        with self.tracer.span("serve.pack") as pack_span:
            if drafts is None:
                drafts = {}
                if self.drafter is not None:
                    drafts = self._propose_drafts(
                        [r for r in rows if r.pending is not None]
                    )
            chunk_len: Dict[int, int] = {}
            need: Dict[int, int] = {}
            for r in rows:
                if r.pending is None:
                    chunk_len[r.uid] = self._next_chunk_len(r, r.context().size)
                    need[r.uid] = chunk_len[r.uid]
                else:
                    d = drafts.get(r.uid)
                    if d is None:
                        d = drafts[r.uid] = np.zeros(0, np.int32)
                    need[r.uid] = d.size + 1
            rows = self._reserve_for_growth(rows, need)
            if not rows:
                return
            W = (
                self._ragged_w_mixed
                if any(r.pending is None for r in rows)
                else self._ragged_w_decode
            )
            # pad to the single fixed row budget — never re-bucketed; lengths
            # == consumed for prefill rows, so one write base serves every mode
            R, page_table, lengths = self._dispatch_rows(rows, pad_to=self.pool.max_slots)
            tokens = np.zeros((R, W), np.int32)
            q_lens = np.zeros(R, np.int32)
            for i, r in enumerate(rows):
                if r.pending is None:
                    real = chunk_len[r.uid]
                    tokens[i, :real] = r.context()[r.consumed : r.consumed + real]
                    q_lens[i] = real
                else:
                    d = drafts[r.uid]
                    tokens[i, 0] = r.pending
                    tokens[i, 1 : 1 + d.size] = d
                    q_lens[i] = 1 + d.size
            pack_span.set(rows=len(rows), width=W)
        # dispatch = build + ENQUEUE only (jit returns futures; the fetch
        # below is where device time surfaces)
        with self.tracer.span("serve.dispatch", rows=len(rows), width=W):
            step_fn = build_ragged_step(
                self.cfg, R, W, self.pool.page_size, attn_impl=self.attn_impl,
                telemetry=self.telemetry, tp=self.tp,
            )
            out, new_k, new_v = step_fn(
                self.params, tokens, self.pool.cache.k_pages, self.pool.cache.v_pages,
                page_table, lengths, q_lens,
            )
            self.pool.set_cache(new_k, new_v)
        self.stats["ragged_steps"] += 1
        self.stats["dispatches"] += 1
        with self.tracer.span("serve.emit"):
            self._settle_ragged_rows(rows, out, chunk_len, q_lens)

    def _settle_ragged_rows(self, rows, out, chunk_len, q_lens) -> None:
        """Post-dispatch accounting for one ragged step: the budgeted host
        fetch, then per-row advance/emit/publish."""
        # the step's single host fetch: [R, W+1] = accepted counts + the
        # greedy token after each position
        out = np.asarray(out)  # lint: allow(DS-R005)
        had_decode = had_spec = False
        for i, r in enumerate(rows):
            if r.pending is None:
                real = chunk_len[r.uid]
                ctx = r.context()
                self.pool.advance(r.slot, real)
                r.consumed += real
                self.stats["prefill_chunks"] += 1
                if self.prefix_cache:
                    self.pool.register_prefix(r.slot, ctx, r.consumed)
                if r.consumed == ctx.size:
                    # the first generated token: greedy after the chunk's
                    # last real position
                    self._emit(r, int(out[i, real]))
                continue
            d = int(q_lens[i]) - 1
            if d:
                had_spec = True
            else:
                had_decode = True
            # acc is bounded by the drafted count in-program; all d+1
            # written positions advance first, then the rejected tail rolls
            # back — net advance is the accepted prefix + bonus token
            self._settle_spec_row(r, d, int(out[i, 0]), out[i])
        if had_decode:
            self.stats["decode_steps"] += 1
        if had_spec:
            self.stats["spec_rounds"] += 1

    # --- the multi-step window (one dispatch = N decode rounds) ----------
    def _window_break(self, reason: str) -> None:
        self.stats["window_break_reasons"][reason] += 1

    def _ragged_window(self) -> bool:
        """Try to serve this step as ONE fused window of ``ms_horizon``
        plain-decode rounds (``decode.py:build_ragged_multistep``). The
        window forms only when the running set is STABLE — no pending
        admissions, no row mid-prefill, no drafts proposed, every row's
        remaining budget worth amortizing, and the whole window's pages
        reservable WITHOUT preemption; any scheduling event records its
        break reason and returns False, and the caller falls back to the
        single-step ragged path (byte-identical streams either way — the
        window program freezes rows in-program exactly where sequential
        steps would retire them). Per-row EOS ids and token budgets ride
        in as arrays, so the fused program never overruns a stream."""
        rows = [r for r in self._active if not r.done]
        if not rows:
            return False
        if self._queue:
            # an admission is waiting: a window would starve its TTFT for
            # up to N rounds — serve single-step until the queue drains
            self._window_break("admission")
            return False
        if any(r.pending is None for r in rows):
            self._window_break("prefill")
            return False
        H = self.ms_horizon
        if max(r.max_new_tokens - len(r.generated) for r in rows) < H:
            # every row would freeze before the horizon: the single-step
            # tail is strictly cheaper than a mostly-frozen window
            self._window_break("budget")
            return False
        if self.drafter is not None:
            # stash the proposals whichever way the probe resolves: the
            # single-step fallback consumes them instead of re-asking a
            # (possibly stateful) Drafter twice in one step
            self._predrafts = drafts = self._propose_drafts(rows)
            if any(d.size for d in drafts.values()):
                # speculation outruns a plain-decode window
                self._window_break("draft")
                return False
        # pre-reserve the whole window's growth — ceil(N/page_size)+1
        # pages per row worst case — WITHOUT preempting: pool pressure is
        # a scheduling event, and the single-step path owns preemption.
        # Per row the reservation is min(H, remaining budget): the
        # in-program budget freeze bounds the row's writes to its budget,
        # so a near-finished row never demands pages (or max_seq_len
        # room) it cannot write — submit() guarantees len + budget fits
        need = {
            r.uid: min(H, r.max_new_tokens - len(r.generated)) for r in rows
        }
        if self._reserve_for_growth(rows, need, preempt=False) is None:
            self._window_break("pool")
            return False
        # the window dispatches: drop the (all-empty) stash — a later
        # step's fallback must ask the drafter fresh, not read this one
        self._predrafts = None
        with self.tracer.span("serve.window", rows=len(rows), horizon=H):
            with self.tracer.span("serve.pack") as pack_span:
                R, page_table, lengths = self._dispatch_rows(
                    rows, pad_to=self.pool.max_slots
                )
                tokens = np.zeros(R, np.int32)
                live = np.zeros(R, np.int32)
                eos_ids = np.full(R, -1, np.int32)
                budgets = np.zeros(R, np.int32)
                for i, r in enumerate(rows):
                    tokens[i] = r.pending
                    live[i] = 1
                    if r.eos_token_id is not None:
                        eos_ids[i] = r.eos_token_id
                    budgets[i] = r.max_new_tokens - len(r.generated)  # >= 1
                pack_span.set(rows=len(rows), horizon=H)
            with self.tracer.span("serve.dispatch", rows=len(rows), width=1,
                                  horizon=H):
                window_fn = build_ragged_multistep(
                    self.cfg, R, 1, H, self.pool.page_size,
                    attn_impl=self.attn_impl, telemetry=self.telemetry,
                    tp=self.tp,
                )
                out, new_k, new_v = window_fn(
                    self.params, tokens, self.pool.cache.k_pages,
                    self.pool.cache.v_pages, page_table, lengths, live,
                    eos_ids, budgets,
                )
                self.pool.set_cache(new_k, new_v)
            self.stats["window_steps"] += 1
            self.stats["dispatches"] += 1
            with self.tracer.span("serve.emit"):
                self._settle_window_rows(rows, out, H)
            # crash INSIDE the window's host phase: every emitted token of
            # the window sits in the journal buffer, none acked — recovery
            # replays from the last synced token and the greedy re-prefill
            # re-derives the window's tokens byte-identically
            chaos.point("serve.mid_window")
        return True

    def _settle_window_rows(self, rows, out, horizon: int) -> None:
        """Post-dispatch accounting for one window: the single budgeted
        host fetch (``[R, 1+N]`` = per-row emitted count + tokens), then
        per-row advance/emit/publish, amortized over up to N tokens per
        row. Rows that froze before the horizon name the window's break
        reason (EOS vs budget); surplus reserved pages go back to the
        pool so a parked reservation never starves the next admission."""
        out = np.asarray(out)  # lint: allow(DS-R005) — the window's one fetch
        eos_broke = budget_broke = False
        for i, r in enumerate(rows):
            n = int(out[i, 0])
            self.pool.advance(r.slot, n)
            for tok in out[i, 1 : 1 + n]:
                self._emit(r, int(tok))
            if r.done and n < horizon:
                if (
                    r.eos_token_id is not None
                    and r.generated
                    and r.generated[-1] == r.eos_token_id
                ):
                    eos_broke = True
                else:
                    budget_broke = True
            if not r.done:
                if self.prefix_cache:
                    self.pool.register_prefix(
                        r.slot, r.context(), int(self.pool.seq_lens[r.slot])
                    )
                self.pool.trim_reservation(r.slot)
        if eos_broke:
            self._window_break("eos")
        if budget_broke:
            self._window_break("budget")

    def _reserve_for_growth(self, running: List[Request], need: Dict[int, int],
                            preempt: bool = True) -> Optional[List[Request]]:
        """Make every running row writable for its next ``need[uid]`` tokens
        (default 1) — page growth plus the pool's copy-on-write barrier for
        any shared prefix page in the written span — preempting the
        policy's victim (default: youngest active request) when the pool is
        dry; vLLM's recompute preemption: the victim's greedy continuation
        is re-derived exactly on re-admission. Mutates and returns
        ``running`` (preempted rows leave the round).

        ``preempt=False`` is the multi-step window's reservation mode (a
        whole horizon's pages per row, up front): preemption pressure is a
        scheduling event that should BREAK the window, not evict anyone —
        on the first row the pool cannot host, every reservation this call
        already made is handed back (``trim_reservation``) and None is
        returned so the caller falls back to the single-step path."""
        idx = 0
        while idx < len(running):
            req = running[idx]
            grow = need.get(req.uid, 1)
            while not self.pool.prepare_write(
                req.slot, int(self.pool.seq_lens[req.slot]) + grow
            ):
                if not preempt:
                    for r in running[: idx + 1]:
                        self.pool.trim_reservation(r.slot)
                    return None
                candidates = [r for r in self._active if r is not req]
                if not candidates:
                    # unreachable while submit() validates total size, kept
                    # as a hard stop against a silent infinite loop
                    raise RuntimeError(
                        f"page pool exhausted by a single sequence (len "
                        f"{int(self.pool.seq_lens[req.slot])}): the pool holds "
                        f"{self.pool.num_pages - 1} pages x {self.pool.page_size} tokens"
                    )
                victim = self.policy.preemption_victim(candidates, self, for_req=req)
                self._preempt(victim)
                if victim in running:
                    vi = running.index(victim)
                    running.remove(victim)
                    if vi < idx:
                        idx -= 1
            idx += 1
        return running

    def _dispatch_rows(self, running: List[Request], pad_to: Optional[int] = None):
        """(rows, page_table, lengths) padded to ``pad_to`` rows (default:
        the smallest slot bucket covering the set; the ragged step passes
        its fixed row budget) — rows past ``len(running)`` are dead padding
        (-1 tables / length 0: trash-page semantics make them always
        safe)."""
        rows = pad_to or min(b for b in self.buckets if b >= len(running))
        page_table = np.full((rows, self.pool.max_pages_per_slot), -1, np.int32)
        lengths = np.zeros(rows, np.int32)
        rows_pt, rows_len = self.pool.rows([r.slot for r in running])
        n = len(running)
        page_table[:n] = rows_pt
        lengths[:n] = rows_len
        return rows, page_table, lengths

    def _settle_spec_row(self, req: Request, d: int, acc: int, out_row) -> None:
        """Post-dispatch accounting for one decode/verify row — advance all
        ``d + 1`` written positions, roll the rejected tail's pages back,
        update the speculation stats, emit the accepted prefix + bonus/
        correction token (stopping at EOS / budget), and republish the
        prefix. Shared verbatim by the bucketed verify round and the ragged
        step so the oracle and the default path cannot drift."""
        self.pool.advance(req.slot, d + 1)
        self.pool.rollback(req.slot, d - acc)
        self.stats["spec_drafted"] += d
        self.stats["spec_accepted"] += acc
        req.spec_drafted += d
        req.spec_accepted += acc
        if d:
            hist = self.stats["spec_accept_hist"]
            hist[min(acc, len(hist) - 1)] += 1
        for tok in out_row[1 : acc + 2]:
            self._emit(req, int(tok))
            if req.done:  # EOS / budget inside the accepted run
                break
        if self.prefix_cache and not req.done:
            # post-rollback length is the canonical accepted context
            self.pool.register_prefix(
                req.slot, req.context(), int(self.pool.seq_lens[req.slot])
            )

    def _plain_decode_step(self, running: List[Request]) -> None:
        running = self._reserve_for_growth(running, {})
        if not running:
            return
        bucket, page_table, lengths = self._dispatch_rows(running)
        tokens = np.zeros(bucket, np.int32)
        tokens[: len(running)] = [r.pending for r in running]
        decode = build_paged_decode_step(
            self.cfg, bucket, self.pool.page_size, attn_impl=self.attn_impl,
            telemetry=self.telemetry,
        )
        out, new_k, new_v = decode(
            self.params, tokens, self.pool.cache.k_pages, self.pool.cache.v_pages,
            page_table, lengths,
        )
        self.pool.set_cache(new_k, new_v)
        self.stats["decode_steps"] += 1
        self.stats["dispatches"] += 1
        # the step's single host fetch: [bucket] tokens
        out = np.asarray(out)  # lint: allow(DS-R005)
        for i, req in enumerate(running):
            self.pool.advance(req.slot, 1)
            self._emit(req, int(out[i]))
            if self.prefix_cache and not req.done:
                # publish any page this write just filled (incremental: one
                # hash per P decode steps per request)
                self.pool.register_prefix(
                    req.slot, req.context(), int(self.pool.seq_lens[req.slot])
                )

    # --- speculative rounds ---------------------------------------------
    def _propose_drafts(self, running: List[Request]) -> Dict[int, np.ndarray]:
        """Host-side drafting: up to ``_draft_cap`` tokens per request,
        clamped so drafts never outrun the request's remaining budget (the
        bonus token always needs one slot) — which also keeps every write
        inside ``max_seq_len``."""
        drafts: Dict[int, np.ndarray] = {}
        for req in running:
            budget = req.max_new_tokens - len(req.generated)  # >= 1 while running
            k = min(self._draft_cap, budget - 1)
            d = np.zeros(0, np.int32)
            if k > 0:
                d = np.asarray(
                    self.drafter.propose(req.uid, req.context(), k), np.int32
                ).reshape(-1)[:k]
            drafts[req.uid] = d
        return drafts

    def _verify_round(self, running: List[Request], drafts: Dict[int, np.ndarray]) -> None:
        """One speculative round: reserve pages for every row's drafts +
        bonus slot, dispatch ONE (bucket, K) verify program, emit each
        row's accepted prefix + bonus/correction token, and roll the
        rejected tail's pages back to the free list."""
        need = {uid: d.size + 1 for uid, d in drafts.items()}
        running = self._reserve_for_growth(running, need)
        if not running:
            return
        d_max = max(drafts[r.uid].size for r in running)
        # the smallest compiled width covering this round's longest draft
        # (preemption may have evicted every drafting row — any width works)
        K = next((l for l in self.spec_lens if l >= d_max), self.spec_lens[-1])
        bucket, page_table, lengths = self._dispatch_rows(running)
        tokens = np.zeros((bucket, K + 1), np.int32)
        draft_lens = np.zeros(bucket, np.int32)
        for i, req in enumerate(running):
            d = drafts[req.uid]
            tokens[i, 0] = req.pending
            tokens[i, 1 : 1 + d.size] = d
            draft_lens[i] = d.size
        verify = build_paged_verify_step(
            self.cfg, bucket, K, self.pool.page_size, attn_impl=self.attn_impl,
            telemetry=self.telemetry,
        )
        out, new_k, new_v = verify(
            self.params, tokens, self.pool.cache.k_pages, self.pool.cache.v_pages,
            page_table, lengths, draft_lens,
        )
        self.pool.set_cache(new_k, new_v)
        self.stats["spec_rounds"] += 1
        self.stats["dispatches"] += 1
        # the round's single host fetch: [bucket, K+2] = accept count + the
        # greedy token after each prefix
        out = np.asarray(out)  # lint: allow(DS-R005)
        for i, req in enumerate(running):
            # acc (out[i, 0]) is bounded by draft_lens in-program; all d+1
            # written positions advance first, then the rejected tail rolls
            # back — net advance is the accepted prefix + bonus token
            self._settle_spec_row(req, int(draft_lens[i]), int(out[i, 0]), out[i])

    # --- bookkeeping ----------------------------------------------------
    def _emit(self, req: Request, token: int) -> None:
        """Record a newly sampled token and retire the request if it just
        hit EOS or its budget (the token is included, matching
        ``decode.generate``'s output contract)."""
        if req.t_first is None:
            req.t_first = self.clock()
            self.tracer.instant_async("request", req.uid, "first_token")
            if self.journal is not None:
                self.journal.append_first_token(req.uid, req.t_first)
        req.generated.append(token)
        req.pending = token
        self.stats["emitted_tokens"] += 1
        self.metrics.counter("serve.tokens").inc()
        if self.journal is not None:
            self.journal.append_emit(req.uid, token)
        self._tenant(req.tenant)["tokens"] += 1
        self.policy.on_emit(req, self)
        if (
            req.eos_token_id is not None and token == req.eos_token_id
        ) or len(req.generated) >= req.max_new_tokens:
            self._finish(req)

    def _finish(self, req: Request) -> None:
        req.done = True
        req.t_finish = self.clock()
        self.pool.free_slot(req.slot)
        req.slot = None
        self._active.remove(req)
        self._results[req.uid] = req.output()
        self.stats["finished"] += 1
        ts = self._tenant(req.tenant)
        ts["finished"] += 1
        ttft_ms = (req.t_first - req.t_submit) * 1e3
        ts["ttft_ms"].append(ttft_ms)
        tpot_ms = None
        if len(req.generated) > 1:
            tpot_ms = (req.t_finish - req.t_first) * 1e3 / (len(req.generated) - 1)
            ts["tpot_ms"].append(tpot_ms)
        self._finished_log.append((req.tenant, ttft_ms, tpot_ms, len(req.generated)))
        if self.tracer.enabled:
            self.tracer.end_async(
                "request", req.uid, f"req{req.uid}",
                tenant=req.tenant, tokens=len(req.generated),
                prefix_cached=req.prefix_cached, admissions=req.admissions,
                spec_drafted=req.spec_drafted, spec_accepted=req.spec_accepted,
                ttft_ms=round(ttft_ms, 3),
                tpot_ms=None if tpot_ms is None else round(tpot_ms, 3),
            )
        # the SLA histograms come from the request's clock timestamps, not
        # the tracer — they record even with tracing disabled
        self.metrics.histogram("serve.ttft_ms").observe(ttft_ms)
        if tpot_ms is not None:
            self.metrics.histogram("serve.tpot_ms").observe(tpot_ms)
        if self.journal is not None:
            self.journal.append_finish(req.uid)
        self.policy.on_finish(req, self)
        if self.drafter is not None:
            self.drafter.drop(req.uid)

    # --- observability ---------------------------------------------------
    @staticmethod
    def _percentiles(values) -> Dict:
        """{count, mean, p50, p99} ms summary ({} count 0 when empty) —
        the one shared definition (the fleet router reports through it
        too)."""
        return percentile_summary(values)

    def finished_log(self):
        """Per-finished-request (tenant, ttft_ms, tpot_ms|None, n_tokens)
        tuples, oldest first (bounded) — the load harness's goodput input."""
        return list(self._finished_log)

    def serve_stats(self) -> Dict:
        """Scheduler counters (incl. ``ragged_steps`` — one per unified
        dispatch on the default path — and the multi-step window block:
        ``window_steps`` fused dispatches, ``window_horizon``,
        ``dispatches_per_token`` over every serving dispatch and emitted
        token, and ``window_break_reasons`` naming why windows could not
        form or ended early) plus derived speculation observability
        (acceptance rate, mean accepted drafts per round, draft-hit
        histogram), pool occupancy/utilization, prefix-cache counters
        (hit rate, CoW copies, cached pages), and latency SLOs — aggregate
        and per-tenant p50/p99 TTFT (submit → first token, queue wait
        included) and TPOT (per generated token after the first) — the
        payload ``InferenceEngine.serve_stats()`` surfaces and ``bench.py``
        records per serving config."""
        s = dict(self.stats)
        s["spec_accept_hist"] = list(self.stats["spec_accept_hist"])
        s["window_break_reasons"] = dict(self.stats["window_break_reasons"])
        drafted, rounds = s["spec_drafted"], s["spec_rounds"]
        s["spec_accept_rate"] = s["spec_accepted"] / drafted if drafted else 0.0
        s["spec_mean_accepted_per_round"] = (
            s["spec_accepted"] / rounds if rounds else 0.0
        )
        # dispatch amortization (multi-step windows): every serving
        # dispatch over every emitted token — steady-state windows drive
        # this toward 1/horizon; 0.0 before anything has been emitted
        s["window_horizon"] = self.ms_horizon if self.ms_enable else 0
        s["dispatches_per_token"] = (
            s["dispatches"] / s["emitted_tokens"] if s["emitted_tokens"] else 0.0
        )
        # tensor-parallel serving: the sharding degree this server runs at
        # (1 = single-chip) and whether the row-parallel all-reduces are
        # EQuARX-quantized — bench and fleet observability key on these
        s["tp_degree"] = self.tp.degree if self.tp is not None else 1
        s["tp_quantized_allreduce"] = (
            bool(self.tp.quantized_allreduce) if self.tp is not None else False
        )
        s.update(
            live_tokens=self.pool.live_tokens(),
            used_pages=self.pool.used_pages(),
            free_pages=self.pool.free_pages(),
            live_hbm_bytes=self.pool.live_hbm_bytes(),
            pool_utilization=self.pool.utilization(),
        )
        all_ttft: List[float] = []
        all_tpot: List[float] = []
        tenants: Dict[str, Dict] = {}
        for name, ts in self._tenant_stats.items():
            all_ttft.extend(ts["ttft_ms"])
            all_tpot.extend(ts["tpot_ms"])
            tenants[name] = {
                "submitted": ts["submitted"],
                "finished": ts["finished"],
                "tokens": ts["tokens"],
                "ttft_ms": self._percentiles(ts["ttft_ms"]),
                "tpot_ms": self._percentiles(ts["tpot_ms"]),
            }
        s["ttft_ms"] = self._percentiles(all_ttft)
        s["tpot_ms"] = self._percentiles(all_tpot)
        s["tenants"] = tenants
        s["prefix"] = self.pool.prefix_stats()
        return s

    def _preempt(self, req: Request) -> None:
        self.pool.free_slot(req.slot)
        req.slot = None
        req.pending = None
        req.consumed = 0
        self._active.remove(req)
        self._queue.appendleft(req)
        self.stats["preempted"] += 1
        self.tracer.instant_async(
            "request", req.uid, "preempt", tokens=len(req.generated)
        )
