"""Continuous-batching scheduler over the paged KV pool.

The dense decode path (``inference/decode.py:generate``) runs fixed-shape
lockstep batches: every sequence prefills together, decodes together, and
the whole batch holds its HBM until the longest row finishes. This module
replaces that with request-level scheduling (DeepSpeed-Inference / Orca /
vLLM style):

* requests are **admitted** whenever a slot and enough pages exist, and
  **evicted** the step they finish — cache HBM tracks live tokens;
* prompts prefill in fixed-size **chunks interleaved with decode steps**,
  so a long prompt never stalls tokens already streaming;
* when the pool runs dry the **youngest** running request is preempted
  (pages freed, request requeued); greedy decoding makes its recomputed
  continuation token-exact, so preemption is invisible in the output;
* compiled-program count is bounded by the **slot-count buckets**: each
  decode step dispatches ONE program shaped to the smallest bucket covering
  the running set, and each prompt chunk one fixed-chunk prefill program.
  Steady state is one dispatch per decode step, ≤1 compile per bucket —
  enforced by the serving tests via the engine's compile telemetry.

``InferenceEngine.serve()`` (``inference/engine.py``) owns a ``PagedServer``
configured from the ``inference.paged_kv`` knobs.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.inference.decode import build_paged_decode_step, build_paged_prefill
from deepspeed_tpu.inference.kv_pool import PagedKVCache, PagePool
from deepspeed_tpu.models.config import TransformerConfig


@dataclass
class Request:
    """One generation request moving through the scheduler."""

    uid: int
    prompt: np.ndarray  # [Lp] int32, immutable
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    generated: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    consumed: int = 0  # prefill progress over context()
    pending: Optional[int] = None  # sampled but not yet written token
    done: bool = False
    admissions: int = 0  # > 1 means the request was preempted and resumed

    def context(self) -> np.ndarray:
        """Tokens to (re)compute on admission: the prompt plus everything
        already emitted — after a preemption the resumed prefill re-derives
        the exact greedy continuation."""
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)]
        ).astype(np.int32)

    def output(self) -> np.ndarray:
        return self.context()


def _default_buckets(max_slots: int) -> List[int]:
    """Powers of two up to and including max_slots."""
    buckets, b = [], 1
    while b < max_slots:
        buckets.append(b)
        b *= 2
    buckets.append(max_slots)
    return sorted(set(buckets))


class PagedServer:
    """Owns the page pool, the per-bucket compiled programs, and the
    admit → prefill-chunk → decode-step loop."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        page_size: int = 16,
        num_pages: int = 0,
        max_slots: int = 8,
        slot_buckets: Optional[Sequence[int]] = None,
        max_seq_len: int = 0,
        prefill_chunk: int = 32,
        attn_impl: str = "auto",
        dtype=None,
        telemetry=None,
    ):
        self.cfg = cfg
        self.params = params
        self.prefill_chunk = int(prefill_chunk)
        self.attn_impl = attn_impl
        self.telemetry = telemetry
        max_seq = int(max_seq_len or cfg.max_seq_len)
        if num_pages <= 0:
            # worst-case sizing: every slot at max length, plus the trash
            # page — no preemption can ever trigger. Shrink num_pages to
            # oversubscribe HBM and trade it for preemptions.
            num_pages = max_slots * (-(-max_seq // page_size)) + 1
        self.pool = PagePool(
            cfg, num_pages, page_size, max_slots,
            max_seq_len=max_seq, dtype=dtype,
        )
        buckets = sorted(set(int(b) for b in (slot_buckets or _default_buckets(max_slots))))
        if buckets[-1] < max_slots:
            buckets.append(max_slots)
        if any(b < 1 for b in buckets):
            raise ValueError(f"slot buckets must be >= 1, got {buckets}")
        self.buckets = buckets
        self._queue: deque[Request] = deque()
        self._active: List[Request] = []  # admission order (oldest first)
        self._results: Dict[int, np.ndarray] = {}
        self._next_uid = 0
        self.stats = {
            "admitted": 0,
            "preempted": 0,
            "finished": 0,
            "prefill_chunks": 0,
            "decode_steps": 0,
        }

    # --- request intake -------------------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
    ) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + int(max_new_tokens)
        if total > self.pool.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} exceeds "
                f"the serving max_seq_len {self.pool.max_seq_len}"
            )
        if self.pool.pages_for(total) > self.pool.num_pages - 1:
            raise ValueError(
                f"request needs {self.pool.pages_for(total)} pages but the pool "
                f"holds {self.pool.num_pages - 1} allocatable"
            )
        uid = self._next_uid
        self._next_uid += 1
        self._queue.append(
            Request(uid=uid, prompt=prompt, max_new_tokens=int(max_new_tokens),
                    eos_token_id=eos_token_id)
        )
        return uid

    def has_work(self) -> bool:
        return bool(self._queue or self._active)

    def result(self, uid: int) -> Optional[np.ndarray]:
        return self._results.get(uid)

    # --- one scheduler iteration ---------------------------------------
    def step(self) -> None:
        """Admit what fits, push every pending prefill one chunk, run one
        decode dispatch over the running set."""
        self._admit()
        self._prefill_step()
        self._decode_step()

    def run(self) -> Dict[int, np.ndarray]:
        while self.has_work():
            self.step()
        return self._results

    def serve(
        self,
        prompts: Sequence,
        max_new_tokens=32,
        eos_token_id: Optional[int] = None,
    ) -> List[np.ndarray]:
        """Submit a batch (scalar or per-request ``max_new_tokens``), run to
        completion, return outputs in submission order."""
        if isinstance(max_new_tokens, (int, np.integer)):
            max_new_tokens = [max_new_tokens] * len(prompts)
        if len(max_new_tokens) != len(prompts):
            raise ValueError(
                f"{len(prompts)} prompts but {len(max_new_tokens)} max_new_tokens"
            )
        uids = [
            self.submit(p, max_new_tokens=int(n), eos_token_id=eos_token_id)
            for p, n in zip(prompts, max_new_tokens)
        ]
        self.run()
        # pop: the server lives as long as the engine, and a per-batch
        # serve() loop must not retain every output ever generated
        return [self._results.pop(u) for u in uids]

    # --- phases ---------------------------------------------------------
    def _admit(self) -> None:
        while self._queue:
            req = self._queue[0]
            ctx_len = req.prompt.size + len(req.generated)
            # reserve the whole context plus the first decode write so a
            # prefill can never die halfway through its own prompt
            slot = self.pool.alloc_slot(ctx_len + 1)
            if slot is None:
                break
            self._queue.popleft()
            req.slot = slot
            req.consumed = 0
            req.pending = None
            req.admissions += 1
            self._active.append(req)
            self.stats["admitted"] += 1

    def _prefill_step(self) -> None:
        C = self.prefill_chunk
        prefill = build_paged_prefill(
            self.cfg, C, self.pool.page_size, attn_impl=self.attn_impl,
            telemetry=self.telemetry,
        )
        for req in [r for r in self._active if r.pending is None and not r.done]:
            ctx = req.context()
            start = req.consumed
            real = min(C, ctx.size - start)
            chunk = np.zeros((1, C), np.int32)
            chunk[0, :real] = ctx[start : start + real]
            pt, _ = self.pool.rows([req.slot])
            tok, new_k, new_v = prefill(
                self.params, chunk, self.pool.cache.k_pages, self.pool.cache.v_pages,
                pt, np.asarray([start], np.int32), np.int32(real - 1),
            )
            self.pool.cache = PagedKVCache(k_pages=new_k, v_pages=new_v)
            self.pool.advance(req.slot, real)
            req.consumed = start + real
            self.stats["prefill_chunks"] += 1
            if req.consumed == ctx.size:
                self._emit(req, int(np.asarray(tok)[0]))

    def _decode_step(self) -> None:
        running = [r for r in self._active if r.pending is not None and not r.done]
        # grow each running row by one position, preempting the youngest
        # active request (prefilling or running) when the pool is dry —
        # vLLM's recompute preemption: the victim's greedy continuation is
        # re-derived exactly on re-admission
        idx = 0
        while idx < len(running):
            req = running[idx]
            while not self.pool.ensure(req.slot, int(self.pool.seq_lens[req.slot]) + 1):
                candidates = [r for r in self._active if r is not req]
                if not candidates:
                    # unreachable while submit() validates total size, kept
                    # as a hard stop against a silent infinite loop
                    raise RuntimeError(
                        f"page pool exhausted by a single sequence (len "
                        f"{int(self.pool.seq_lens[req.slot])}): the pool holds "
                        f"{self.pool.num_pages - 1} pages x {self.pool.page_size} tokens"
                    )
                victim = candidates[-1]  # latest admission
                self._preempt(victim)
                if victim in running:
                    vi = running.index(victim)
                    running.remove(victim)
                    if vi < idx:
                        idx -= 1
            idx += 1
        if not running:
            return
        bucket = min(b for b in self.buckets if b >= len(running))
        tokens = np.zeros(bucket, np.int32)
        page_table = np.full((bucket, self.pool.max_pages_per_slot), -1, np.int32)
        lengths = np.zeros(bucket, np.int32)
        rows_pt, rows_len = self.pool.rows([r.slot for r in running])
        n = len(running)
        tokens[:n] = [r.pending for r in running]
        page_table[:n] = rows_pt
        lengths[:n] = rows_len
        decode = build_paged_decode_step(
            self.cfg, bucket, self.pool.page_size, attn_impl=self.attn_impl,
            telemetry=self.telemetry,
        )
        out, new_k, new_v = decode(
            self.params, tokens, self.pool.cache.k_pages, self.pool.cache.v_pages,
            page_table, lengths,
        )
        self.pool.cache = PagedKVCache(k_pages=new_k, v_pages=new_v)
        self.stats["decode_steps"] += 1
        out = np.asarray(out)  # the step's single host fetch: [bucket] tokens
        for i, req in enumerate(running):
            self.pool.advance(req.slot, 1)
            self._emit(req, int(out[i]))

    # --- bookkeeping ----------------------------------------------------
    def _emit(self, req: Request, token: int) -> None:
        """Record a newly sampled token and retire the request if it just
        hit EOS or its budget (the token is included, matching
        ``decode.generate``'s output contract)."""
        req.generated.append(token)
        req.pending = token
        if (
            req.eos_token_id is not None and token == req.eos_token_id
        ) or len(req.generated) >= req.max_new_tokens:
            self._finish(req)

    def _finish(self, req: Request) -> None:
        req.done = True
        self.pool.free_slot(req.slot)
        req.slot = None
        self._active.remove(req)
        self._results[req.uid] = req.output()
        self.stats["finished"] += 1

    def _preempt(self, req: Request) -> None:
        self.pool.free_slot(req.slot)
        req.slot = None
        req.pending = None
        req.consumed = 0
        self._active.remove(req)
        self._queue.appendleft(req)
        self.stats["preempted"] += 1
