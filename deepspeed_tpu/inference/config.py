"""Inference config (reference: ``deepspeed/inference/config.py``, 304 LoC)."""

from __future__ import annotations

from enum import Enum
from typing import Any, Dict, List, Optional

from pydantic import Field, model_validator

from deepspeed_tpu.runtime.config import AnalysisConfig, TracingConfig
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class DtypeEnum(str, Enum):
    fp32 = "fp32"
    fp16 = "fp16"
    bf16 = "bf16"
    int8 = "int8"


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    enabled: bool = True
    ep_size: int = 1
    moe_experts: list = Field(default_factory=lambda: [1])
    type: str = "standard"


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    num_bits: int = 8
    group_size: int = 64


class BaseQuantConfig(DeepSpeedConfigModel):
    enabled: bool = False
    num_bits: int = 8
    group_size: int = 64


class WeightQuantConfig(BaseQuantConfig):
    pass


class ActivationQuantConfig(BaseQuantConfig):
    pass


class QKVQuantConfig(DeepSpeedConfigModel):
    enabled: bool = False


class CheckpointConfig(DeepSpeedConfigModel):
    checkpoint_dir: Optional[str] = None
    save_mp_checkpoint_path: Optional[str] = None
    base_dir: Optional[str] = None


class MultiStepConfig(DeepSpeedConfigModel):
    """Multi-step in-program serving windows (``decode.py:
    build_ragged_multistep`` / ``scheduler.py:_ragged_window``).

    With ``enable``, a scheduler step whose running set is stable — no
    pending admissions, no prefill chunks, no drafts, no preemption
    pressure — dispatches ONE fused ``lax.scan`` program of up to
    ``horizon`` plain-decode rounds: per-row EOS/length stopping masks
    freeze finished rows in-program, the page table rides in pre-reserved
    for the whole window's KV growth, and the host dispatch gap, packing,
    emit, and journal sync are paid once per window — steady-state
    dispatches/token → ``1/horizon``. Any scheduling event falls back to
    the single-step ragged path (``serve_stats()['window_break_reasons']``
    counts why), so greedy streams stay byte-identical to single-step —
    and to bucketed and dense — serving. One horizon is armed at a time,
    adding at most ONE compiled serving program (≤ 4 total with the
    narrow + mixed ragged widths)."""

    enable: bool = False
    horizon: int = 8  # decode rounds fused into one dispatch (>= 2)

    @model_validator(mode="after")
    def _check_horizon(self):
        if self.enable and self.horizon < 2:
            raise ValueError(
                f"paged_kv.multi_step.horizon must be >= 2 (1 is the "
                f"single-step path), got {self.horizon}"
            )
        return self


class ShardedServingConfig(DeepSpeedConfigModel):
    """Multi-chip tensor-parallel serving knobs (``inference/tp.py``).

    With an effective tp degree > 1 (``tp_degree``, or — when 0 — the
    engine-level ``tensor_parallel.tp_size``), the ragged serving programs
    run under ``shard_map`` on a ``model``-axis mesh: weights shard
    column-parallel (q/k/v/gate/up) and row-parallel (o/down) per the
    AutoTP map, the paged KV pools shard over the **kv-head axis** (page
    tables stay host-side and replicated — prefix cache, CoW, journal,
    and the fleet router are untouched), and greedy streams stay
    **byte-identical** to single-chip serving for fp32/bf16 weights.

    ``quantized_allreduce`` swaps the row-parallel projections' fp psum
    for the EQuARX-style int8 exchange (all-to-all + local fp32 reduce +
    all-gather): 4x fewer bytes on the decode critical path at a bounded
    quantization error — the serving contract under this knob is
    allclose, not byte-identical. ``comm_chunks`` splits each projection
    so every all-reduce overlaps the next chunk's matmul (the ``overlap``
    analysis pass verifies the schedule). ``weight_quant_bits = 8`` stores
    the matmul weights int8 with per-output-channel scales
    (``compression/int8.py``), dequantized in the matmul epilogue —
    elementwise weight error ≤ max|w_channel|/254."""

    tp_degree: int = 0  # 0 = follow tensor_parallel.tp_size; 1 = single-chip
    quantized_allreduce: bool = False
    comm_chunks: int = 2  # row-parallel output split for comm/compute overlap
    weight_quant_bits: int = 0  # 0 = off; 8 = int8 per-channel weights

    @model_validator(mode="after")
    def _check(self):
        if self.tp_degree < 0:
            raise ValueError(f"sharded.tp_degree must be >= 0, got {self.tp_degree}")
        if self.comm_chunks < 1:
            raise ValueError(f"sharded.comm_chunks must be >= 1, got {self.comm_chunks}")
        if self.weight_quant_bits not in (0, 8):
            raise ValueError(
                f"sharded.weight_quant_bits supports 0 (off) or 8 (int8), "
                f"got {self.weight_quant_bits}"
            )
        return self


class PagedKVConfig(DeepSpeedConfigModel):
    """Paged-KV serving knobs (``engine.serve()``: block-pool cache +
    continuous batching, ``inference/kv_pool.py`` / ``inference/scheduler.py``).

    Cache HBM is ``num_pages × page_size × bytes_per_token`` where
    ``bytes_per_token = 2 · L · NKV · D · dtype_bytes`` — sized to LIVE
    tokens instead of the dense workspace's ``batch × max_len``. With
    ``num_pages = 0`` the pool is sized worst-case
    (``max_slots × ceil(max_seq_len / page_size) + 1``, preemption-free);
    set it lower to oversubscribe and trade HBM for recompute preemptions.

    ``ragged`` (default ON) serves every step as ONE dispatch of the
    unified ragged program (``decode.py:build_ragged_step``): mixed
    prefill-chunk, decode, and verify rows ride together, driven by
    per-row ``(kv_len, q_len)`` metadata arrays, so shifting traffic never
    retraces and total compiled serving programs is ≤ 2 (the narrow
    decode/verify width plus the mixed width covering prefill chunks) —
    chunked prefill shares the dispatch with decoders instead of stealing
    whole steps, and spec-K varies freely per request. With
    ``ragged = False`` the bucketed per-shape programs are kept as the
    token-exactness oracle: compiled-program count is then
    ``len(slot_buckets) + 1`` (one decode program per bucket, one prefill
    program per chunk size) plus ``len(slot_buckets) × len(spec_lens)``
    verify programs when ``spec_decode.enable`` is set. Greedy streams
    are byte-identical across the two paths.

    ``multi_step`` (see :class:`MultiStepConfig`) arms fused windows of N
    plain-decode rounds per dispatch on top of the ragged path — the host
    dispatch gap amortizes to 1/N in steady state, streams stay
    byte-identical, and any scheduling event falls back to single-step.

    ``prefix_cache`` turns on page-level prefix sharing: full KV pages are
    indexed by a content chain hash, requests attach the longest cached
    prefix of their context by reference (refcounted, copy-on-write on
    divergence), and N requests sharing a system prompt pay its prefill
    and HBM once. Greedy streams stay byte-identical to sharing-off
    serving; sharing adds zero programs and zero dispatches.
    """

    enabled: bool = True
    page_size: int = 16
    num_pages: int = 0  # 0 = worst-case auto-size (no preemption possible)
    max_slots: int = 8  # concurrent sequences (rows of the decode batch)
    slot_buckets: list = Field(default_factory=list)  # [] = powers of 2 up to max_slots
    max_seq_len: int = 0  # 0 = the model config's max_seq_len
    prefill_chunk: int = 32  # prompt tokens per interleaved prefill dispatch
    attn_impl: str = "auto"  # auto | pallas | xla (decode attention backend)
    prefix_cache: bool = True  # page-level prefix sharing (hash-of-block + CoW)
    ragged: bool = True  # one ragged program per step (False = bucketed oracle)
    # multi-step windows: N decode rounds fused into one dispatch when the
    # running set is stable (requires the ragged path)
    multi_step: MultiStepConfig = Field(default_factory=MultiStepConfig)
    # multi-chip tensor-parallel serving (requires the ragged path):
    # sharded weights + kv-head-sharded pages + quantized comms knobs
    sharded: ShardedServingConfig = Field(default_factory=ShardedServingConfig)

    @model_validator(mode="after")
    def _check_multi_step(self):
        if self.multi_step.enable and not self.ragged:
            raise ValueError(
                "paged_kv.multi_step runs over the ragged serving path: "
                "enable paged_kv.ragged (or disable multi_step)"
            )
        if self.sharded.tp_degree > 1 and not self.ragged:
            raise ValueError(
                "paged_kv.sharded tensor-parallel serving runs over the "
                "ragged serving path: enable paged_kv.ragged (or set "
                "sharded.tp_degree <= 1)"
            )
        return self


class TenantConfig(DeepSpeedConfigModel):
    """One tenant's serving contract (``inference/traffic.py:TenantSpec``):
    token-budget ``weight`` (fair share of served tokens), strict
    ``priority`` class (admitted first, preempted last), TTFT/TPOT SLA
    targets (reported as attainment, not enforced), and admission-control
    caps (``max_queued`` submissions rejected beyond the queue depth;
    ``max_live_slots`` bounds concurrent slots)."""

    name: str
    weight: float = 1.0
    priority: int = 0
    ttft_target_ms: Optional[float] = None
    tpot_target_ms: Optional[float] = None
    max_queued: Optional[int] = None
    max_live_slots: Optional[int] = None


class TrafficConfig(DeepSpeedConfigModel):
    """Multi-tenant SLA serving knobs. With ``enabled`` the engine wraps
    its ``PagedServer`` in a ``MultiTenantServer``: weighted-deficit +
    priority scheduling over the per-tenant contracts in ``tenants``,
    per-tenant breakdowns in ``serve_stats()``, and queue-cap admission
    control at ``submit``. Unknown tenants fall back to a weight-1
    priority-0 default."""

    enabled: bool = False
    tenants: List[TenantConfig] = Field(default_factory=list)


class JournalConfig(DeepSpeedConfigModel):
    """Serving crash-recovery journal (``inference/journal.py``).

    With ``enabled`` (and a ``dir``) every admitted request and emitted
    token is appended to an on-disk journal — durable once per scheduler
    step — and building the server on a directory that already holds
    records REPLAYS it first: finished results are restored, live requests
    re-queue with their journaled tokens pre-seeded, and every stream
    resumes byte-identically from its last emitted token (re-prefill rides
    the prefix cache, so shared prompts pay nearly nothing). Segments seal
    atomically at ``segment_bytes``; ``fsync=False`` trades durability of
    the last step for write latency (replay still never reads a torn
    record — CRCs gate every line)."""

    enabled: bool = False
    dir: Optional[str] = None
    segment_bytes: int = 1 << 20
    fsync: bool = True


class SpecDecodeConfig(DeepSpeedConfigModel):
    """Speculative-decoding knobs for paged serving (``engine.serve()``).

    Each speculative round drafts up to ``max_draft`` tokens per request
    host-side (``inference/spec_decode.py``: model-free n-gram /
    prompt-lookup of order ``ngram_order``) and verifies them in ONE
    device dispatch; greedy outputs stay byte-identical to
    speculation-off serving. ``spec_lens`` are the compiled verify widths
    K (a round uses the smallest K covering its longest draft); program
    count is bounded by ``len(slot_buckets) × len(spec_lens)``. With
    ``spec_lens = []`` the single width ``max_draft`` is compiled.
    """

    enable: bool = False
    max_draft: int = 4  # drafted tokens per request per round (the K cap)
    ngram_order: int = 3  # longest suffix n-gram the drafter looks up
    spec_lens: list = Field(default_factory=list)  # [] = [max_draft]


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    replace_with_kernel_inject: bool = Field(False, alias="kernel_inject")
    dtype: DtypeEnum = DtypeEnum.bf16
    tensor_parallel: DeepSpeedTPConfig = Field(default_factory=DeepSpeedTPConfig, alias="tp")
    enable_cuda_graph: bool = False  # parity flag; maps to jit compile cache
    use_triton: bool = False
    triton_autotune: bool = False
    zero: Dict[str, Any] = Field(default_factory=dict)
    triangular_masking: bool = Field(True, alias="tm")
    moe: DeepSpeedMoEConfig = Field(default_factory=DeepSpeedMoEConfig)
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    paged_kv: PagedKVConfig = Field(default_factory=PagedKVConfig)
    spec_decode: SpecDecodeConfig = Field(default_factory=SpecDecodeConfig)
    traffic: TrafficConfig = Field(default_factory=TrafficConfig)
    journal: JournalConfig = Field(default_factory=JournalConfig)
    analysis: AnalysisConfig = Field(default_factory=AnalysisConfig)
    # unified tracing/metrics plane (profiling/tracer.py): serving step
    # phases (admit/pack/dispatch/emit/journal-sync) + per-request
    # lifecycle spans, merged by engine.observability(); same knobs as the
    # training side incl. the crash flight recorder
    tracing: TracingConfig = Field(default_factory=TracingConfig)
    checkpoint: Optional[Any] = None
    base_dir: str = ""
    set_empty_params: bool = False
    save_mp_checkpoint_path: Optional[str] = None
    checkpoint_config: CheckpointConfig = Field(default_factory=CheckpointConfig, alias="ckpt_config")
    return_tuple: bool = True
    training_mp_size: int = 1
    replace_method: str = "auto"
    injection_policy: Optional[Dict] = Field(None, alias="injection_dict")
    injection_policy_tuple: Optional[tuple] = None
    config: Optional[Dict] = None
    max_out_tokens: int = Field(1024, alias="max_tokens")
    min_out_tokens: int = Field(1, alias="min_tokens")
    transposed_mode: bool = False
    ep_size: int = 1
    ep_group: Optional[Any] = Field(None, alias="expert_group")
    ep_mp_group: Optional[Any] = Field(None, alias="expert_mp_group")
    moe_experts: list = Field(default_factory=lambda: [1])
    moe_type: str = "standard"

    @model_validator(mode="before")
    @classmethod
    def _legacy_mp_size(cls, values):
        """Reference's deprecated ``mp_size`` maps onto tensor_parallel.tp_size."""
        if isinstance(values, dict) and "mp_size" in values:
            mp = values.pop("mp_size")
            values.setdefault("tensor_parallel", {"tp_size": mp})
        return values

