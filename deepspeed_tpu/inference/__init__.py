"""Inference: engine, KV-cached decode, and the paged serving layer."""

from deepspeed_tpu.inference.fleet import (  # noqa: F401
    ConsistentHashRing,
    FleetRouter,
    ReplicaHandle,
)
from deepspeed_tpu.inference.kv_pool import (  # noqa: F401
    PagedKVCache,
    PagePool,
    init_paged_cache,
)
from deepspeed_tpu.inference.scheduler import (  # noqa: F401
    PagedServer,
    Request,
    SchedulingPolicy,
    YoungestFirstPolicy,
)
from deepspeed_tpu.inference.spec_decode import Drafter, NGramDrafter  # noqa: F401
from deepspeed_tpu.inference.traffic import (  # noqa: F401
    MultiTenantServer,
    SLAPolicy,
    TenantSpec,
)
