"""Block-pool KV cache for paged serving, with page-level prefix sharing.

The dense decode workspace (``inference/decode.py:init_cache``) allocates
``[L, B, max_len, NKV, D]`` per batch — HBM scales with ``batch × max_len``
whether or not those tokens exist. Here the cache is a shared pool of
fixed-size pages ``[L, num_pages, NKV, page_size, D]`` plus a per-sequence
page table: HBM holds ``live_tokens × bytes_per_token`` rounded up to page
granularity, and any free page can serve any sequence (the vLLM block-table
layout; the reference approximates it with contiguous per-sequence
workspaces — ``allocate_workspace`` in
``csrc/transformer/inference/csrc/pt_binding.cpp``).

Split of responsibilities:

* ``PagedKVCache`` — the device arrays. Jitted programs read/write them
  through ``ops/transformer/paged_attention.py`` and the scatter in
  ``inference/decode.py``; they are donated into every serving program so
  updates alias in place.
* ``PagePool`` — the host-side allocator: free list, per-slot page tables
  and live lengths (numpy; they ride into each dispatch as plain int32
  arrays, so allocation changes never retrace a program), alloc/free/defrag,
  and the **prefix index**.

Prefix sharing (production traffic: N requests carrying the same system
prompt must pay its prefill and HBM once):

* every FULL page a sequence writes can be *registered* under a
  **chain hash** — ``hash(previous block's chain key, this block's token
  content)`` — so a key identifies a whole prefix, not just a block;
* a new request *matches* its prompt against the index block-by-block and
  **attaches** the longest indexed prefix: the shared pages enter its page
  table, the per-page **refcount** rises, and prefill resumes after them;
* pages reachable from the index are **immutable**. The write barrier
  (``prepare_write``) enforces it: a shared page (refcount > 1) in the
  about-to-be-written span is replaced by a private **copy-on-write**
  duplicate (divergence), and an exclusively-owned indexed page is
  dropped from the index before the write lands;
* releasing the last reference to an indexed page parks it on a
  **cached LRU** instead of the free list — the prefix survives its
  author, and the allocator reclaims cached pages (oldest first) only
  when the free list runs dry.

``free_pages()`` therefore counts *reclaimable* pages (free + cached), so
admission control never refuses a request that evicting cold prefixes
could host. Page 0 is the reserved TRASH page: it is never allocated,
table sentinels (-1) clamp onto it inside the kernels, and dead-slot
writes land there — a padded batch row can never corrupt a live
sequence's pages.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.config import TransformerConfig

TRASH_PAGE = 0

# root of every prefix hash chain (arbitrary constant; only equality of
# chain keys matters, and keys are process-local like python hash())
_ROOT_CHAIN = 0x9E3779B9

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


def _copy_page(k_pages, v_pages, src, dst):
    """Copy page ``src`` over page ``dst`` in both pools — jitted with the
    pools DONATED, so XLA aliases them in place and a CoW event costs one
    page's bytes, not a rebuild of the whole cache."""
    kp = jax.lax.dynamic_index_in_dim(k_pages, src, axis=1, keepdims=True)
    vp = jax.lax.dynamic_index_in_dim(v_pages, src, axis=1, keepdims=True)
    return (
        jax.lax.dynamic_update_slice_in_dim(k_pages, kp, dst, axis=1),
        jax.lax.dynamic_update_slice_in_dim(v_pages, vp, dst, axis=1),
    )


# one compiled copier per (shape, dtype) — shared across pools
_copy_page_cache: dict = {}


def _sharding_key(sharding):
    """Hashable identity of a NamedSharding for the copier cache (None for
    the unsharded pools)."""
    if sharding is None:
        return None
    from deepspeed_tpu.utils.jax_compat import mesh_fingerprint

    return (str(sharding.spec), mesh_fingerprint(sharding.mesh))


def _copy_page_fn(k_pages, sharding=None):
    key = (k_pages.shape, str(k_pages.dtype), _sharding_key(sharding))
    fn = _copy_page_cache.get(key)
    if fn is None:
        kwargs = {}
        if sharding is not None:
            # pin the outputs to the pool's kv-head sharding so the donated
            # inputs alias shard-for-shard (an unconstrained output could
            # legally come back resharded, silently breaking the alias)
            kwargs["out_shardings"] = (sharding, sharding)
        fn = jax.jit(_copy_page, donate_argnums=(0, 1), **kwargs)
        _copy_page_cache[key] = fn
    return fn


class PagedKVCache(NamedTuple):
    """Device page pool, one stacked array per K and V.

    Layout ``[L, num_pages, NKV, page_size, D]``: the layer axis scans, and
    each layer slice is exactly the ``[NP, NKV, P, D]`` pool the paged
    attention kernels take.
    """

    k_pages: jax.Array
    v_pages: jax.Array

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[3]

    @property
    def bytes_per_token(self) -> int:
        """HBM bytes one cached token costs across all layers (K + V)."""
        L, _, NKV, _, D = self.k_pages.shape
        return 2 * L * NKV * D * self.k_pages.dtype.itemsize

    def hbm_bytes(self) -> int:
        return self.k_pages.nbytes + self.v_pages.nbytes


def init_paged_cache(
    cfg: TransformerConfig, num_pages: int, page_size: int, dtype=None,
    sharding=None,
) -> PagedKVCache:
    """Allocate the device page pools. ``sharding`` (tensor-parallel
    serving) places them kv-head-sharded across the mesh — the page
    CONTENTS shard on axis 2 while the host-side tables stay replicated,
    so per-chip KV HBM is ``hbm_bytes() / tp``."""
    if dtype is None:
        dtype = _DTYPES[cfg.dtype]
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, cfg.head_dim)
    if sharding is not None:
        # allocate DIRECTLY sharded: a full-size zeros + device_put would
        # transiently commit the whole pool to one chip — tp× the
        # steady-state per-chip footprint, an OOM at bring-up on exactly
        # the pools sized against aggregate mesh HBM
        zeros = jax.jit(
            lambda: (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)),
            out_shardings=(sharding, sharding),
        )
        k, v = zeros()
    else:
        k, v = jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
    return PagedKVCache(k_pages=k, v_pages=v)


class PagePool:
    """Host-side page allocator over a ``PagedKVCache``.

    A *slot* is one concurrently-running sequence (a row of the serving
    batch); each slot owns a page-table row of ``max_pages_per_slot``
    entries. ``seq_lens[slot]`` counts tokens already written. Sequences
    acquire pages lazily as they grow and release them on ``free_slot`` —
    total cache HBM is fixed at ``num_pages``, but the *live* footprint is
    ``used_pages × page_size × bytes_per_token``. Pages are refcounted:
    prefix sharing lets one page appear in many tables, and a page only
    becomes reclaimable when its last reference drops.

    Every mutation of the page tables, free list, refcounts, or prefix
    index goes through the pool's own methods — lint DS-R007 flags outside
    writes, because a bypassed write barrier corrupts the CoW/refcount
    invariants silently.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        num_pages: int,
        page_size: int,
        max_slots: int,
        max_seq_len: Optional[int] = None,
        dtype=None,
        kv_sharding=None,
    ):
        if page_size < 1 or num_pages < 2:
            raise ValueError("need page_size >= 1 and num_pages >= 2 (page 0 is reserved)")
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        self.max_pages_per_slot = -(-self.max_seq_len // self.page_size)
        # tensor-parallel serving: the page contents shard over the kv-head
        # axis; every host-side structure below (tables, free lists,
        # refcounts, prefix index) is replicated logic and never changes
        self.kv_sharding = kv_sharding
        self.cache = init_paged_cache(
            cfg, num_pages, page_size, dtype=dtype, sharding=kv_sharding
        )
        # LIFO free list keeps hot pages hot; page 0 stays out of circulation
        self._free = list(range(num_pages - 1, TRASH_PAGE, -1))
        self._free_slots = list(range(max_slots - 1, -1, -1))
        self.page_table = np.full((max_slots, self.max_pages_per_slot), -1, np.int32)
        self.seq_lens = np.zeros(max_slots, np.int32)
        self._owned = np.zeros(max_slots, np.int32)  # pages held per slot
        # --- prefix sharing state ---------------------------------------
        self._refcount = np.zeros(num_pages, np.int32)  # table refs per page
        self._hash_index: dict = {}  # chain key -> page id (full-page content)
        self._page_hash: dict = {}  # page id -> chain key (reverse map)
        self._cached: "OrderedDict[int, None]" = OrderedDict()  # ref-0 indexed, LRU
        # per slot: chain key per leading full page whose content-chain is
        # known (published, or found already indexed under another page)
        self._chain_keys: List[List[int]] = [[] for _ in range(max_slots)]
        self.stats = {
            "prefix_lookups": 0,
            "prefix_query_tokens": 0,  # prompt tokens offered to match_prefix
            "prefix_hit_tokens": 0,  # tokens served by attaching cached pages
            "prefix_hit_pages": 0,
            "registered_pages": 0,
            "cow_copies": 0,
            "index_invalidations": 0,  # exclusive indexed pages rewritten
            "cache_evictions": 0,  # cold cached pages reclaimed for allocation
        }

    # --- capacity accounting -------------------------------------------
    @property
    def num_pages(self) -> int:
        return self.cache.num_pages

    def free_pages(self) -> int:
        """Reclaimable pages: truly free plus cached (refcount-0 prefix
        pages the allocator may evict on demand)."""
        return len(self._free) + len(self._cached)

    def used_pages(self) -> int:
        """Pages referenced by at least one live slot (trash page and
        cached-but-unreferenced prefix pages excluded)."""
        return self.num_pages - 1 - self.free_pages()

    def cached_pages(self) -> int:
        return len(self._cached)

    def pages_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_size)

    def live_tokens(self) -> int:
        return int(self.seq_lens.sum())

    def live_hbm_bytes(self) -> int:
        """HBM actually pinned by live sequences (page-granular)."""
        return self.used_pages() * self.page_size * self.cache.bytes_per_token

    def memory_report(self) -> dict:
        """Static residency accounting for the analysis HBM ledger: total
        device bytes of the page pools, the per-chip share under the pool's
        kv-head sharding (``total / tp`` when sharded — the tensor-parallel
        serving contract), and the host-side scheduling structures (page
        table, sequence lengths, refcounts, ownership) that stay replicated
        host RAM, never HBM."""
        total = self.cache.hbm_bytes()
        per_chip = total
        devices = 1
        if self.kv_sharding is not None:
            try:
                devices = int(self.kv_sharding.num_devices)
                shard = self.kv_sharding.shard_shape(
                    tuple(self.cache.k_pages.shape)
                )
                n = 1
                for d in shard:
                    n *= int(d)
                per_chip = 2 * n * self.cache.k_pages.dtype.itemsize
            except Exception:
                per_chip = total
        return {
            "kv_total_bytes": total,
            "kv_bytes_per_chip": per_chip,
            "kv_devices": devices,
            "live_kv_bytes": self.live_hbm_bytes(),
            "page_table_location": "host",
            "host_table_bytes": int(
                self.page_table.nbytes
                + self.seq_lens.nbytes
                + self._refcount.nbytes
                + self._owned.nbytes
            ),
        }

    def utilization(self) -> float:
        """Live tokens over allocated page capacity (1.0 = no page waste;
        prefix sharing can push it past 1.0 — N sequences reading one
        page's tokens count N times against a single allocation)."""
        cap = self.used_pages() * self.page_size
        return self.live_tokens() / cap if cap else 0.0

    def set_cache(self, new_k: jax.Array, new_v: jax.Array) -> None:
        """Install the page arrays a serving program returned (the donated
        buffers aliased in place). The one sanctioned external write."""
        self.cache = PagedKVCache(k_pages=new_k, v_pages=new_v)

    # --- page acquisition / release -------------------------------------
    def _acquire_page(self) -> Optional[int]:
        """One page off the free list, or — when it is dry — the coldest
        cached prefix page, dropped from the index first."""
        if self._free:
            return self._free.pop()
        if self._cached:
            page, _ = self._cached.popitem(last=False)  # oldest first
            self._drop_index(int(page))
            self.stats["cache_evictions"] += 1
            return int(page)
        return None

    def _release_page(self, page: int) -> None:
        """Last reference dropped: indexed pages park on the cached LRU
        (the prefix outlives its author), the rest return to the free list."""
        if page in self._page_hash:
            self._cached[page] = None  # newest end of the LRU
        else:
            self._free.append(page)

    def _drop_index(self, page: int) -> None:
        key = self._page_hash.pop(page, None)
        if key is not None and self._hash_index.get(key) == page:
            del self._hash_index[key]

    # --- prefix index ----------------------------------------------------
    def _block_key(self, chain: int, block: np.ndarray) -> int:
        return hash((chain, np.ascontiguousarray(block, np.int32).tobytes()))

    def match_prefix(self, tokens) -> List[Tuple[int, int]]:
        """Longest indexed full-page prefix of ``tokens`` as
        ``[(page_id, chain_key), ...]``. Capped at ``len(tokens) - 1``
        tokens: at least one prompt token is always left to prefill, so the
        request's first output token has logits to come from."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        P = self.page_size
        max_blocks = min(max(tokens.size - 1, 0) // P, self.max_pages_per_slot)
        out: List[Tuple[int, int]] = []
        chain = _ROOT_CHAIN
        for b in range(max_blocks):
            key = self._block_key(chain, tokens[b * P : (b + 1) * P])
            page = self._hash_index.get(key)
            if page is None:
                break
            out.append((int(page), key))
            chain = key
        return out

    def register_prefix(self, slot: int, tokens, upto: Optional[int] = None) -> int:
        """Publish ``slot``'s leading full pages into the prefix index so
        later requests can attach them. ``tokens`` is the slot's canonical
        context (prompt + accepted tokens); pages holding ``tokens[:upto]``
        (default: the slot's live length) are hashed block-by-block chained
        on the prefix. Incremental — pages already chained are skipped, so
        the per-step cost is one hash per newly-FILLED page. Returns the
        number of full pages chained. When a block's content is already
        indexed under another page, the existing entry wins (first writer)
        and this slot's page stays private."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        live = int(self.seq_lens[slot])
        upto = live if upto is None else min(int(upto), live, tokens.size)
        P = self.page_size
        n_full = upto // P
        chain_list = self._chain_keys[slot]
        chain = chain_list[-1] if chain_list else _ROOT_CHAIN
        i = len(chain_list)
        while i < n_full:
            key = self._block_key(chain, tokens[i * P : (i + 1) * P])
            page = int(self.page_table[slot, i])
            if key not in self._hash_index and page not in self._page_hash:
                self._hash_index[key] = page
                self._page_hash[page] = key
                self.stats["registered_pages"] += 1
            chain_list.append(key)
            chain = key
            i += 1
        return n_full

    def prefix_stats(self) -> dict:
        """Counters + derived prefix observability for ``serve_stats()``:
        ``prefix_hit_rate`` = fraction of looked-up prompt tokens served by
        attaching already-cached pages."""
        s = dict(self.stats)
        s["indexed_pages"] = len(self._page_hash)
        s["cached_pages"] = len(self._cached)
        q = s["prefix_query_tokens"]
        s["prefix_hit_rate"] = s["prefix_hit_tokens"] / q if q else 0.0
        return s

    # --- slot lifecycle -------------------------------------------------
    def can_admit(self, n_tokens: int) -> bool:
        """A free slot exists and the pool can hold ``n_tokens`` now
        (before any prefix credit — attaching cached pages only helps)."""
        return (
            bool(self._free_slots)
            and n_tokens <= self.max_seq_len
            and self.pages_for(n_tokens) <= self.free_pages()
        )

    def alloc_slot(self, n_tokens: int = 0, prefix_tokens=None) -> Optional[int]:
        """Claim a slot, pre-reserving pages for ``n_tokens``; None if the
        pool cannot host it right now (caller keeps the request queued).

        With ``prefix_tokens`` (the request's context) the longest indexed
        full-page prefix is ATTACHED first: the shared pages enter the page
        table with their refcount raised, ``seq_lens[slot]`` starts at the
        attached length, and only the remainder draws fresh pages — N
        requests sharing a system prompt allocate (and prefill) its KV
        exactly once."""
        if not self._free_slots:
            return None
        want = max(int(n_tokens), 1)
        if want > self.max_seq_len:
            return None
        matched: List[Tuple[int, int]] = []
        if prefix_tokens is not None:
            matched = self.match_prefix(prefix_tokens)
        # attached cached pages leave the reclaimable set, so discount them
        fresh = self.pages_for(want) - len(matched)
        avail = self.free_pages() - sum(1 for p, _ in matched if p in self._cached)
        if fresh > avail:
            return None
        slot = self._free_slots.pop()
        if prefix_tokens is not None:
            # counted only on successful admission: a stalled request retried
            # every step must not dilute the reported hit rate
            self.stats["prefix_lookups"] += 1
            self.stats["prefix_query_tokens"] += int(
                np.asarray(prefix_tokens).reshape(-1).size
            )
        self.seq_lens[slot] = 0
        self._chain_keys[slot] = []
        for i, (page, key) in enumerate(matched):
            self.page_table[slot, i] = page
            if self._refcount[page] == 0:
                self._cached.pop(page, None)
            self._refcount[page] += 1
            self._owned[slot] += 1
            self._chain_keys[slot].append(key)
        if matched:
            self.seq_lens[slot] = len(matched) * self.page_size
            self.stats["prefix_hit_pages"] += len(matched)
            self.stats["prefix_hit_tokens"] += len(matched) * self.page_size
        if n_tokens and not self.ensure(slot, n_tokens):
            self.free_slot(slot)
            return None
        return slot

    def ensure(self, slot: int, new_len: int) -> bool:
        """Grow ``slot``'s table to cover ``new_len`` tokens. All-or-nothing:
        on a pool-exhausted failure nothing is allocated (the caller decides
        whom to preempt and retries). Cold cached prefix pages are evicted
        (oldest first) when the free list alone cannot cover the growth."""
        if new_len > self.max_seq_len:
            return False
        need = self.pages_for(new_len) - self._owned[slot]
        if need <= 0:
            return True
        if need > self.free_pages():
            return False
        for _ in range(int(need)):
            page = self._acquire_page()
            self.page_table[slot, self._owned[slot]] = page
            self._refcount[page] = 1
            self._owned[slot] += 1
        return True

    def prepare_write(self, slot: int, new_len: int) -> bool:
        """Write barrier: make positions ``[seq_lens[slot], new_len)``
        writable, then guarantee every page in that span is EXCLUSIVE and
        UNINDEXED. Shared pages (refcount > 1 — a prefix some other
        sequence still reads) are replaced by private copy-on-write
        duplicates; exclusively-owned pages still in the index are dropped
        from it (an indexed page's content is immutable, and it is about
        to change). All-or-nothing like ``ensure``: False means nothing
        was allocated or copied and the caller should preempt and retry.
        Serving schedulers must call this (not bare ``ensure``) before
        every dispatch that writes KV."""
        cur = int(self.seq_lens[slot])
        if new_len > self.max_seq_len:
            return False
        if new_len <= cur:
            return True
        P = self.page_size
        first = cur // P
        last_w = (new_len - 1) // P
        owned = int(self._owned[slot])
        span = range(first, min(last_w + 1, owned))
        shared = [
            i for i in span if self._refcount[self.page_table[slot, i]] > 1
        ]
        grow = max(self.pages_for(new_len) - owned, 0)
        if grow + len(shared) > self.free_pages():
            return False
        if not self.ensure(slot, new_len):
            return False
        for i in shared:
            src = int(self.page_table[slot, i])
            dst = self._acquire_page()
            # one donated in-place page copy per divergence event — never
            # per step, and never a rebuild of the whole cache
            copy = _copy_page_fn(self.cache.k_pages, self.kv_sharding)
            new_k, new_v = copy(
                self.cache.k_pages, self.cache.v_pages,
                jnp.int32(src), jnp.int32(dst),
            )
            self.cache = PagedKVCache(k_pages=new_k, v_pages=new_v)
            self.page_table[slot, i] = dst
            self._refcount[dst] = 1
            self._refcount[src] -= 1
            if self._refcount[src] == 0:
                self._release_page(src)
            self.stats["cow_copies"] += 1
        for i in span:
            page = int(self.page_table[slot, i])
            if page in self._page_hash:
                self._drop_index(page)
                self.stats["index_invalidations"] += 1
        # pages from the first written one on are no longer a published
        # prefix of this slot
        if first < len(self._chain_keys[slot]):
            del self._chain_keys[slot][first:]
        return True

    def advance(self, slot: int, n_tokens: int) -> None:
        """Record ``n_tokens`` newly written to ``slot`` (pages must already
        be ensured)."""
        new_len = int(self.seq_lens[slot]) + int(n_tokens)
        assert self.pages_for(new_len) <= self._owned[slot], (
            f"slot {slot}: advancing to {new_len} tokens past its "
            f"{int(self._owned[slot])} allocated pages"
        )
        self.seq_lens[slot] = new_len

    def rollback(self, slot: int, n_tokens: int) -> int:
        """Un-write the last ``n_tokens`` of ``slot`` — speculative decode's
        rejected draft tail: shrink the live length and release every page
        past the new length (refcount-aware: a still-shared page survives
        for its other readers; an exclusive indexed page parks on the
        cached LRU; the rest return to the free list LIFO, so tail pages
        are the first reused). The data in the rolled-back region is NOT
        cleared — the length mask makes it invisible, and the next write at
        those positions overwrites it (through the write barrier). Returns
        how many pages this slot released."""
        n_tokens = int(n_tokens)
        new_len = int(self.seq_lens[slot]) - n_tokens
        if n_tokens < 0 or new_len < 0:
            raise ValueError(
                f"rollback({slot}, {n_tokens}): slot holds "
                f"{int(self.seq_lens[slot])} tokens"
            )
        self.seq_lens[slot] = new_len
        keep = self.pages_for(new_len)
        freed = 0
        while self._owned[slot] > keep:
            self._owned[slot] -= 1
            i = int(self._owned[slot])
            page = int(self.page_table[slot, i])
            self.page_table[slot, i] = -1
            self._refcount[page] -= 1
            if self._refcount[page] == 0:
                self._release_page(page)
            freed += 1
        del self._chain_keys[slot][min(len(self._chain_keys[slot]), keep):]
        return freed

    def trim_reservation(self, slot: int) -> int:
        """Release pages reserved past the slot's LIVE length. A multi-step
        serving window pre-reserves the ``ceil(N / page_size) + 1`` pages a
        row could touch (``prepare_write`` to ``len + N``) before its one
        dispatch; rows that freeze early (EOS / budget) or a window that
        falls back pre-dispatch hand the unused tail straight back here so
        reservations never starve admissions. Refcount semantics are
        ``rollback``'s (a zero-token rollback: only surplus pages move).
        Returns how many pages were released."""
        return self.rollback(slot, 0)

    def free_slot(self, slot: int) -> int:
        """Release the slot and drop its page references (pages whose last
        reference this was go back to the pool — or to the cached LRU when
        they still serve the prefix index); returns how many pages the slot
        held."""
        n = int(self._owned[slot])
        for i in range(n):
            page = int(self.page_table[slot, i])
            self._refcount[page] -= 1
            if self._refcount[page] == 0:
                self._release_page(page)
        self.page_table[slot, :] = -1
        self.seq_lens[slot] = 0
        self._owned[slot] = 0
        self._chain_keys[slot] = []
        self._free_slots.append(slot)
        return n

    # --- maintenance ----------------------------------------------------
    def integrity_check(self) -> None:
        """Verify the pool partition invariant: every allocatable page is
        exactly one of {free, cached, referenced}, refcounts equal the
        number of table references, cached pages are indexed, and every
        slot's live length fits its owned pages. Raises ``RuntimeError``
        naming the first violation. Used after crash recovery (the rebuilt
        pool must be internally consistent before serving resumes) and by
        the randomized soak tests."""
        refs: dict = {}
        for s in range(self.max_slots):
            owned = int(self._owned[s])
            if self.pages_for(int(self.seq_lens[s])) > owned:
                raise RuntimeError(
                    f"pool integrity: slot {s} holds {int(self.seq_lens[s])} "
                    f"tokens but only {owned} pages"
                )
            for i in range(owned):
                p = int(self.page_table[s, i])
                if p <= TRASH_PAGE or p >= self.num_pages:
                    raise RuntimeError(
                        f"pool integrity: slot {s} table entry {i} is {p}"
                    )
                refs[p] = refs.get(p, 0) + 1
        free = set(self._free)
        cached = set(int(p) for p in self._cached)
        referenced = set(refs)
        for name_a, set_a, name_b, set_b in (
            ("free", free, "cached", cached),
            ("free", free, "referenced", referenced),
            ("cached", cached, "referenced", referenced),
        ):
            overlap = set_a & set_b
            if overlap:
                raise RuntimeError(
                    f"pool integrity: page {min(overlap)} is both {name_a} "
                    f"and {name_b}"
                )
        allocatable = set(range(TRASH_PAGE + 1, self.num_pages))
        missing = allocatable - free - cached - referenced
        if missing:
            raise RuntimeError(f"pool integrity: page {min(missing)} leaked")
        for p, n in refs.items():
            if int(self._refcount[p]) != n:
                raise RuntimeError(
                    f"pool integrity: page {p} refcount {int(self._refcount[p])} "
                    f"but {n} table reference(s)"
                )
        for p in cached:
            if p not in self._page_hash:
                raise RuntimeError(
                    f"pool integrity: cached page {p} is not in the prefix index"
                )

    def defrag(self) -> int:
        """Compact live pages into the lowest ids (one device gather per
        K/V), rewriting tables, refcounts, and the prefix index, and
        rebuilding the free list. Live = referenced by any slot OR parked
        on the cached LRU (their bytes still serve future prefix matches).
        Shared pages move once and every referencing table row follows.
        Returns the number of pages that moved."""
        live: List[int] = []
        seen = set()
        for s in range(self.max_slots):
            for i in range(int(self._owned[s])):
                p = int(self.page_table[s, i])
                if p not in seen:
                    seen.add(p)
                    live.append(p)
        for p in self._cached:  # refcount 0: never in a table
            live.append(int(p))
        perm = np.arange(self.num_pages, dtype=np.int32)  # new_id -> old_id
        remap = {}  # old_id -> new_id
        nxt = TRASH_PAGE + 1
        for old in live:
            remap[old] = nxt
            perm[nxt] = old
            nxt += 1
        # unassigned tail: the remaining (free) pages in any order
        rest = [p for p in range(TRASH_PAGE + 1, self.num_pages) if p not in remap]
        perm[nxt:] = np.asarray(rest, np.int32)
        moves = sum(1 for old, new in remap.items() if old != new)
        if moves == 0:
            return 0
        gather = jnp.asarray(perm)
        self.cache = PagedKVCache(
            k_pages=self.cache.k_pages[:, gather],
            v_pages=self.cache.v_pages[:, gather],
        )
        for s in range(self.max_slots):
            for i in range(int(self._owned[s])):
                self.page_table[s, i] = remap[int(self.page_table[s, i])]
        new_rc = np.zeros_like(self._refcount)
        for old, new in remap.items():
            new_rc[new] = self._refcount[old]
        self._refcount = new_rc
        self._page_hash = {remap[p]: k for p, k in self._page_hash.items()}
        self._hash_index = {k: remap[p] for k, p in self._hash_index.items()}
        self._cached = OrderedDict((remap[int(p)], None) for p in self._cached)
        self._free = list(range(self.num_pages - 1, nxt - 1, -1))
        return moves

    # --- dispatch views -------------------------------------------------
    def rows(self, slots) -> Tuple[np.ndarray, np.ndarray]:
        """(page_table_rows, seq_lens) for a list of slots, as the int32
        arrays a serving program takes. Padding to a bucket is the caller's
        job (``-1`` rows / length 0 are always safe: trash-page semantics)."""
        idx = np.asarray(slots, np.int32)
        return self.page_table[idx], self.seq_lens[idx]
