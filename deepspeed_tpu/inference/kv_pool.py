"""Block-pool KV cache for paged serving.

The dense decode workspace (``inference/decode.py:init_cache``) allocates
``[L, B, max_len, NKV, D]`` per batch — HBM scales with ``batch × max_len``
whether or not those tokens exist. Here the cache is a shared pool of
fixed-size pages ``[L, num_pages, NKV, page_size, D]`` plus a per-sequence
page table: HBM holds ``live_tokens × bytes_per_token`` rounded up to page
granularity, and any free page can serve any sequence (the vLLM block-table
layout; the reference approximates it with contiguous per-sequence
workspaces — ``allocate_workspace`` in
``csrc/transformer/inference/csrc/pt_binding.cpp``).

Split of responsibilities:

* ``PagedKVCache`` — the device arrays. Jitted programs read/write them
  through ``ops/transformer/paged_attention.py`` and the scatter in
  ``inference/decode.py``; they are donated into every serving program so
  updates alias in place.
* ``PagePool`` — the host-side allocator: free list, per-slot page tables
  and live lengths (numpy; they ride into each dispatch as plain int32
  arrays, so allocation changes never retrace a program), alloc/free/defrag.

Page 0 is the reserved TRASH page: it is never allocated, table sentinels
(-1) clamp onto it inside the kernels, and dead-slot writes land there — a
padded batch row can never corrupt a live sequence's pages.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.config import TransformerConfig

TRASH_PAGE = 0

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


class PagedKVCache(NamedTuple):
    """Device page pool, one stacked array per K and V.

    Layout ``[L, num_pages, NKV, page_size, D]``: the layer axis scans, and
    each layer slice is exactly the ``[NP, NKV, P, D]`` pool the paged
    attention kernels take.
    """

    k_pages: jax.Array
    v_pages: jax.Array

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[3]

    @property
    def bytes_per_token(self) -> int:
        """HBM bytes one cached token costs across all layers (K + V)."""
        L, _, NKV, _, D = self.k_pages.shape
        return 2 * L * NKV * D * self.k_pages.dtype.itemsize

    def hbm_bytes(self) -> int:
        return self.k_pages.nbytes + self.v_pages.nbytes


def init_paged_cache(
    cfg: TransformerConfig, num_pages: int, page_size: int, dtype=None
) -> PagedKVCache:
    if dtype is None:
        dtype = _DTYPES[cfg.dtype]
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size, cfg.head_dim)
    return PagedKVCache(k_pages=jnp.zeros(shape, dtype), v_pages=jnp.zeros(shape, dtype))


class PagePool:
    """Host-side page allocator over a ``PagedKVCache``.

    A *slot* is one concurrently-running sequence (a row of the serving
    batch); each slot owns a page-table row of ``max_pages_per_slot``
    entries. ``seq_lens[slot]`` counts tokens already written. Sequences
    acquire pages lazily as they grow and return them on ``free_slot`` —
    total cache HBM is fixed at ``num_pages``, but the *live* footprint is
    ``used_pages × page_size × bytes_per_token``.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        num_pages: int,
        page_size: int,
        max_slots: int,
        max_seq_len: Optional[int] = None,
        dtype=None,
    ):
        if page_size < 1 or num_pages < 2:
            raise ValueError("need page_size >= 1 and num_pages >= 2 (page 0 is reserved)")
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        self.max_pages_per_slot = -(-self.max_seq_len // self.page_size)
        self.cache = init_paged_cache(cfg, num_pages, page_size, dtype=dtype)
        # LIFO free list keeps hot pages hot; page 0 stays out of circulation
        self._free = list(range(num_pages - 1, TRASH_PAGE, -1))
        self._free_slots = list(range(max_slots - 1, -1, -1))
        self.page_table = np.full((max_slots, self.max_pages_per_slot), -1, np.int32)
        self.seq_lens = np.zeros(max_slots, np.int32)
        self._owned = np.zeros(max_slots, np.int32)  # pages held per slot

    # --- capacity accounting -------------------------------------------
    @property
    def num_pages(self) -> int:
        return self.cache.num_pages

    def free_pages(self) -> int:
        return len(self._free)

    def used_pages(self) -> int:
        return self.num_pages - 1 - len(self._free)  # trash page excluded

    def pages_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_size)

    def live_tokens(self) -> int:
        return int(self.seq_lens.sum())

    def live_hbm_bytes(self) -> int:
        """HBM actually pinned by live sequences (page-granular)."""
        return self.used_pages() * self.page_size * self.cache.bytes_per_token

    def utilization(self) -> float:
        """Live tokens over allocated page capacity (1.0 = no page waste)."""
        cap = self.used_pages() * self.page_size
        return self.live_tokens() / cap if cap else 0.0

    # --- slot lifecycle -------------------------------------------------
    def can_admit(self, n_tokens: int) -> bool:
        """A free slot exists and the pool can hold ``n_tokens`` now."""
        return (
            bool(self._free_slots)
            and n_tokens <= self.max_seq_len
            and self.pages_for(n_tokens) <= self.free_pages()
        )

    def alloc_slot(self, n_tokens: int = 0) -> Optional[int]:
        """Claim a slot, pre-reserving pages for ``n_tokens``; None if the
        pool cannot host it right now (caller keeps the request queued)."""
        if not self.can_admit(max(n_tokens, 1)):
            return None
        slot = self._free_slots.pop()
        self.seq_lens[slot] = 0
        if n_tokens and not self.ensure(slot, n_tokens):
            self.free_slot(slot)
            return None
        return slot

    def ensure(self, slot: int, new_len: int) -> bool:
        """Grow ``slot``'s table to cover ``new_len`` tokens. All-or-nothing:
        on a pool-exhausted failure nothing is allocated (the caller decides
        whom to preempt and retries)."""
        if new_len > self.max_seq_len:
            return False
        need = self.pages_for(new_len) - self._owned[slot]
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        for _ in range(int(need)):
            self.page_table[slot, self._owned[slot]] = self._free.pop()
            self._owned[slot] += 1
        return True

    def advance(self, slot: int, n_tokens: int) -> None:
        """Record ``n_tokens`` newly written to ``slot`` (pages must already
        be ensured)."""
        new_len = int(self.seq_lens[slot]) + int(n_tokens)
        assert self.pages_for(new_len) <= self._owned[slot], (
            f"slot {slot}: advancing to {new_len} tokens past its "
            f"{int(self._owned[slot])} allocated pages"
        )
        self.seq_lens[slot] = new_len

    def rollback(self, slot: int, n_tokens: int) -> int:
        """Un-write the last ``n_tokens`` of ``slot`` — speculative decode's
        rejected draft tail: shrink the live length and return every page
        past the new length to the free list (LIFO, so the tail pages are
        the first reused). The data in the rolled-back region is NOT
        cleared — the length mask makes it invisible, and the next write at
        those positions overwrites it. Returns how many pages came back."""
        n_tokens = int(n_tokens)
        new_len = int(self.seq_lens[slot]) - n_tokens
        if n_tokens < 0 or new_len < 0:
            raise ValueError(
                f"rollback({slot}, {n_tokens}): slot holds "
                f"{int(self.seq_lens[slot])} tokens"
            )
        self.seq_lens[slot] = new_len
        keep = self.pages_for(new_len)
        freed = 0
        while self._owned[slot] > keep:
            self._owned[slot] -= 1
            i = int(self._owned[slot])
            self._free.append(int(self.page_table[slot, i]))
            self.page_table[slot, i] = -1
            freed += 1
        return freed

    def free_slot(self, slot: int) -> int:
        """Release the slot and return its pages to the pool; returns how
        many pages came back."""
        n = int(self._owned[slot])
        for i in range(n):
            self._free.append(int(self.page_table[slot, i]))
        self.page_table[slot, :] = -1
        self.seq_lens[slot] = 0
        self._owned[slot] = 0
        self._free_slots.append(slot)
        return n

    # --- maintenance ----------------------------------------------------
    def defrag(self) -> int:
        """Compact live pages into the lowest ids (one device gather per
        K/V), rewriting tables and rebuilding the free list. Keeps the hot
        working set dense — e.g. so a checkpointed/snapshotted pool prefix
        of ``used_pages + 1`` pages captures every live token. Returns the
        number of pages that moved."""
        live = [
            int(self.page_table[s, i])
            for s in range(self.max_slots)
            for i in range(int(self._owned[s]))
        ]
        perm = np.arange(self.num_pages, dtype=np.int32)  # new_id -> old_id
        remap = {}  # old_id -> new_id
        nxt = TRASH_PAGE + 1
        for old in live:
            remap[old] = nxt
            perm[nxt] = old
            nxt += 1
        # unassigned tail: the remaining (free) pages in any order
        rest = [p for p in range(TRASH_PAGE + 1, self.num_pages) if p not in remap]
        perm[nxt:] = np.asarray(rest, np.int32)
        moves = sum(1 for old, new in remap.items() if old != new)
        if moves == 0:
            return 0
        gather = jnp.asarray(perm)
        self.cache = PagedKVCache(
            k_pages=self.cache.k_pages[:, gather],
            v_pages=self.cache.v_pages[:, gather],
        )
        for s in range(self.max_slots):
            for i in range(int(self._owned[s])):
                self.page_table[s, i] = remap[int(self.page_table[s, i])]
        self._free = list(range(self.num_pages - 1, nxt - 1, -1))
        return moves

    # --- dispatch views -------------------------------------------------
    def rows(self, slots) -> Tuple[np.ndarray, np.ndarray]:
        """(page_table_rows, seq_lens) for a list of slots, as the int32
        arrays a serving program takes. Padding to a bucket is the caller's
        job (``-1`` rows / length 0 are always safe: trash-page semantics)."""
        idx = np.asarray(slots, np.int32)
        return self.page_table[idx], self.seq_lens[idx]
