"""Model-free speculative drafting for the paged serving engine.

Speculative decoding splits every serving round into host-side *drafting*
and one device *verify* dispatch: a drafter proposes up to K plausible next
tokens per running request, and ``build_paged_verify_step``
(``inference/decode.py``) scores all K+1 positions (drafts + the bonus
slot) in a single program, accepting the longest prefix that matches the
model's own greedy argmax — so the output stream is byte-identical to
non-speculative decode while each accepted draft turns a whole
model-streaming dispatch (plus its tunnel RTT, PERF.md) into one extra
row of an already-running matmul.

This module owns the drafting side:

* ``Drafter`` — the interface the scheduler drives. Implementations keep
  per-request state keyed by the request uid (the scheduler calls
  ``drop`` when a request finishes); a small draft *model* can implement
  the same two methods and slot in unchanged.
* ``NGramDrafter`` — prompt-lookup / n-gram drafting (the model-free
  default): the continuation after the most recent earlier occurrence of
  the context's own suffix n-gram. Zero extra HBM, no second model, and
  an incremental per-request index so each emitted token costs O(order)
  host work — repetitive spans (code, templated text, retrieval quotes)
  are exactly where serving traffic has exploitable structure.

Drafting never needs to be right — only cheap. A wrong draft costs one
rejected row in the verify matmul; a missing draft just makes the round a
plain decode step.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

# per n-gram key: how many most-recent occurrence starts to retain (the
# newest occurrence is usually the suffix itself, so keep a few behind it)
_OCCURRENCES_KEPT = 4


class Drafter:
    """Interface between the scheduler and a draft source.

    ``propose(uid, context, k)`` returns up to ``k`` int32 draft tokens
    continuing ``context`` (the request's prompt + everything emitted);
    returning fewer — or none — is always legal. ``drop(uid)`` releases
    any per-request state once the request finishes.
    """

    def propose(self, uid: int, context: np.ndarray, k: int) -> np.ndarray:
        raise NotImplementedError

    def drop(self, uid: int) -> None:  # noqa: B027 - optional hook
        pass


class _NGramIndex:
    """One request's incremental n-gram index: for every order 1..N, the
    most recent start positions of each n-gram seen so far."""

    __slots__ = ("toks", "idx")

    def __init__(self, order: int):
        self.toks: List[int] = []
        self.idx: List[Dict[tuple, List[int]]] = [dict() for _ in range(order)]

    def extend(self, new_tokens) -> None:
        order = len(self.idx)
        for t in new_tokens:
            self.toks.append(int(t))
            i = len(self.toks) - 1
            for o in range(1, min(order, i + 1) + 1):
                key = tuple(self.toks[i - o + 1 : i + 1])
                starts = self.idx[o - 1].setdefault(key, [])
                starts.insert(0, i - o + 1)  # newest first
                del starts[_OCCURRENCES_KEPT:]


class NGramDrafter(Drafter):
    """Prompt-lookup drafting: propose the continuation that followed the
    most recent earlier occurrence of the context's suffix n-gram, trying
    orders ``ngram_order`` down to 1 (longer matches first — they predict
    better)."""

    def __init__(self, ngram_order: int = 3):
        if ngram_order < 1:
            raise ValueError(f"ngram_order must be >= 1, got {ngram_order}")
        self.order = int(ngram_order)
        self._state: Dict[int, _NGramIndex] = {}

    def propose(self, uid: int, context: np.ndarray, k: int) -> np.ndarray:
        empty = np.zeros(0, np.int32)
        context = np.asarray(context, np.int32).reshape(-1)
        n = context.size
        if k < 1 or n < 2:
            return empty
        st = self._state.get(uid)
        if st is None or len(st.toks) > n:
            # new request — or a context that shrank, which the scheduler
            # never produces (preemption keeps emitted tokens): rebuild
            st = self._state[uid] = _NGramIndex(self.order)
        st.extend(context[len(st.toks) :])
        for o in range(min(self.order, n - 1), 0, -1):
            key = tuple(int(t) for t in context[n - o :])
            for start in st.idx[o - 1].get(key, ()):
                cont = start + o
                if cont < n:  # skip the suffix's own occurrence (no future)
                    return context[cont : cont + k].copy()
        return empty

    def drop(self, uid: int) -> None:
        self._state.pop(uid, None)
