"""Incremental decoding with a KV cache.

TPU-native counterpart of the reference's fused decoder inference kernels
(``csrc/transformer/inference/csrc/pt_binding.cpp``: ``softmax_context`` =
KV-cache attention, ``qkv_gemm``/``mlp_gemm`` fused projections,
``apply_rotary_pos_emb``, workspace = the preallocated KV cache,
``allocate_workspace`` :1929): one jitted ``prefill`` program consumes the
prompt and fills the cache; one jitted ``decode_step`` program appends a
single token — in-place cache updates via ``dynamic_update_slice`` with
buffer donation, so decoding runs at HBM-bandwidth with no reallocation and
exactly two compiled programs per (batch, max_len) bucket.

Works on the flagship ``TransformerLM`` parameter layout (stacked [L, ...]
layer params, ``models/transformer.py``); numerics are kept in lockstep with
the training forward — guarded by the decode-vs-full-forward parity test
(``tests/unit/inference/test_decode.py``).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression.int8 import qmatmul
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.models.transformer import _norm, _rope

NEG_INF_F = -1e30  # additive mask for dead beams (finite: keeps fp math NaN-free)


class KVCache(NamedTuple):
    """Preallocated decode workspace (reference allocate_workspace)."""

    k: jax.Array  # [L, B, max_len, NKV, D]
    v: jax.Array  # [L, B, max_len, NKV, D]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(cfg: TransformerConfig, batch: int, max_len: int, dtype=None) -> KVCache:
    if dtype is None:
        dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}[
            cfg.dtype
        ]
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def _layer_project_qkv(cfg: TransformerConfig, p, h):
    """Norm + qkv projection for a [B, T, H] slab (same ops as
    models/transformer.py _layer). Column-parallel under TP serving: the
    weights arrive pre-sliced by shard_map (cfg is then the LOCAL view),
    and ``qmatmul`` fuses int8 dequantization when the weights are
    quantized (``compression/int8.py``)."""
    B, T, _ = h.shape
    NH, NKV, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    hn = _norm(h, p["attn_norm_scale"], p.get("attn_norm_bias"), cfg.norm, cfg.norm_eps)
    q = qmatmul(hn, p["wq"])
    k = qmatmul(hn, p["wk"])
    v = qmatmul(hn, p["wv"])
    if cfg.qkv_bias:
        q = q + p["bq"].astype(hn.dtype)
        k = k + p["bk"].astype(hn.dtype)
        v = v + p["bv"].astype(hn.dtype)
    return (
        q.reshape(B, T, NH, D),
        k.reshape(B, T, NKV, D),
        v.reshape(B, T, NKV, D),
    )


def _moe_ffn(cfg, p, h):
    """Eval-mode MoE routing for a normed [B, T, H] slab (ISSUE 20 serving
    tentpole): in-program top-k gate + capacity-bucketed expert einsum, the
    exact inference semantics of ``moe/layer.py`` ``MoE.apply(train=False)``
    — eval capacity factor, no gate noise, no RNG (deterministic drops).
    Capacity is a Python int from the static token count, so shifting
    expert-routing mixes are pure data: the paged programs never retrace.
    Expert weights may be int8 (``quantize_params_int8`` stacks scales as
    ``[E, 1, I]``); ``apply_expert_ffn`` fuses the dequantization."""
    from deepspeed_tpu.moe import sharded_moe
    from deepspeed_tpu.moe.experts import apply_dense_ffn, apply_expert_ffn

    B, T, H = h.shape
    tokens = h.reshape(-1, H)
    logits = tokens.astype(jnp.float32) @ p["gate"]["wg"]
    _l_aux, combine_w, dispatch_m, _counts = sharded_moe.topkgating(
        logits,
        cfg.moe_top_k,
        cfg.eval_capacity_factor,
        cfg.min_capacity,
        drop_tokens=cfg.moe_drop_tokens,
        rng=None,
        noisy_gate_policy=None,
        use_rts=cfg.moe_use_rts,
    )
    dispatched = sharded_moe.dispatch(tokens, dispatch_m)
    expert_out = apply_expert_ffn(p["experts"], dispatched, cfg.activation)
    out = sharded_moe.combine(expert_out, combine_w)
    if "mlp" in p:
        # PR-MoE residual branch: dense MLP in parallel, learned 2-way mix
        mlp_out = apply_dense_ffn(p["mlp"], tokens, cfg.activation)
        coef = tokens.astype(jnp.float32) @ p["coefficient"]["w"] + p["coefficient"]["b"]
        coef = jax.nn.softmax(coef, axis=-1).astype(out.dtype)
        out = out * coef[..., 0:1] + mlp_out * coef[..., 1:2]
    return out.reshape(B, T, H)


def _ffn_body(cfg: TransformerConfig, p, x, norm_scale, norm_bias, tp=None):
    """norm → ffn, NO residual — callers place the residual per architecture."""
    from deepspeed_tpu.moe.experts import apply_dense_ffn

    h = _norm(x, norm_scale, norm_bias, cfg.norm, cfg.norm_eps)
    if "moe" in p:
        if tp is not None:
            raise NotImplementedError(
                "tensor-parallel MoE serving is not supported: expert "
                "placement is the 'expert' mesh axis, not a TP weight split"
            )
        return _moe_ffn(cfg, p["moe"], h)
    return apply_dense_ffn(p, h, cfg.activation, tp=tp)


def _layer_mlp(cfg: TransformerConfig, p, x, tp=None):
    return x + _ffn_body(cfg, p, x, p["mlp_norm_scale"], p.get("mlp_norm_bias"), tp=tp)


def _softmax_scale(cfg, head_dim: int) -> float:
    return (
        cfg.attn_softmax_scale
        if getattr(cfg, "attn_softmax_scale", None) is not None
        else 1.0 / float(np.sqrt(head_dim))
    )


def _post_attention(cfg, p, x, attn, tp=None):
    """Output projection + residual placement + mlp — shared tail of every
    cached-attention layer (dense and paged), so the two decode paths can
    never drift on the residual architecture. Under TP serving the output
    projection is row-parallel: each chip holds its heads' slice of
    ``wo``, the partial sums meet in ``tp.row_matmul``'s (chunked,
    optionally quantized) all-reduce, and the bias — replicated — is
    added exactly once, after the reduce."""
    B, T = x.shape[:2]
    a = attn.reshape(B, T, cfg.num_heads * cfg.head_dim)
    attn = (tp.row_matmul(a, p["wo"]) if tp is not None else qmatmul(a, p["wo"]))
    attn = attn.astype(x.dtype)
    if cfg.use_bias:
        attn = attn + p["bo"].astype(x.dtype)
    if cfg.parallel_residual:
        # GPT-J/NeoX: mlp branch reads x (shared ln_1 or its own norm),
        # not the attn-updated residual
        norm_scale = p["attn_norm_scale"] if cfg.shared_parallel_norm else p["mlp_norm_scale"]
        norm_bias = (
            p.get("attn_norm_bias") if cfg.shared_parallel_norm else p.get("mlp_norm_bias")
        )
        return x + attn + _ffn_body(cfg, p, x, norm_scale, norm_bias, tp=tp)
    x = x + attn
    return _layer_mlp(cfg, p, x, tp=tp)


def _cached_attention(cfg, q, k_cache, v_cache, q_positions, kv_len_mask, kv_len=None):
    """q [B,T,NH,D] against the full cache [B,S,NKV,D]; positions beyond the
    valid length are masked (the reference softmax_context semantics)."""
    NH, NKV = q.shape[2], k_cache.shape[2]
    scale = _softmax_scale(cfg, q.shape[-1])
    if (
        q.shape[1] == 1
        and kv_len is not None
        and cfg.position != "alibi"
        and k_cache.shape[1] % 256 == 0
    ):
        # single-token decode: the fused ragged kernel reads only live cache
        # blocks (and GQA kv rows once, without any head expansion)
        from deepspeed_tpu.ops.transformer.decode_attention import decode_attention

        out = decode_attention(q[:, 0], k_cache, v_cache, kv_len, scale=scale)
        return out[:, None]
    S = k_cache.shape[1]
    kv_pos = jnp.arange(S, dtype=jnp.int32)
    causal = q_positions[:, None, :, None] >= kv_pos[None, None, None, :]
    valid = kv_len_mask[None, None, None, :] if kv_len_mask is not None else True
    if NKV != NH:
        # GQA: group the queries [B,T,NKV,G,D] against the shared kv rows —
        # an NH-wide jnp.repeat of the cache here would materialize a
        # G-times copy of the whole workspace every decode step
        B, T, _, D = q.shape
        G = NH // NKV
        qg = q.reshape(B, T, NKV, G, D)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, k_cache).astype(jnp.float32) * scale
        mask = causal & valid  # [B, 1, T, S] -> [B, 1, 1, T, S] under kv/group axes
        scores = jnp.where(mask[:, :, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
        out = jnp.einsum("bkgts,bskd->btkgd", probs, v_cache)
        return out.reshape(B, T, NH, D)
    scores = jnp.einsum("btnd,bsnd->bnts", q, k_cache).astype(jnp.float32) * scale
    scores = jnp.where(causal & valid, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    return jnp.einsum("bnts,bsnd->btnd", probs, v_cache)


def _forward_with_cache(cfg, params, tokens, cache: KVCache, start_pos):
    """Run [B, T] tokens starting at ``start_pos``, reading+writing the
    cache. Returns (logits_of_last_token, new_cache)."""
    B, T = tokens.shape
    dtype = cache.k.dtype
    x = params["embed"]["tokens"].astype(dtype)[tokens]
    positions = start_pos + jnp.arange(T, dtype=jnp.int32)
    positions_b = jnp.broadcast_to(positions[None, :], (B, T))
    if cfg.position == "learned":
        x = x + params["embed"]["pos"].astype(dtype)[positions][None]

    S = cache.max_len
    kv_pos = jnp.arange(S, dtype=jnp.int32)
    kv_len_mask = kv_pos < (start_pos + T)

    def layer_step(carry, per_layer):
        x = carry
        p, k_cache_l, v_cache_l = per_layer
        q, k_new, v_new = _layer_project_qkv(cfg, p, x)
        if cfg.position == "rope":
            q = _rope(q, positions_b, cfg.rope_theta, cfg.rope_dim)
            k_new = _rope(k_new, positions_b, cfg.rope_theta, cfg.rope_dim)
        k_cache_l = jax.lax.dynamic_update_slice(
            k_cache_l, k_new.astype(k_cache_l.dtype), (0, start_pos, 0, 0)
        )
        v_cache_l = jax.lax.dynamic_update_slice(
            v_cache_l, v_new.astype(v_cache_l.dtype), (0, start_pos, 0, 0)
        )
        attn = _cached_attention(
            cfg, q, k_cache_l, v_cache_l, positions_b, kv_len_mask, kv_len=start_pos + T
        )
        x = _post_attention(cfg, p, x, attn)
        return x, (k_cache_l, v_cache_l)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache.k, cache.v)
    )

    return _final_logits(cfg, params, x)[:, -1, :], KVCache(k=new_k, v=new_v)


def _final_logits(cfg, params, x):
    """Final norm + LM head. Under TP serving with an untied vocab-sharded
    head the returned logits are each chip's LOCAL vocab slice — the
    builders resolve greedy tokens through ``tp.argmax`` (global-first-max
    semantics), so full logits never gather."""
    x = _norm(
        x, params["final_norm_scale"], params.get("final_norm_bias"), cfg.norm, cfg.norm_eps
    )
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["tokens"].astype(x.dtype).T
    else:
        logits = qmatmul(x, params["lm_head"])
        if cfg.lm_head_bias:
            logits = logits + params["lm_head_bias"].astype(logits.dtype)
    return logits


def _cfg_key(cfg) -> Tuple:
    """Value-based cache key: ``id(cfg)`` could serve a stale compiled
    program if a config object is garbage-collected and another allocated
    at the recycled address."""
    import dataclasses

    try:
        return (
            type(cfg).__name__,
            tuple(
                (f.name, repr(getattr(cfg, f.name, None)))
                for f in dataclasses.fields(cfg)
            ),
        )
    except TypeError:
        return (type(cfg).__name__, repr(cfg))


_decoder_cache: Dict[Tuple, Tuple] = {}


def _jit(fn, telemetry, name, **jit_kwargs):
    """jax.jit, counted under ``name`` when a CompileTelemetry is given —
    the engines' compile_stats() path (profiling/compile_telemetry.py)."""
    if telemetry is None:
        return jax.jit(fn, **jit_kwargs)
    return telemetry.instrument(name, fn, **jit_kwargs)


def _telemetry_uid(telemetry):
    """Program-cache key component: compiled callables built against one
    telemetry registry must not be served to another engine's registry."""
    return None if telemetry is None else telemetry.uid


def build_decoder(cfg: TransformerConfig, telemetry=None) -> Tuple[Any, Any]:
    """(prefill, decode_step) jitted pair for a model config.

    ``prefill(params, tokens, cache)`` consumes the prompt [B, T];
    ``decode_step(params, token, cache, pos)`` appends one token [B].
    Both donate the cache buffer (in-place workspace update).
    """
    key = (_cfg_key(cfg), _telemetry_uid(telemetry))
    if key in _decoder_cache:
        return _decoder_cache[key]

    prefill = _jit(
        lambda params, tokens, cache: _forward_with_cache(
            cfg, params, tokens, cache, jnp.int32(0)
        ),
        telemetry,
        "kv_prefill",
        donate_argnums=(2,),
    )
    decode_step = _jit(
        lambda params, token, cache, pos: _forward_with_cache(
            cfg, params, token[:, None], cache, pos
        ),
        telemetry,
        "kv_decode_step",
        donate_argnums=(2,),
    )
    _decoder_cache[key] = (prefill, decode_step)
    return prefill, decode_step


# LRU-bounded: serving/rollout loops with varying prompt lengths would
# otherwise retain one whole-loop executable per (lengths, sampling) bucket
# for the process lifetime
_loop_cache: "OrderedDict[Tuple, Any]" = OrderedDict()
_LOOP_CACHE_MAX = 32


def _loop_cache_get(key):
    loop = _loop_cache.get(key)
    if loop is not None:
        _loop_cache.move_to_end(key)
    return loop


def _loop_cache_put(key, loop):
    _loop_cache[key] = loop
    while len(_loop_cache) > _LOOP_CACHE_MAX:
        _loop_cache.popitem(last=False)


def generate(
    cfg: TransformerConfig,
    params,
    input_ids,
    max_new_tokens: int,
    eos_token_id=None,
    temperature: float = 0.0,
    rng=None,
    top_k: int = 0,
    top_p: float = 1.0,
    pad_token_id: int = 0,
    dtype=None,
    telemetry=None,
):
    """KV-cached generation: one jitted prefill + ONE jitted decode loop.

    The whole token-by-token loop is a single compiled ``lax.while_loop``
    program — sampling (greedy / temperature / top-k / top-p,
    ``inference/sampling.py``) and the EOS check run on device, so the only
    host round-trip of the entire generation is fetching the final token
    array. The loop exits early on device once every row has emitted EOS
    (rows finished earlier keep emitting EOS as padding).

    Replaces the reference's per-token kernel-launch loop
    (``deepspeed/inference/engine.py:578`` → HF generate) — same sampling
    controls, but batched into two XLA programs per (batch, lengths,
    sampling-config) bucket.
    """
    import functools

    from deepspeed_tpu.inference.sampling import sample_logits

    tokens = jnp.asarray(input_ids)
    if tokens.ndim == 1:
        tokens = tokens[None, :]
    B, prompt_len = tokens.shape
    max_len = prompt_len + max_new_tokens
    cache = init_cache(cfg, B, max_len, dtype=dtype)
    prefill, _ = build_decoder(cfg, telemetry)
    logits, cache = prefill(params, tokens, cache)
    if rng is None:
        # no rng = greedy (matching sample_logits), never a silently fixed
        # key masquerading as randomness; the carry still needs a key object
        temperature = 0.0
        rng = jax.random.PRNGKey(0)

    key = (
        _cfg_key(cfg), B, prompt_len, max_new_tokens, eos_token_id,
        float(temperature), int(top_k), float(top_p), int(pad_token_id),
        str(tokens.dtype), str(cache.k.dtype), _telemetry_uid(telemetry),
    )
    loop = _loop_cache_get(key)
    if loop is None:
        sample = functools.partial(
            sample_logits, temperature=temperature, top_k=top_k, top_p=top_p
        )

        def _loop(params, logits, cache, rng, out):
            def cond(c):
                step, _, _, _, _, finished = c
                return jnp.logical_and(
                    step < max_new_tokens, jnp.logical_not(jnp.all(finished))
                )

            def body(c):
                step, logits, cache, rng, out, finished = c
                rng, sub = jax.random.split(rng)
                tok = sample(logits, sub).astype(out.dtype)
                if eos_token_id is not None:
                    tok = jnp.where(
                        finished, jnp.asarray(eos_token_id, out.dtype), tok
                    )
                out = jax.lax.dynamic_update_slice(
                    out, tok[:, None], (0, prompt_len + step)
                )
                if eos_token_id is not None:
                    finished = finished | (tok == eos_token_id)
                logits, cache = _forward_with_cache(
                    cfg, params, tok[:, None], cache, prompt_len + step
                )
                return (step + 1, logits, cache, rng, out, finished)

            state = (
                jnp.int32(0), logits, cache, rng, out, jnp.zeros((B,), bool)
            )
            step, _, cache, _, out, _ = jax.lax.while_loop(cond, body, state)
            # the final cache is returned (and ignored by the caller) so the
            # donated input cache can alias an output instead of being copied
            # into the loop carry
            return out, step, cache

        loop = _jit(_loop, telemetry, "kv_decode_loop", donate_argnums=(2, 4))
        _loop_cache_put(key, loop)

    out0 = jnp.full((B, max_len), pad_token_id, tokens.dtype)
    out0 = jax.lax.dynamic_update_slice(out0, tokens, (0, 0))
    out, n_emitted, _ = loop(params, logits, cache, rng, out0)
    return out[:, : prompt_len + int(jax.device_get(n_emitted))]


def beam_generate(
    cfg: TransformerConfig,
    params,
    input_ids,
    max_new_tokens: int,
    num_beams: int = 4,
    eos_token_id=None,
    pad_token_id: int = 0,
    length_penalty: float = 1.0,
    dtype=None,
    telemetry=None,
):
    """KV-cached beam search as ONE jitted decode loop.

    The reference reaches beam search by delegating to HF ``generate``
    (``deepspeed/inference/engine.py:578``), which re-orders its past-KV
    tuples on the host every step. Here beams are a device-side batch
    dimension: the prompt prefills ONCE at batch B, the cache is tiled to
    B*K rows before the loop (so the loop donates and aliases it in place),
    and each step's beam reorder is a gather over the cache's batch axis
    INSIDE the compiled ``lax.while_loop`` — no host round-trips until the
    final fetch.

    Hypothesis semantics follow HF's BeamSearchScorer with
    ``early_stopping=True``: each step draws 2K candidates so EOS landings
    never shrink the live set below K; EOS candidates are recorded into a
    per-row best-finished register scored by
    ``cum_logprob / (prompt_len + emitted)**length_penalty`` (full sequence
    length, the HF denominator) and the K best non-EOS candidates continue;
    a row stops once K finished hypotheses have been seen. The final answer
    is the better of the best finished hypothesis and the best live beam.
    First-expansion dedup: beam 0 starts at cum 0, the rest at -inf, so the
    first top-2K draw expands distinct tokens. Returns
    [B, prompt_len + emitted].
    """
    K = int(num_beams)
    tokens = jnp.asarray(input_ids)
    if tokens.ndim == 1:
        tokens = tokens[None, :]
    B, prompt_len = tokens.shape
    max_len = prompt_len + max_new_tokens
    V = cfg.vocab_size

    cache = init_cache(cfg, B, max_len, dtype=dtype)
    prefill, _ = build_decoder(cfg, telemetry)
    logits, cache = prefill(params, tokens, cache)  # [B, V]

    # tile to B*K OUTSIDE the loop: the loop's donated cache/out buffers are
    # then exactly the arrays it carries, so XLA aliases them in place
    # one-time beam tiling, not a per-step expansion: after divergence each
    # beam owns its cache rows (the loop updates them in place per beam)
    cache = KVCache(k=jnp.repeat(cache.k, K, axis=1), v=jnp.repeat(cache.v, K, axis=1))  # lint: allow(DS-R001)
    out0 = jnp.full((B * K, max_len), pad_token_id, tokens.dtype)
    out0 = jax.lax.dynamic_update_slice(out0, jnp.repeat(tokens, K, axis=0), (0, 0))
    logits = jnp.repeat(logits, K, axis=0)

    key = (
        "beam", _cfg_key(cfg), B, K, prompt_len, max_new_tokens,
        eos_token_id, int(pad_token_id), float(length_penalty),
        str(tokens.dtype), str(cache.k.dtype), _telemetry_uid(telemetry),
    )
    loop = _loop_cache_get(key)
    if loop is None:

        def _norm_score(cum, emitted):
            # HF denominator: the FULL sequence length (prompt + generated)
            length = (prompt_len + jnp.maximum(emitted, 1)).astype(jnp.float32)
            return cum / length**length_penalty

        def _loop(params, logits, cache, out):
            cum0 = jnp.full((B, K), NEG_INF_F, jnp.float32).at[:, 0].set(0.0)
            rows = jnp.arange(B, dtype=jnp.int32)

            def cond(c):
                step, done_count = c[0], c[5]
                live = (
                    jnp.any(done_count < K)
                    if eos_token_id is not None
                    else jnp.bool_(True)
                )
                return jnp.logical_and(step < max_new_tokens, live)

            def body(c):
                (step, logits, cache, out, cum, done_count,
                 best_score, best_out, best_len) = c
                # every live beam has emitted exactly `step` tokens (beams
                # only permute among equals), so length is scalar state
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
                total = cum[:, :, None] + logp.reshape(B, K, V)
                # 2K candidates (HF): EOS landings never starve the live set
                cand_cum, flat_idx = jax.lax.top_k(total.reshape(B, K * V), 2 * K)
                cand_beam = flat_idx // V  # [B, 2K]
                cand_tok = flat_idx % V

                if eos_token_id is not None:
                    is_eos = cand_tok == eos_token_id
                    # HF records/counts ONLY EOS candidates ranked < K
                    # (BeamSearchScorer: beam_token_rank >= group_size -> skip);
                    # lower-ranked EOS are neither recorded nor continued
                    topk_rank = jnp.arange(2 * K) < K
                    rec = is_eos & topk_rank[None, :]
                    fin = jnp.where(
                        rec, _norm_score(cand_cum, jnp.int32(step) + 1), NEG_INF_F
                    )
                    j = jnp.argmax(fin, axis=1)
                    row_score = jnp.take_along_axis(fin, j[:, None], 1)[:, 0]
                    src = rows * K + jnp.take_along_axis(cand_beam, j[:, None], 1)[:, 0]
                    cand_out = jnp.take(out, src, axis=0)
                    cand_out = jax.lax.dynamic_update_slice(
                        cand_out,
                        jnp.full((B, 1), eos_token_id, out.dtype),
                        (0, prompt_len + step),
                    )
                    better = row_score > best_score
                    best_out = jnp.where(better[:, None], cand_out, best_out)
                    best_score = jnp.where(better, row_score, best_score)
                    best_len = jnp.where(better, step + 1, best_len)
                    done_count = done_count + jnp.sum(rec, axis=1)
                    live_vals = jnp.where(is_eos, NEG_INF_F, cand_cum)
                else:
                    live_vals = cand_cum

                new_cum, pick = jax.lax.top_k(live_vals, K)  # [B, K] into 2K
                beam_src = jnp.take_along_axis(cand_beam, pick, axis=1)
                tok = jnp.take_along_axis(cand_tok, pick, axis=1).astype(out.dtype)

                flat_src = (beam_src + rows[:, None] * K).reshape(B * K)
                out = jnp.take(out, flat_src, axis=0)
                cache = KVCache(
                    k=jnp.take(cache.k, flat_src, axis=1),
                    v=jnp.take(cache.v, flat_src, axis=1),
                )

                flat_tok = tok.reshape(B * K)
                out = jax.lax.dynamic_update_slice(
                    out, flat_tok[:, None], (0, prompt_len + step)
                )
                logits, cache = _forward_with_cache(
                    cfg, params, flat_tok[:, None], cache, prompt_len + step
                )
                return (step + 1, logits, cache, out, new_cum, done_count,
                        best_score, best_out, best_len)

            state = (
                jnp.int32(0), logits, cache, out, cum0,
                jnp.zeros((B,), jnp.int32),              # finished hyps seen
                jnp.full((B,), NEG_INF_F, jnp.float32),  # best finished score
                out[::K],                                # best finished seq
                jnp.zeros((B,), jnp.int32),              # its emitted length
            )
            (step, _, cache, out, cum, _,
             best_score, best_out, best_len) = jax.lax.while_loop(cond, body, state)
            live = _norm_score(cum, step)  # every live beam emitted `step`
            k_live = jnp.argmax(live, axis=1)
            live_out = jnp.take(out, rows * K + k_live, axis=0)
            live_score = jnp.take_along_axis(live, k_live[:, None], 1)[:, 0]
            use_fin = best_score >= live_score
            final_out = jnp.where(use_fin[:, None], best_out, live_out)
            final_len = jnp.where(use_fin, best_len, step)
            return final_out, jnp.max(final_len), cache

        loop = _jit(_loop, telemetry, "kv_beam_loop", donate_argnums=(2, 3))
        _loop_cache_put(key, loop)

    out, n_emitted, _ = loop(params, logits, cache, out0)
    return out[:, : prompt_len + int(jax.device_get(n_emitted))]


# --- paged (block-table) serving programs ----------------------------------
# The continuous-batching scheduler (inference/scheduler.py) drives these.
# Ragged mode (the default): ONE `build_ragged_step` program per step
# handles mixed prefill-chunk, decode, and verify rows together, driven by
# per-row (kv_len, q_len) metadata arrays — total compiled serving programs
# ≤ 2 (a narrow decode/verify width plus the mixed width covering prefill
# chunks). Multi-step windows (`build_ragged_multistep`, armed via
# `paged_kv.multi_step`) add at most ONE more program per horizon: a
# lax.scan of N plain-decode rounds dispatched when the running set is
# stable, amortizing the host gap to 1/N. Bucketed mode (the token-exactness oracle): per decode step ONE
# dispatch of a slot-bucket-sized program (or, with speculation, ONE
# dispatch of a (bucket, K)-shaped verify program); per prompt chunk one
# dispatch of a fixed-chunk prefill program — programs bounded by (slot
# buckets × spec lengths + slot buckets + chunk sizes). Neither is ever
# bounded by traffic.


def _program_name(kind: str, rows: int, width: int) -> str:
    """Unified serving-program name ``paged_<kind>_r<rows>_w<width>``: one
    scheme across the decode / prefill / verify / ragged builders (decode
    was keyed ``b<bucket>``, prefill ``c<chunk>``, verify
    ``b<bucket>_k<K>`` before), so compile telemetry counts serving
    programs consistently — the ragged ≤2-compile gate and the bench's
    ``compiled_programs`` field both count ``paged_*`` entries."""
    return f"paged_{kind}_r{int(rows)}_w{int(width)}"


# one cache for every compiled serving program, keyed by the unified
# program name + the build inputs that change lowering
_paged_program_cache: Dict[Tuple, Any] = {}


def _paged_program_key(name, cfg, page_size, attn_impl, telemetry, tp=None) -> Tuple:
    return (
        name, _cfg_key(cfg), int(page_size), attn_impl, _telemetry_uid(telemetry),
        None if tp is None else tp.cache_key(),
    )


def _tp_suffix(tp) -> str:
    """Program-name suffix for tensor-parallel builds: a shard_map-wrapped
    program is a different executable from the single-chip one even at the
    same (rows, width), and telemetry must not merge their counters — so
    every knob that changes the compiled schedule (degree, quantized
    comms, int8 weights, non-default comm chunking) marks the name."""
    if tp is None:
        return ""
    return (
        f"_tp{tp.degree}"
        + ("q" if tp.quantized_allreduce else "")
        + ("w8" if tp.quantized_weights else "")
        + (f"c{tp.comm_chunks}" if tp.comm_chunks != 2 else "")
    )


def _accepted_prefix(tokens, greedy, n_drafts):
    """Per-row count of leading drafts (``tokens[:, 1:]``) that match the
    model's own greedy argmax for their positions, bounded by ``n_drafts``
    — THE acceptance rule (argmax-compare ⇒ greedy outputs byte-identical
    to sequential decode), shared by the bucketed verify program and the
    ragged step so the oracle and the default path cannot drift."""
    n_slots = tokens.shape[1] - 1
    matches = (tokens[:, 1:] == greedy[:, :-1]) & (
        jnp.arange(n_slots, dtype=jnp.int32)[None, :] < n_drafts[:, None]
    )
    return jnp.sum(jnp.cumprod(matches.astype(jnp.int32), axis=1), axis=1)


def _scatter_pages(pages_l, vals, page_table, positions, page_size, valid=None):
    """Write [B, T, NKV, D] new k/v rows into one layer's page pool
    [NP, NKV, P, D] at absolute ``positions`` [B, T] through the page table
    [B, MAXP]. Sentinel table entries (< 0, i.e. unallocated/dead rows)
    clamp onto the reserved trash page 0, so padded bucket rows and prompt
    pad tails write garbage only where nothing lives. ``valid`` (bool
    [B, T], optional) force-redirects masked positions onto the trash page
    regardless of the table: the verify program's pad draft slots sit past
    a row's ensured pages, where ``positions // page_size`` could alias a
    LIVE page after the maxp clamp."""
    NP = pages_l.shape[0]
    maxp = page_table.shape[1]
    slot = jnp.clip(positions // page_size, 0, maxp - 1)
    pid = jnp.clip(jnp.take_along_axis(page_table, slot, axis=1), 0, NP - 1)
    if valid is not None:
        pid = jnp.where(valid, pid, 0)  # page 0 = the reserved trash page
    off = positions % page_size
    # advanced-index scatter: (pid, off) broadcast to [B, T] and land first,
    # giving the [B, T, NKV, D] update window vals fills exactly
    return pages_l.at[pid, :, off, :].set(vals)


def _paged_forward(cfg, params, tokens, k_pages, v_pages, page_table, positions_b,
                   attn_lens, attn_impl, write_valid=None, prefill_kv_lens=None,
                   ragged_q_lens=None, tp=None):
    """Forward [B, T] tokens against the paged cache: scatter each token's
    k/v into its page, then attend — single-token rows (T == 1) through the
    paged decode kernel with live lengths ``attn_lens``, chunks through the
    causal prefill attention (mask from ``positions_b``). ``write_valid``
    ([B, T] bool) redirects masked positions' k/v writes to the trash page;
    ``prefill_kv_lens`` ([B]) additionally bounds the causal attention to
    each row's live kv prefix (the verify program's pad-slot safety).
    ``ragged_q_lens`` ([B]) switches the attention to the unified ragged
    entry (mixed prefill/decode/verify rows, per-row metadata — the
    one-program serving step). ``tp`` (a ``inference/tp.py:TPServing``)
    marks the body as running INSIDE shard_map on a tensor-parallel mesh:
    ``cfg`` is then the local per-shard view (heads and kv pages sliced on
    the head axes), the row-parallel projections all-reduce through the
    context, and the returned logits may be the local vocab slice.
    Returns (logits [B, T, V], new_k_pages, new_v_pages)."""
    from deepspeed_tpu.ops.transformer.paged_attention import (
        paged_decode_attention,
        paged_prefill_attention,
        ragged_paged_attention,
    )

    B, T = tokens.shape
    dtype = k_pages.dtype
    P = k_pages.shape[3]
    x = params["embed"]["tokens"].astype(dtype)[tokens]
    if cfg.position == "learned":
        x = x + params["embed"]["pos"].astype(dtype)[positions_b]
    scale = _softmax_scale(cfg, cfg.head_dim)

    def layer_step(x, per_layer):
        p, kp_l, vp_l = per_layer
        q, k_new, v_new = _layer_project_qkv(cfg, p, x)
        if cfg.position == "rope":
            q = _rope(q, positions_b, cfg.rope_theta, cfg.rope_dim)
            k_new = _rope(k_new, positions_b, cfg.rope_theta, cfg.rope_dim)
        kp_l = _scatter_pages(kp_l, k_new.astype(dtype), page_table, positions_b, P,
                              valid=write_valid)
        vp_l = _scatter_pages(vp_l, v_new.astype(dtype), page_table, positions_b, P,
                              valid=write_valid)
        # attn_lens discriminates decode from prefill: a prefill_chunk=1
        # program also has T == 1 but must take the causal-mask path
        if ragged_q_lens is not None:
            attn = ragged_paged_attention(
                q, kp_l, vp_l, page_table, prefill_kv_lens, ragged_q_lens,
                scale=scale, impl=attn_impl,
            )
        elif T == 1 and attn_lens is not None:
            attn = paged_decode_attention(
                q[:, 0], kp_l, vp_l, page_table, attn_lens, scale=scale, impl=attn_impl
            )[:, None]
        else:
            attn = paged_prefill_attention(
                q, kp_l, vp_l, page_table, positions_b, scale=scale,
                kv_lens=prefill_kv_lens,
            )
        x = _post_attention(cfg, p, x, attn, tp=tp)
        return x, (kp_l, vp_l)

    x, (new_k, new_v) = jax.lax.scan(layer_step, x, (params["layers"], k_pages, v_pages))
    return _final_logits(cfg, params, x), new_k, new_v


def build_paged_decode_step(cfg, bucket: int, page_size: int, attn_impl: str = "auto",
                            telemetry=None):
    """One-dispatch decode step for a ``bucket``-row slot batch.

    ``decode_step(params, tokens [B], k_pages, v_pages, page_table [B, MAXP],
    lengths [B]) -> (next_tokens [B], k_pages, v_pages)``: writes each row's
    pending token at position ``lengths[b]``, attends over ``lengths[b]+1``
    live positions, returns the greedy next token (argmax runs in-program —
    the only host traffic per step is the [B] token fetch). Pages donated.
    Compiled once per bucket size; MAXP rides in from the table shape.
    """
    if cfg.position == "alibi":
        raise NotImplementedError("paged serving does not support alibi attention biases")
    name = _program_name("decode", bucket, 1)
    key = _paged_program_key(name, cfg, page_size, attn_impl, telemetry)
    fn = _paged_program_cache.get(key)
    if fn is not None:
        return fn

    def _decode(params, tokens, k_pages, v_pages, page_table, lengths):
        logits, new_k, new_v = _paged_forward(
            cfg, params, tokens[:, None], k_pages, v_pages, page_table,
            lengths[:, None], lengths + 1, attn_impl,
        )
        return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), new_k, new_v

    fn = _jit(_decode, telemetry, name, donate_argnums=(2, 3))
    _paged_program_cache[key] = fn
    return fn


def build_paged_prefill(cfg, chunk: int, page_size: int, attn_impl: str = "auto",
                        telemetry=None):
    """Fixed-size prompt-chunk program (one compile per chunk size).

    ``prefill(params, tokens [1, C], k_pages, v_pages, page_table [1, MAXP],
    start [1], last_idx) -> (next_token [1], k_pages, v_pages)``: scatters
    the chunk's k/v at ``start..start+C-1``, attends causally, and returns
    the greedy token after position ``last_idx`` (traced, so ragged final
    chunks never retrace). Short final chunks arrive padded; pad slots
    (index > ``last_idx``) redirect their writes to the trash page — a pad
    position past the table width would otherwise clamp onto the LAST live
    column and overwrite real prompt k/v — and are causally invisible to
    every real token."""
    if cfg.position == "alibi":
        raise NotImplementedError("paged serving does not support alibi attention biases")
    name = _program_name("prefill", 1, chunk)
    key = _paged_program_key(name, cfg, page_size, attn_impl, telemetry)
    fn = _paged_program_cache.get(key)
    if fn is not None:
        return fn

    def _prefill(params, tokens, k_pages, v_pages, page_table, start, last_idx):
        T = tokens.shape[1]
        offs = jnp.arange(T, dtype=jnp.int32)
        positions_b = start[:, None] + offs[None, :]
        valid = (offs <= last_idx)[None, :]  # pad tail -> trash page
        logits, new_k, new_v = _paged_forward(
            cfg, params, tokens, k_pages, v_pages, page_table, positions_b,
            None, attn_impl, write_valid=valid,
        )
        last = jax.lax.dynamic_index_in_dim(logits, last_idx, axis=1, keepdims=False)
        return jnp.argmax(last, axis=-1).astype(jnp.int32), new_k, new_v

    fn = _jit(_prefill, telemetry, name, donate_argnums=(2, 3))
    _paged_program_cache[key] = fn
    return fn


def build_paged_verify_step(cfg, bucket: int, K: int, page_size: int,
                            attn_impl: str = "auto", telemetry=None):
    """One-dispatch speculative draft-and-verify step for a ``bucket``-row
    slot batch and draft width ``K``.

    ``verify(params, tokens [B, K+1], k_pages, v_pages, page_table [B, MAXP],
    lengths [B], draft_lens [B]) -> (out [B, K+2], k_pages, v_pages)``.
    Row b's ``tokens`` are its pending token followed by up to K host-drafted
    tokens (garbage past ``draft_lens[b]``). The program scatters k/v for
    every position ``lengths[b] + j`` (pad slots redirect to the trash page),
    scores all K+1 positions in ONE causal chunk-prefill attention pass over
    the row's pages, and resolves the speculation in-program:
    ``out[:, 0]`` is the accepted-prefix length ``n`` — the count of leading
    drafts that equal the model's own greedy argmax, bounded by
    ``draft_lens`` — and ``out[:, 1:]`` the greedy token after each prefix,
    so the round emits ``out[b, 1 : n+2]`` (n accepted drafts + the
    bonus/correction token), byte-identical to n+1 sequential decode steps.
    The host rolls the rejected tail's pages back via ``PagePool.rollback``.

    Pages are donated; the packed [B, K+2] fetch is the round's only host
    traffic. Compiled once per (bucket, K); the scheduler bounds total
    verify programs by ``len(slot_buckets) × len(spec_lens)``.

    Exactness caveat: verify scores through the XLA chunk attention, so
    byte-identical spec-on/spec-off streams are guaranteed when the plain
    decode steps use the same backend (``attn_impl="xla"``, the tested
    config). Under ``"auto"`` on TPU the plain steps run the Pallas decode
    kernel — mathematically the same scores, but an argmax near-tie could
    in principle resolve differently across the two lowerings.
    """
    if cfg.position == "alibi":
        raise NotImplementedError("paged serving does not support alibi attention biases")
    if K < 1:
        raise ValueError(f"speculative verify needs K >= 1 drafted slots, got {K}")
    name = _program_name("verify", bucket, K + 1)
    key = _paged_program_key(name, cfg, page_size, attn_impl, telemetry)
    fn = _paged_program_cache.get(key)
    if fn is not None:
        return fn

    def _verify(params, tokens, k_pages, v_pages, page_table, lengths, draft_lens):
        T = K + 1
        offs = jnp.arange(T, dtype=jnp.int32)
        positions_b = lengths[:, None] + offs[None, :]
        # pad slots (j > draft_lens[b]) hold garbage tokens whose positions
        # may reach past the row's ensured pages — their writes go to the
        # trash page and their kv rows are masked out of the attention
        valid = offs[None, :] <= draft_lens[:, None]
        kv_lens = jnp.where(lengths > 0, lengths + draft_lens + 1, 0)
        logits, new_k, new_v = _paged_forward(
            cfg, params, tokens, k_pages, v_pages, page_table, positions_b,
            None, attn_impl, write_valid=valid, prefill_kv_lens=kv_lens,
        )
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
        accepted = _accepted_prefix(tokens, greedy, draft_lens)
        packed = jnp.concatenate([accepted[:, None].astype(jnp.int32), greedy], axis=1)
        return packed, new_k, new_v

    fn = _jit(_verify, telemetry, name, donate_argnums=(2, 3))
    _paged_program_cache[key] = fn
    return fn


def build_ragged_multistep(cfg, rows: int, width: int, horizon: int, page_size: int,
                           attn_impl: str = "auto", telemetry=None, tp=None):
    """N plain-decode rounds in ONE dispatch: a ``lax.scan`` of ``horizon``
    iterations of the ragged step body, so the host dispatch gap, packing,
    and journal syncs are paid once per WINDOW instead of once per token.

    ``multistep(params, tokens [R], k_pages, v_pages, page_table [R, MAXP],
    lengths [R], live [R], eos_ids [R], budgets [R])
    -> (out [R, 1+N], k_pages, v_pages)``.

    Row r starts from its pending token ``tokens[r]`` at live kv length
    ``lengths[r]`` (``live[r] == 0`` marks dead padding rows). Each round
    writes the carried token at the row's next position, attends through
    the SAME ragged paged-attention entry the single-step program uses
    (per-row ``(kv_len, q_len)`` metadata with ``q_len ∈ {0, 1}``), takes
    the greedy argmax in-program, and advances the carry. Stopping is pure
    in-program data: a row FREEZES — its ``q_len`` drops to 0, so further
    writes redirect to the trash page and its length stops — the round it
    emits its ``eos_ids[r]`` token (−1 = no EOS) or its ``budgets[r]``-th
    window token. A frozen row is indistinguishable from a dead padding
    row to every other row, which is what makes the window byte-identical
    to ``horizon`` sequential single-step dispatches.

    In-window KV growth needs no host resync: positions index the page
    table (``position // page_size``), and the scheduler pre-reserves the
    ``ceil(N / page_size) + 1`` pages a row can touch before dispatching
    (``_reserve_for_growth``), so the table rides in already covering the
    whole window.

    ``out[:, 0]`` is the per-row emitted count n (≤ N); ``out[:, 1 : 1+n]``
    the emitted tokens — everything packed into ONE array so the window's
    single host fetch stays a single transfer. Pages are donated; the
    table rides in per window (rebuilt host-side, nothing to alias back).

    Compiled once per (rows, horizon): the scheduler arms one horizon, so
    the serving program set stays ≤ narrow + mixed + one window program.
    ``width`` is reserved for drafted windows and must be 1 today (plain
    decode — the window mode only engages when drafting is idle).
    """
    if cfg.position == "alibi":
        raise NotImplementedError("paged serving does not support alibi attention biases")
    if width != 1:
        raise ValueError(f"multi-step windows run plain decode only (width 1), got {width}")
    if rows < 1 or horizon < 2:
        raise ValueError(
            f"multi-step window needs rows >= 1 and horizon >= 2, got "
            f"{rows} rows x horizon {horizon}"
        )
    name = f"{_program_name('multistep', rows, width)}_n{int(horizon)}" + _tp_suffix(tp)
    key = _paged_program_key(name, cfg, page_size, attn_impl, telemetry, tp)
    fn = _paged_program_cache.get(key)
    if fn is not None:
        return fn
    N = int(horizon)
    run_cfg = cfg if tp is None else tp.local_cfg(cfg)

    def _window(params, tokens, k_pages, v_pages, page_table, lengths, live,
                eos_ids, budgets):
        def round_fn(carry, _):
            tok, kp, vp, lens, alive, emitted = carry
            q_lens = alive.astype(jnp.int32)  # [R]: 1 live, 0 frozen/dead
            kv_lens = jnp.where(alive, lens + 1, 0)
            logits, kp, vp = _paged_forward(
                run_cfg, params, tok[:, None], kp, vp, page_table, lens[:, None],
                None, attn_impl, write_valid=alive[:, None],
                prefill_kv_lens=kv_lens, ragged_q_lens=q_lens, tp=tp,
            )
            nxt = (
                tp.argmax(logits[:, -1, :]) if tp is not None
                else jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            )
            out_tok = jnp.where(alive, nxt, -1)
            emitted = emitted + q_lens
            lens = lens + q_lens
            # freeze AFTER emitting the EOS / budget-hitting token — the
            # scheduler's _emit includes that token, matching sequential
            # decode's output contract
            alive = alive & (nxt != eos_ids) & (emitted < budgets)
            tok = jnp.where(alive, nxt, tok)
            return (tok, kp, vp, lens, alive, emitted), out_tok

        alive0 = live > 0
        emitted0 = jnp.zeros_like(lengths)
        (tok, kp, vp, lens, alive, emitted), toks = jax.lax.scan(
            round_fn, (tokens, k_pages, v_pages, lengths, alive0, emitted0),
            None, length=N,
        )
        packed = jnp.concatenate([emitted[:, None], toks.T], axis=1)  # [R, 1+N]
        return packed, kp, vp

    body = _window if tp is None else tp.shard_program(_window, n_args=9)
    fn = _jit(body, telemetry, name, donate_argnums=(2, 3))
    _paged_program_cache[key] = fn
    return fn


def build_ragged_step(cfg, rows: int, width: int, page_size: int,
                      attn_impl: str = "auto", telemetry=None, tp=None):
    """THE one serving program: a ``rows × width`` ragged step that handles
    mixed prefill-chunk, decode, and verify rows in a single dispatch.

    ``ragged_step(params, tokens [R, W], k_pages, v_pages,
    page_table [R, MAXP], lengths [R], q_lens [R])
    -> (out [R, W+1], k_pages, v_pages)``.

    Row r carries ``q_lens[r]`` real tokens written at absolute positions
    ``lengths[r] + j`` (``lengths`` = the row's live kv length BEFORE the
    step — prefill progress and decode length coincide there). The mode is
    pure data, never shape:

    * a **prefill chunk** row is the next ``q_lens[r]`` prompt tokens;
    * a **decode** row is the single pending token (``q_lens[r] == 1``);
    * a **verify** row is the pending token plus ``q_lens[r] - 1`` drafts;
    * a **dead** padding row has ``q_lens[r] == 0`` (sentinel table,
      trash-page writes, zero attention).

    The program scatters k/v for every real position (window slots past
    ``q_lens[r]`` redirect to the trash page), attends through ONE ragged
    paged-attention call driven by the per-row ``(kv_len, q_len)``
    metadata, and resolves every mode in-program: ``out[r, 1 + j]`` is the
    greedy token after position j (decode rows read ``out[r, 1]``, a
    finishing prefill chunk reads ``out[r, q_lens[r]]``), and ``out[r, 0]``
    is the verify rows' accepted-prefix length (count of leading drafts
    matching the model's own greedy argmax — byte-identical to sequential
    decode; 0 wherever nothing was drafted). Pages are donated; the packed
    [R, W+1] fetch is the step's only host traffic.

    Because slot count, chunk progress, spec-K, and the mode mix all ride
    in as array contents, shifting traffic NEVER retraces: the scheduler
    compiles at most two widths of this program (decode/verify width and
    the mixed width covering prefill chunks) for an entire serve.

    With ``tp`` (a ``inference/tp.py:TPServing``) the SAME body runs under
    ``shard_map`` on the tensor-parallel mesh: weights and kv pages ride
    in sharded (column/row-parallel projections, kv-head-sliced pools),
    the per-layer row-parallel all-reduces are explicit (chunked for
    overlap, optionally EQuARX-quantized), and the greedy/accepted-prefix
    resolution uses the global argmax — so the packed host fetch, the
    one-dispatch-per-step contract, the page donation, and the ≤2-program
    budget are all unchanged on the mesh.
    """
    if cfg.position == "alibi":
        raise NotImplementedError("paged serving does not support alibi attention biases")
    if rows < 1 or width < 1:
        raise ValueError(f"ragged step needs rows >= 1 and width >= 1, got {rows}x{width}")
    name = _program_name("ragged", rows, width) + _tp_suffix(tp)
    key = _paged_program_key(name, cfg, page_size, attn_impl, telemetry, tp)
    fn = _paged_program_cache.get(key)
    if fn is not None:
        return fn
    W = int(width)
    run_cfg = cfg if tp is None else tp.local_cfg(cfg)

    def _step(params, tokens, k_pages, v_pages, page_table, lengths, q_lens):
        offs = jnp.arange(W, dtype=jnp.int32)
        positions_b = lengths[:, None] + offs[None, :]
        valid = offs[None, :] < q_lens[:, None]
        kv_lens = jnp.where(q_lens > 0, lengths + q_lens, 0)
        logits, new_k, new_v = _paged_forward(
            run_cfg, params, tokens, k_pages, v_pages, page_table, positions_b,
            None, attn_impl, write_valid=valid, prefill_kv_lens=kv_lens,
            ragged_q_lens=q_lens, tp=tp,
        )
        greedy = (
            tp.argmax(logits) if tp is not None
            else jnp.argmax(logits, axis=-1).astype(jnp.int32)
        )  # [R, W]
        # verify resolution (inert elsewhere: decode rows have no drafts and
        # prefill rows' accepted count is ignored by the host)
        accepted = _accepted_prefix(tokens, greedy, q_lens - 1)
        packed = jnp.concatenate([accepted[:, None].astype(jnp.int32), greedy], axis=1)
        return packed, new_k, new_v

    body = _step if tp is None else tp.shard_program(_step, n_args=7)
    fn = _jit(body, telemetry, name, donate_argnums=(2, 3))
    _paged_program_cache[key] = fn
    return fn
