"""Incremental decoding with a KV cache.

TPU-native counterpart of the reference's fused decoder inference kernels
(``csrc/transformer/inference/csrc/pt_binding.cpp``: ``softmax_context`` =
KV-cache attention, ``qkv_gemm``/``mlp_gemm`` fused projections,
``apply_rotary_pos_emb``, workspace = the preallocated KV cache,
``allocate_workspace`` :1929): one jitted ``prefill`` program consumes the
prompt and fills the cache; one jitted ``decode_step`` program appends a
single token — in-place cache updates via ``dynamic_update_slice`` with
buffer donation, so decoding runs at HBM-bandwidth with no reallocation and
exactly two compiled programs per (batch, max_len) bucket.

Works on the flagship ``TransformerLM`` parameter layout (stacked [L, ...]
layer params, ``models/transformer.py``); numerics are kept in lockstep with
the training forward — guarded by the decode-vs-full-forward parity test
(``tests/unit/inference/test_decode.py``).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.models.transformer import _norm, _rope


class KVCache(NamedTuple):
    """Preallocated decode workspace (reference allocate_workspace)."""

    k: jax.Array  # [L, B, max_len, NKV, D]
    v: jax.Array  # [L, B, max_len, NKV, D]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(cfg: TransformerConfig, batch: int, max_len: int, dtype=None) -> KVCache:
    if dtype is None:
        dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}[
            cfg.dtype
        ]
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def _layer_project_qkv(cfg: TransformerConfig, p, h):
    """Norm + qkv projection for a [B, T, H] slab (same ops as
    models/transformer.py _layer)."""
    B, T, _ = h.shape
    NH, NKV, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    hn = _norm(h, p["attn_norm_scale"], p.get("attn_norm_bias"), cfg.norm, cfg.norm_eps)
    q = hn @ p["wq"].astype(hn.dtype)
    k = hn @ p["wk"].astype(hn.dtype)
    v = hn @ p["wv"].astype(hn.dtype)
    if cfg.qkv_bias:
        q = q + p["bq"].astype(hn.dtype)
        k = k + p["bk"].astype(hn.dtype)
        v = v + p["bv"].astype(hn.dtype)
    return (
        q.reshape(B, T, NH, D),
        k.reshape(B, T, NKV, D),
        v.reshape(B, T, NKV, D),
    )


def _layer_mlp(cfg: TransformerConfig, p, x):
    from deepspeed_tpu.moe.experts import apply_dense_ffn

    h = _norm(x, p["mlp_norm_scale"], p.get("mlp_norm_bias"), cfg.norm, cfg.norm_eps)
    return x + apply_dense_ffn(p, h, cfg.activation)


def _cached_attention(cfg, q, k_cache, v_cache, q_positions, kv_len_mask, kv_len=None):
    """q [B,T,NH,D] against the full cache [B,S,NKV,D]; positions beyond the
    valid length are masked (the reference softmax_context semantics)."""
    NH, NKV = q.shape[2], k_cache.shape[2]
    scale = (
        cfg.attn_softmax_scale
        if getattr(cfg, "attn_softmax_scale", None) is not None
        else 1.0 / np.sqrt(q.shape[-1])
    )
    if (
        q.shape[1] == 1
        and kv_len is not None
        and cfg.position != "alibi"
        and k_cache.shape[1] % 256 == 0
    ):
        # single-token decode: the fused ragged kernel reads only live cache
        # blocks (and GQA kv rows once, without the repeat below)
        from deepspeed_tpu.ops.transformer.decode_attention import decode_attention

        out = decode_attention(q[:, 0], k_cache, v_cache, kv_len, scale=scale)
        return out[:, None]
    if NKV != NH:
        k_cache = jnp.repeat(k_cache, NH // NKV, axis=2)
        v_cache = jnp.repeat(v_cache, NH // NKV, axis=2)
    scores = jnp.einsum("btnd,bsnd->bnts", q, k_cache).astype(jnp.float32) * scale
    S = k_cache.shape[1]
    kv_pos = jnp.arange(S, dtype=jnp.int32)
    causal = q_positions[:, None, :, None] >= kv_pos[None, None, None, :]
    valid = kv_len_mask[None, None, None, :] if kv_len_mask is not None else True
    scores = jnp.where(causal & valid, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    return jnp.einsum("bnts,bsnd->btnd", probs, v_cache)


def _forward_with_cache(cfg, params, tokens, cache: KVCache, start_pos):
    """Run [B, T] tokens starting at ``start_pos``, reading+writing the
    cache. Returns (logits_of_last_token, new_cache)."""
    B, T = tokens.shape
    dtype = cache.k.dtype
    x = params["embed"]["tokens"].astype(dtype)[tokens]
    positions = start_pos + jnp.arange(T, dtype=jnp.int32)
    positions_b = jnp.broadcast_to(positions[None, :], (B, T))
    if cfg.position == "learned":
        x = x + params["embed"]["pos"].astype(dtype)[positions][None]

    S = cache.max_len
    kv_pos = jnp.arange(S, dtype=jnp.int32)
    kv_len_mask = kv_pos < (start_pos + T)

    def layer_step(carry, per_layer):
        x = carry
        p, k_cache_l, v_cache_l = per_layer
        q, k_new, v_new = _layer_project_qkv(cfg, p, x)
        if cfg.position == "rope":
            q = _rope(q, positions_b, cfg.rope_theta)
            k_new = _rope(k_new, positions_b, cfg.rope_theta)
        k_cache_l = jax.lax.dynamic_update_slice(
            k_cache_l, k_new.astype(k_cache_l.dtype), (0, start_pos, 0, 0)
        )
        v_cache_l = jax.lax.dynamic_update_slice(
            v_cache_l, v_new.astype(v_cache_l.dtype), (0, start_pos, 0, 0)
        )
        attn = _cached_attention(
            cfg, q, k_cache_l, v_cache_l, positions_b, kv_len_mask, kv_len=start_pos + T
        )
        attn = attn.reshape(B, T, cfg.num_heads * cfg.head_dim) @ p["wo"].astype(x.dtype)
        if cfg.use_bias:
            attn = attn + p["bo"].astype(x.dtype)
        x = x + attn
        x = _layer_mlp(cfg, p, x)
        return x, (k_cache_l, v_cache_l)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache.k, cache.v)
    )

    x = _norm(
        x, params["final_norm_scale"], params.get("final_norm_bias"), cfg.norm, cfg.norm_eps
    )
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["tokens"].astype(x.dtype).T
    else:
        logits = x @ params["lm_head"].astype(x.dtype)
    return logits[:, -1, :], KVCache(k=new_k, v=new_v)


_decoder_cache: Dict[int, Tuple] = {}


def build_decoder(cfg: TransformerConfig) -> Tuple[Any, Any]:
    """(prefill, decode_step) jitted pair for a model config.

    ``prefill(params, tokens, cache)`` consumes the prompt [B, T];
    ``decode_step(params, token, cache, pos)`` appends one token [B].
    Both donate the cache buffer (in-place workspace update).
    """
    key = id(cfg)
    if key in _decoder_cache:
        return _decoder_cache[key]

    prefill = jax.jit(
        lambda params, tokens, cache: _forward_with_cache(
            cfg, params, tokens, cache, jnp.int32(0)
        ),
        donate_argnums=(2,),
    )
    decode_step = jax.jit(
        lambda params, token, cache, pos: _forward_with_cache(
            cfg, params, token[:, None], cache, pos
        ),
        donate_argnums=(2,),
    )
    _decoder_cache[key] = (prefill, decode_step)
    return prefill, decode_step


def generate(
    cfg: TransformerConfig,
    params,
    input_ids,
    max_new_tokens: int,
    eos_token_id=None,
    temperature: float = 0.0,
    rng=None,
):
    """KV-cached greedy/sampled generation: one prefill + N decode steps
    (each a cached compiled program)."""
    tokens = jnp.asarray(input_ids)
    if tokens.ndim == 1:
        tokens = tokens[None, :]
    B, prompt_len = tokens.shape
    max_len = prompt_len + max_new_tokens
    cache = init_cache(cfg, B, max_len)
    prefill, decode_step = build_decoder(cfg)

    logits, cache = prefill(params, tokens, cache)
    out = [tokens]
    pos = prompt_len
    finished = np.zeros(B, bool)
    for _ in range(max_new_tokens):
        if temperature > 0.0 and rng is not None:
            rng, sub = jax.random.split(rng)
            next_tok = jax.random.categorical(sub, logits / temperature, axis=-1)
        else:
            next_tok = jnp.argmax(logits, axis=-1)
        next_tok = next_tok.astype(tokens.dtype)
        if eos_token_id is not None:
            # rows that already emitted EOS keep emitting EOS (padding), not
            # arbitrary continuation tokens
            next_tok = jnp.where(jnp.asarray(finished), jnp.asarray(eos_token_id, tokens.dtype), next_tok)
            out.append(next_tok[:, None])
            finished |= np.asarray(jax.device_get(next_tok)) == eos_token_id
            if finished.all():
                break
        else:
            out.append(next_tok[:, None])
        logits, cache = decode_step(params, next_tok, cache, jnp.int32(pos))
        pos += 1
    return jnp.concatenate(out, axis=1)
