"""Jittable token sampling: greedy, temperature, top-k, top-p.

The reference defers sampling to HF ``generate`` kwargs
(``deepspeed/inference/engine.py:578`` dispatches to the wrapped module's
generate, which applies HF's LogitsProcessor stack). Here the filters are
pure jnp transforms fused INTO the compiled decode loop — sampling adds no
host round-trip and no extra kernel launch.

All of ``temperature`` / ``top_k`` / ``top_p`` are static Python values
(compile-time constants): each distinct sampling configuration is its own
compiled program, matching how serving stacks bucket by sampling params.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _nucleus_cutoff(sorted_desc: jnp.ndarray, p: float) -> jnp.ndarray:
    """Smallest logit inside the nucleus of a descending-sorted row."""
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # shifting the comparison by one slot keeps the boundary token
    keep = (cum - probs) < p
    keep = keep.at[..., 0].set(True)  # the top token always survives
    kept_logits = jnp.where(keep, sorted_desc, jnp.inf)
    return jnp.min(kept_logits, axis=-1, keepdims=True)


def apply_filters(logits: jnp.ndarray, top_k: int = 0, top_p: float = 1.0) -> jnp.ndarray:
    """Mask logits outside the top-k / nucleus (HF order: top-k first, then
    top-p over the k-filtered distribution). The single shared
    implementation — one O(V log V) sort serves both filters, which matters
    because this runs inside the per-token decode loop."""
    if top_k <= 0 and top_p >= 1.0:
        return logits
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    if top_k > 0:
        k = min(top_k, logits.shape[-1])
        cutoff = sorted_desc[..., k - 1][..., None]
        sorted_desc = jnp.where(sorted_desc < cutoff, NEG_INF, sorted_desc)
    if top_p < 1.0:
        # the nucleus cutoff is >= the kth value, so it subsumes top-k's
        cutoff = _nucleus_cutoff(sorted_desc, top_p)
    return jnp.where(logits < cutoff, NEG_INF, logits)


def top_k_filter(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask everything below the k-th largest logit (per row)."""
    return apply_filters(logits, top_k=k)


def top_p_filter(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filter: keep the smallest prefix of the sorted distribution
    whose cumulative probability reaches ``p`` (the top token always
    survives, even when ``p`` is 0 or its probability alone exceeds it)."""
    return apply_filters(logits, top_p=p)


def sample_logits(
    logits: jnp.ndarray,  # [B, V]
    rng: Optional[jax.Array],
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Next-token ids [B]. ``temperature == 0`` (or no rng) = greedy;
    otherwise filter via ``apply_filters`` and draw categorically."""
    if temperature <= 0.0 or rng is None:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(
        rng, apply_filters(logits / temperature, top_k, top_p), axis=-1
    )
