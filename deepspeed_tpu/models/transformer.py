"""Decoder-only transformer (the built-in model family).

TPU-native replacement for the reference's fused transformer layers
(``csrc/transformer/ds_transformer_cuda.cpp``,
``deepspeed/ops/transformer/transformer.py:296`` DeepSpeedTransformerLayer)
and the per-arch injected models (``deepspeed/model_implementations/``):
one configurable decoder covering GPT-2/Llama/OPT/NeoX-style architectures.

Engineering choices for the MXU/HBM:

* params for all layers are **stacked** ([L, ...] leading dim) and the block
  runs under ``lax.scan`` — O(1) compile time in depth, and XLA pipelines the
  per-layer collectives.
* ``jax.checkpoint`` (remat) wraps the scanned body with a configurable
  policy — the activation-checkpointing subsystem of the reference
  (``deepspeed/runtime/activation_checkpointing``).
* attention is einsum-based (MXU-shaped); the Pallas flash-attention kernel
  swaps in via ``config.flash_attention`` when available.
* weights carry Megatron-style TP specs over the ``model`` axis
  (``tp_partition_rules``), composed with ZeRO sharding by the partitioner.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.runtime.module import DSModule

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


def _flash_attention_available() -> bool:
    try:
        from deepspeed_tpu.ops.transformer.flash_attention import flash_attention  # noqa: F401

        return True
    except ImportError:
        return False


def _maybe_quantize_activation(x, site: str):
    """QAT activation hook (compression/act_quant.py contract): identity
    unless the enclosing forward was entered through a ``CompressedModule``
    with an active ``activation_quantization`` group. Lazy import keeps the
    model family free of the compression package on the hot path."""
    from deepspeed_tpu.compression.act_quant import is_active, maybe_quantize

    if not is_active():
        return x
    return maybe_quantize(x, site)


def _norm(x, scale, bias, kind: str, eps: float):
    x32 = x.astype(jnp.float32)
    if kind == "rmsnorm":
        rms = jnp.sqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + eps)
        out = x32 / rms * scale.astype(jnp.float32)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        out = (x32 - mean) / jnp.sqrt(var + eps) * scale.astype(jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def _rope(x, positions, theta: float, rope_dim=None):
    """Rotary embedding over the last dim of [B, T, N, D]. ``rope_dim``
    rotates only the leading features (GPT-J rotary_dim / NeoX rotary_pct);
    the tail passes through unrotated."""
    if rope_dim is not None and rope_dim < x.shape[-1]:
        rotated = _rope(x[..., :rope_dim], positions, theta)
        return jnp.concatenate([rotated, x[..., rope_dim:]], axis=-1)
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _alibi_slopes(n_heads: int) -> np.ndarray:
    def pow2slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    if np.log2(n_heads).is_integer():
        return pow2slopes(n_heads)
    closest = 2 ** int(np.floor(np.log2(n_heads)))
    return np.concatenate([pow2slopes(closest), pow2slopes(2 * closest)[0::2][: n_heads - closest]])


def _vocab_sharded() -> bool:
    """True when the active topology tensor-shards the vocab dim (TP)."""
    try:
        from deepspeed_tpu.parallel.mesh import get_topology

        return get_topology().axis_size("model") > 1
    except Exception:
        return False


def cross_entropy_loss(logits, labels, ignore_index: int = -100):
    """Mean token CE in fp32, ignoring ``ignore_index`` positions.

    Two gold-logit strategies, picked at trace time:

    * TP (vocab-sharded logits): one-hot select — ``take_along_axis``'s
      transpose is a scatter-add whose sharding the SPMD partitioner cannot
      reconcile with vocab-sharded logits (involuntary full
      rematerialization); the select's transpose is a plain masked multiply.
    * otherwise: ``take_along_axis`` — the select costs a full extra
      HBM pass over the [tokens, vocab] logits (the widest tensor in the
      step) where the gather reads one element per token. Measured ~2% of
      the 125M-config step time on v5e.

    The fp32 cast happens inside each consumer (not once up front) so XLA
    fuses it into the logsumexp reduction instead of materializing an fp32
    copy of the logits."""
    mask = labels != ignore_index
    safe_labels = jnp.where(mask, labels, 0)
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    if _vocab_sharded():
        vocab_iota = jnp.arange(logits.shape[-1], dtype=safe_labels.dtype)
        onehot = safe_labels[..., None] == vocab_iota
        gold = jnp.sum(jnp.where(onehot, logits.astype(jnp.float32), 0.0), axis=-1)
    else:
        gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[
            ..., 0
        ].astype(jnp.float32)
    nll = (logz - gold) * mask
    return nll.sum() / jnp.maximum(mask.sum(), 1)


class TransformerLM(DSModule):
    """Causal LM. Batch forms accepted by ``apply``:

    * ``tokens`` [B, T] — returns logits (inference path)
    * ``(tokens, labels)`` or ``{"input_ids":..., "labels":...}`` — returns
      the scalar LM loss (training path)
    """

    def __init__(self, config: TransformerConfig):
        self.config = config
        self.dtype = _DTYPES[config.dtype]

    # --- parameter construction ----------------------------------------
    def init(self, rng, batch) -> Dict[str, Any]:
        cfg = self.config
        H, L = cfg.hidden_size, cfg.num_layers
        NH, NKV, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        I = cfg.intermediate_size
        keys = jax.random.split(rng, 16)
        k = iter(keys)
        std = 0.02

        def dense(key, shape, out_std=std):
            return (jax.random.normal(key, shape, dtype=jnp.float32) * out_std)

        def stacked(key, shape, out_std=std):
            return dense(key, (L,) + shape, out_std)

        params: Dict[str, Any] = {
            "embed": {"tokens": dense(next(k), (cfg.vocab_size, H))},
        }
        if cfg.position == "learned":
            params["embed"]["pos"] = dense(next(k), (cfg.max_seq_len, H))
        if cfg.embed_norm:
            params["embed"]["norm_scale"] = jnp.ones((H,))
            if cfg.norm == "layernorm":
                params["embed"]["norm_bias"] = jnp.zeros((H,))

        layer: Dict[str, Any] = {
            "attn_norm_scale": jnp.ones((L, H)),
            "wq": stacked(next(k), (H, NH * D)),
            "wk": stacked(next(k), (H, NKV * D)),
            "wv": stacked(next(k), (H, NKV * D)),
            "wo": stacked(next(k), (NH * D, H), out_std=std / np.sqrt(2 * L)),
            "mlp_norm_scale": jnp.ones((L, H)),
            "w_out": stacked(next(k), (I, H), out_std=std / np.sqrt(2 * L)),
        }
        if cfg.activation in ("swiglu", "geglu"):
            layer["w_gate"] = stacked(next(k), (H, I))
            layer["w_up"] = stacked(next(k), (H, I))
        else:
            layer["w_in"] = stacked(next(k), (H, I))
        if cfg.norm == "layernorm":
            layer["attn_norm_bias"] = jnp.zeros((L, H))
            layer["mlp_norm_bias"] = jnp.zeros((L, H))
        if cfg.qkv_bias:
            layer["bq"] = jnp.zeros((L, NH * D))
            layer["bk"] = jnp.zeros((L, NKV * D))
            layer["bv"] = jnp.zeros((L, NKV * D))
        if cfg.use_bias:
            layer["bo"] = jnp.zeros((L, H))
            layer["b_out"] = jnp.zeros((L, H))
            if cfg.activation not in ("swiglu", "geglu"):
                layer["b_in"] = jnp.zeros((L, I))
        params["layers"] = layer

        if cfg.prenorm:  # post-LN nets end inside the last layer's norm
            params["final_norm_scale"] = jnp.ones((H,))
            if cfg.norm == "layernorm":
                params["final_norm_bias"] = jnp.zeros((H,))
        if not cfg.tie_embeddings:
            params["lm_head"] = dense(next(k), (H, cfg.vocab_size))
            if cfg.lm_head_bias:
                params["lm_head_bias"] = jnp.zeros((cfg.vocab_size,))
        return params

    # --- TP sharding rules ----------------------------------------------
    def tp_partition_rules(self, params_shapes=None) -> Any:
        """Megatron-style specs over the 'model' mesh axis: column-parallel
        qkv/gate/up (shard the output features = heads), row-parallel
        wo/w_out (shard the input features); vocab-parallel embeddings.
        The stacked layer dim [L] stays unsharded (it is scanned).
        (reference analog: deepspeed/module_inject/auto_tp.py policy walk)

        NOTE: the paged SERVING engine uses its own specialisation of this
        map (``inference/tp.py:TPServing.partition_specs``): same
        column/row split for the projections, but embeddings REPLICATE
        (the lookup gather stays chip-local under shard_map) and the
        untied LM head is vocab-COLUMN-parallel with an in-program global
        argmax instead of the input-vocab-sharded table here — serving
        resolves greedy tokens, never a cross-entropy."""
        if params_shapes is None:
            return None

        def spec_for(path: str, ndim: int) -> P:
            stacked = ndim == 3  # [L, in, out]
            col = {"wq", "wk", "wv", "w_gate", "w_up", "w_in"}
            row = {"wo", "w_out"}
            name = path.split("/")[-1]
            if name in col:
                return P(None, None, "model") if stacked else P(None, "model")
            if name in row:
                return P(None, "model", None) if stacked else P("model", None)
            if name in {"bq", "bk", "bv", "b_in"}:
                return P(None, "model") if ndim == 2 else P("model")
            if name == "tokens":
                return P("model", None)  # vocab-parallel embedding
            if name == "lm_head":
                return P(None, "model")
            return P(*([None] * ndim))

        def walk(prefix, tree):
            if isinstance(tree, dict):
                return {k: walk(f"{prefix}/{k}", v) for k, v in tree.items()}
            return spec_for(prefix, len(tree.shape))

        return walk("", params_shapes)

    # --- forward ---------------------------------------------------------
    def _attention(self, q, k, v, positions, dropout_rng, train):
        """[B, T, NH, D] q / [B, T, NKV, D] k,v → [B, T, NH, D].

        Dispatches to sequence-parallel paths BEFORE expanding GQA kv heads
        so ring's ppermute and (when divisible) Ulysses' all-to-all move only
        the NKV-head kv bytes.
        """
        cfg = self.config
        scale = (
            cfg.attn_softmax_scale
            if cfg.attn_softmax_scale is not None
            else 1.0 / np.sqrt(q.shape[-1])
        )
        if cfg.sequence_parallel:
            sp_out = self._sp_attention(q, k, v, positions, dropout_rng, train, scale)
            if sp_out is not None:
                return sp_out
        return self._local_full_attention(q, k, v, positions, scale, dropout_rng, train)

    def _local_full_attention(self, q, k, v, positions, scale, dropout_rng=None, train=False):
        """Full-sequence attention on (possibly head-sharded) q/k/v: the
        single implementation used by the local path and as the Ulysses
        local op. GQA (NKV < NH) is computed by grouping the queries against
        the shared kv rows — an NH-wide ``jnp.repeat`` of k/v here would
        materialize a G-times copy of the [B, S, NKV, D] activations every
        layer (the same blowup the paged decode path banned in PR 2); only
        the fused flash kernel, which requires equal head counts, still
        expands."""
        cfg = self.config
        NH, NKV = q.shape[2], k.shape[2]
        if (
            cfg.flash_attention
            and _flash_attention_available()
            and cfg.position != "alibi"
            and cfg.causal
            and (not train or cfg.attn_dropout == 0)  # no dropout inside the fused kernel
        ):
            from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

            if NKV != NH:
                k, v = _expand_gqa(q, k, v)  # kernel contract: equal head counts
            return flash_attention(q, k, v, causal=True, scale=scale)
        if NKV != NH:
            # grouped GQA: heads stay [NKV, G]-factored through both einsums
            B, T, _, D = q.shape
            G = NH // NKV
            qg = q.reshape(B, T, NKV, G, D)
            scores = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) * scale
            if cfg.position == "alibi":
                slopes = jnp.asarray(_alibi_slopes(NH), dtype=jnp.float32).reshape(NKV, G)
                dist = (positions[:, None, :] - positions[:, :, None]).astype(jnp.float32)
                scores = scores - slopes[None, :, :, None, None] * jnp.abs(dist)[:, None, None]
            if cfg.causal:
                mask = positions[:, None, None, :, None] >= positions[:, None, None, None, :]
                scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            if train and cfg.attn_dropout > 0 and dropout_rng is not None:
                keep = jax.random.bernoulli(dropout_rng, 1 - cfg.attn_dropout, probs.shape)
                probs = probs * keep / (1 - cfg.attn_dropout)
            probs = probs.astype(v.dtype)
            out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
            return out.reshape(B, T, NH, D)
        scores = jnp.einsum("btnd,bsnd->bnts", q, k).astype(jnp.float32) * scale
        if cfg.position == "alibi":
            slopes = jnp.asarray(_alibi_slopes(NH), dtype=jnp.float32)
            dist = (positions[:, None, :] - positions[:, :, None]).astype(jnp.float32)
            scores = scores - slopes[None, :, None, None] * jnp.abs(dist)[:, None]
        if cfg.causal:
            mask = positions[:, None, :, None] >= positions[:, None, None, :]
            scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        if train and cfg.attn_dropout > 0 and dropout_rng is not None:
            keep = jax.random.bernoulli(dropout_rng, 1 - cfg.attn_dropout, probs.shape)
            probs = probs * keep / (1 - cfg.attn_dropout)
        probs = probs.astype(v.dtype)
        return jnp.einsum("bnts,bsnd->btnd", probs, v)

    def _sp_attention(self, q, k, v, positions, dropout_rng, train, scale):
        """Sequence-parallel attention (Ulysses all-to-all or ring ppermute).

        Returns None when the mesh has no sequence axis (caller falls through
        to the local path). Reference: deepspeed/sequence/layer.py (Ulysses);
        ring is the TPU-native long-context extension (sequence/ring.py).
        Both SP paths assume contiguous 0..T-1 positions (what ``_forward``
        produces); packed/offset position ids are not supported under SP.
        """
        cfg = self.config
        if cfg.sequence_parallel_mode not in ("ulysses", "ring"):
            raise ValueError(
                f"unknown sequence_parallel_mode {cfg.sequence_parallel_mode!r}; "
                "expected 'ulysses' or 'ring'"
            )
        from deepspeed_tpu.parallel.mesh import get_topology

        topo = get_topology()
        sp = topo.axis_size("sequence")
        if sp == 1:
            return None
        if cfg.position == "alibi":
            raise NotImplementedError("sequence_parallel with alibi positions is unsupported")
        if train and cfg.attn_dropout > 0:
            raise NotImplementedError("sequence_parallel with attention dropout is unsupported")
        batch_axes = topo.dense_batch_axes()
        head_axes = "model" if topo.axis_size("model") > 1 else None

        if cfg.sequence_parallel_mode == "ring":
            from deepspeed_tpu.sequence.ring import ring_attention

            return ring_attention(
                q, k, v,
                mesh=topo.mesh,
                causal=cfg.causal,
                scale=scale,
                batch_axes=batch_axes,
                head_axes=head_axes,
            )

        from deepspeed_tpu.sequence.layer import DistributedAttention

        # Ulysses scatters the head dim over the sequence axis; kv can ride
        # the all-to-all at NKV heads iff sp divides NKV — otherwise they
        # must be pre-expanded to NH (layer.py:37's head-count constraint).
        NKV = k.shape[2]
        expand_late = NKV != q.shape[2] and NKV % sp == 0

        def local_attn(q_, k_, v_):
            # grouped-GQA local op: the group ratio survives the head
            # scatter (NH/sp vs NKV/sp), so no expansion is needed here
            return self._local_full_attention(q_, k_, v_, positions, scale)

        dist_attn = DistributedAttention(
            local_attn, topo.mesh, batch_axes=batch_axes, head_axes=head_axes
        )
        if not expand_late:
            k, v = _expand_gqa(q, k, v)  # a2a head-count constraint: sp ∤ NKV
        return dist_attn(q, k, v)

    def _mlp(self, p, h, rng, train):
        """Dense FFN; MoE model families override this (returns (out, aux_loss))."""
        from deepspeed_tpu.moe.experts import apply_dense_ffn

        h = _maybe_quantize_activation(h, "layers/mlp_input")
        return apply_dense_ffn(p, h, self.config.activation), jnp.zeros((), jnp.float32)

    def _layer_params(self, params, i: int):
        """Per-layer param tree for the unrolled (non-scan) path; model
        families with heterogeneous layers (MoE interleave) override this."""
        return jax.tree_util.tree_map(lambda a: a[i], params["layers"])

    def _layer(self, carry_x, layer_params, positions, rng, train):
        cfg = self.config
        p = layer_params
        x = carry_x
        B, T, H = x.shape
        NH, NKV, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

        # pre-LN (GPT/Llama): norm feeds the block, residual stays unnormed.
        # post-LN (BERT family): the block reads the residual stream raw and
        # the norm is applied AFTER adding the residual.
        if cfg.prenorm:
            h = _norm(x, p["attn_norm_scale"], p.get("attn_norm_bias"), cfg.norm, cfg.norm_eps)
        else:
            h = x
        h = _maybe_quantize_activation(h, "layers/attn_input")
        q = h @ p["wq"].astype(h.dtype)
        k = h @ p["wk"].astype(h.dtype)
        v = h @ p["wv"].astype(h.dtype)
        if cfg.qkv_bias:
            q, k, v = q + p["bq"].astype(h.dtype), k + p["bk"].astype(h.dtype), v + p["bv"].astype(h.dtype)
        q = q.reshape(B, T, NH, D)
        k = k.reshape(B, T, NKV, D)
        v = v.reshape(B, T, NKV, D)
        if cfg.position == "rope":
            q = _rope(q, positions, cfg.rope_theta, cfg.rope_dim)
            k = _rope(k, positions, cfg.rope_theta, cfg.rope_dim)
        rng, r_attn, r_hid, r_mlp = jax.random.split(rng, 4) if rng is not None else (None, None, None, None)
        attn = self._attention(q, k, v, positions, r_attn, train)
        attn = attn.reshape(B, T, NH * D) @ p["wo"].astype(h.dtype)
        if cfg.use_bias:
            attn = attn + p["bo"].astype(h.dtype)
        if train and cfg.hidden_dropout > 0 and r_hid is not None:
            keep = jax.random.bernoulli(r_hid, 1 - cfg.hidden_dropout, attn.shape)
            attn = attn * keep / (1 - cfg.hidden_dropout)
        if cfg.parallel_residual:
            # GPT-J/NeoX: both branches read x — attn already consumed
            # norm1(x) as h; the mlp branch reads the SAME h (GPT-J shared
            # ln_1) or its own norm2(x) (NeoX)
            h_mlp = (
                h
                if cfg.shared_parallel_norm
                else _norm(x, p["mlp_norm_scale"], p.get("mlp_norm_bias"), cfg.norm, cfg.norm_eps)
            )
            out, aux = self._mlp(p, h_mlp, r_mlp, train)
            return x + attn + out, aux
        if cfg.prenorm:
            x = x + attn
            h = _norm(x, p["mlp_norm_scale"], p.get("mlp_norm_bias"), cfg.norm, cfg.norm_eps)
        else:
            x = _norm(x + attn, p["attn_norm_scale"], p.get("attn_norm_bias"), cfg.norm, cfg.norm_eps)
            h = x
        out, aux = self._mlp(p, h, r_mlp, train)
        if cfg.prenorm:
            return x + out, aux
        return _norm(x + out, p["mlp_norm_scale"], p.get("mlp_norm_bias"), cfg.norm, cfg.norm_eps), aux

    def _activation_constraint(self, x):
        """Pin [B, T, H] activations to (batch-axes, sequence, None): one
        explicit anchor stops XLA's sharding propagation from flip-flopping
        layouts at the embed→scan and scan→head boundaries ("involuntary
        full rematerialization" replicate-then-reshard). H stays replicated
        over 'model' — Megatron semantics: activations are full between
        blocks, sharded only inside them."""
        try:
            from deepspeed_tpu.parallel.mesh import get_topology

            topo = get_topology()
        except Exception:
            return x
        from jax.sharding import NamedSharding

        batch_axes = topo.dense_batch_axes()
        # pin T over 'sequence' only for SP models: a non-SP model's attention
        # needs the full sequence, and a T pin would force a replicate-reshard
        # around every attention block
        seq = (
            "sequence"
            if self.config.sequence_parallel and topo.axis_size("sequence") > 1
            else None
        )
        if batch_axes is None and seq is None:
            return x
        # standalone model.apply (no engine placed the batch): skip when the
        # shapes don't tile the mesh rather than demand engine batch sizes
        axes = batch_axes if isinstance(batch_axes, tuple) else (batch_axes,) if batch_axes else ()
        b_tile = int(np.prod([topo.axis_size(a) for a in axes])) if axes else 1
        s_tile = topo.axis_size("sequence") if seq else 1
        if x.shape[0] % b_tile or x.shape[1] % s_tile:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(topo.mesh, P(batch_axes, seq, None))
        )

    def _sparse_embed(self, params, tokens):
        """Token-embedding lookup whose backward DP-reduces compact
        (ids, rows) pairs (``runtime/sparse_tensor.py``; reference
        engine.py:2398-2465 sparse allreduce)."""
        from deepspeed_tpu.runtime.sparse_tensor import sparse_embedding_lookup

        data_axes = None
        try:
            from deepspeed_tpu.parallel.mesh import get_topology

            topo = get_topology()
            if topo.axis_size("sequence") > 1:
                raise ValueError(
                    "sparse_embedding_grads is unsupported with sequence "
                    "parallelism (the pair gather assumes batch-only sharding)"
                )
            axes = topo.dense_batch_axes()
            if axes is not None:
                data_axes = axes if isinstance(axes, tuple) else (axes,)
        except ValueError:
            raise
        except Exception:
            data_axes = None
        table = params["embed"]["tokens"].astype(self.dtype)
        return sparse_embedding_lookup(table, tokens, data_axes)

    def _forward(self, params, tokens, rngs, train, pld_theta=None, ltd_idx=None):
        cfg = self.config
        tokens = jnp.asarray(tokens)
        B, T = tokens.shape
        if cfg.sparse_embedding_grads:
            x = self._sparse_embed(params, tokens)
        else:
            x = params["embed"]["tokens"].astype(self.dtype)[tokens]
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
        if cfg.position == "learned":
            x = x + params["embed"]["pos"].astype(self.dtype)[positions[0]][None]
        if cfg.embed_norm:
            x = _norm(
                x,
                params["embed"]["norm_scale"],
                params["embed"].get("norm_bias"),
                cfg.norm,
                cfg.norm_eps,
            )
        x = self._activation_constraint(x)

        base_rng = (rngs or {}).get("dropout") if isinstance(rngs, dict) else rngs
        L = cfg.num_layers
        pld_active = pld_theta is not None and train
        ltd_active = ltd_idx is not None and train
        if pld_active and base_rng is None:
            raise ValueError(
                "progressive layer drop needs a dropout rng (the per-layer "
                "keep draw); pass rngs={'dropout': key} to apply()"
            )
        if pld_active and ltd_active:
            raise ValueError(
                "progressive_layer_drop and random-LTD cannot be combined"
            )
        if ltd_active:
            n_ltd = int(ltd_idx.shape[0])
            if n_ltd > L - 2:
                raise ValueError(
                    f"random-LTD covers {n_ltd} layers but only {L - 2} middle "
                    "layers exist (the first and last layers always run full)"
                )

        # comm-overlap plan (runtime/zero/overlap.py): set by the engine
        # around its training-loss traces. reduce_grads pins each layer's
        # cotangent to its scattered layout inside the backward scan
        # (bucketed reduce-scatter); the prefetch pipeline below restructures
        # the whole scan. Both are value-preserving, so every path stays
        # bit-identical to the unpipelined program.
        from deepspeed_tpu.runtime.zero.overlap import active_plan

        overlap_plan = active_plan()

        def body(carry, scanned):
            x, rng = carry
            per_layer, layer_idx = scanned if pld_active else (scanned, None)
            if overlap_plan is not None:
                per_layer = overlap_plan.reduce_grads(per_layer)
            if not pld_active:
                x_new, rng, aux = self._scan_layer_step(
                    x, per_layer, positions, rng, train
                )
                return (x_new, rng), aux
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None

            def run(x_in):
                y, aux = self._layer(x_in, per_layer, positions, sub, train)
                return self._activation_constraint(y), aux

            # PLD (reference runtime/progressive_layer_drop.py:40; Zhang &
            # He 2020 stochastic depth): layer i bypassed with prob
            # (i+1)/L * (1 - theta) — deeper layers dropped more; no
            # rescale, identity passthrough, all layers active at eval.
            # lax.cond skips the layer's compute at runtime.
            sub, keep_rng = jax.random.split(sub)
            keep_p = 1.0 - (layer_idx.astype(jnp.float32) + 1.0) / L * (
                1.0 - jnp.float32(pld_theta)
            )
            keep = jax.random.bernoulli(keep_rng, keep_p)
            x_new, aux = jax.lax.cond(
                keep, run, lambda x_in: (x_in, jnp.zeros((), jnp.float32)), x
            )
            return (x_new, rng), aux

        def ltd_body(carry, scanned):
            # random-LTD (reference data_routing/basic_layer.py
            # RandomLayerTokenDrop; kernels csrc/random_ltd/): this layer
            # processes ONLY its own random token subset — untouched tokens
            # ride the residual stream past it. The subset is sorted, so
            # causal attention and RoPE see true positions in order.
            from deepspeed_tpu.runtime.data_pipeline.data_routing import (
                gather_tokens,
                scatter_tokens,
            )

            x, rng = carry
            per_layer, idx = scanned  # idx [B, kept]
            if overlap_plan is not None:
                per_layer = overlap_plan.reduce_grads(per_layer)
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            x_sub = gather_tokens(x, idx)
            pos_sub = jnp.take_along_axis(positions, idx, axis=1)
            y, aux = self._layer(x_sub, per_layer, pos_sub, sub, train)
            x_new = self._activation_constraint(scatter_tokens(x, y, idx))
            return (x_new, rng), aux

        if cfg.remat:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            body = jax.checkpoint(body, policy=policy, prevent_cse=False)
            ltd_body = jax.checkpoint(ltd_body, policy=policy, prevent_cse=False)

        aux_total = jnp.zeros((), jnp.float32)
        if ltd_active:
            # layer 0 full → LTD layers 1..1+n_ltd on subsets → rest full
            def run_full(x, rng, aux_total, lo, hi):
                if hi <= lo:
                    return x, rng, aux_total
                if cfg.scan_layers:
                    sub = jax.tree_util.tree_map(lambda a: a[lo:hi], params["layers"])
                    (x, rng), aux = jax.lax.scan(body, (x, rng), sub)
                    return x, rng, aux_total + jnp.sum(aux)
                for i in range(lo, hi):
                    (x, rng), aux = body((x, rng), self._layer_params(params, i))
                    aux_total = aux_total + aux
                return x, rng, aux_total

            x, base_rng, aux_total = run_full(x, base_rng, aux_total, 0, 1)
            if cfg.scan_layers:
                mid = jax.tree_util.tree_map(
                    lambda a: a[1 : 1 + n_ltd], params["layers"]
                )
                (x, base_rng), aux = jax.lax.scan(ltd_body, (x, base_rng), (mid, ltd_idx))
                aux_total = aux_total + jnp.sum(aux)
            else:
                for j in range(n_ltd):
                    (x, base_rng), aux = ltd_body(
                        (x, base_rng), (self._layer_params(params, 1 + j), ltd_idx[j])
                    )
                    aux_total = aux_total + aux
            x, base_rng, aux_total = run_full(x, base_rng, aux_total, 1 + n_ltd, L)
        elif cfg.scan_layers and (
            overlap_plan is not None
            and overlap_plan.prefetch_enabled
            and not pld_active
        ):
            x, aux_total = self._pipelined_layer_scan(
                overlap_plan, params["layers"], x, base_rng, positions, train
            )
        elif cfg.scan_layers:
            xs = (
                (params["layers"], jnp.arange(L, dtype=jnp.int32))
                if pld_active
                else params["layers"]
            )
            (x, _), aux_per_layer = jax.lax.scan(body, (x, base_rng), xs)
            aux_total = jnp.sum(aux_per_layer)
        else:
            for i in range(L):
                per = self._layer_params(params, i)
                scanned = (per, jnp.int32(i)) if pld_active else per
                (x, base_rng), aux = body((x, base_rng), scanned)
                aux_total = aux_total + aux

        if cfg.prenorm:
            x = _norm(x, params["final_norm_scale"], params.get("final_norm_bias"), cfg.norm, cfg.norm_eps)
        if cfg.tie_embeddings:
            logits = x @ params["embed"]["tokens"].astype(self.dtype).T
        else:
            logits = x @ params["lm_head"].astype(self.dtype)
            if cfg.lm_head_bias:
                logits = logits + params["lm_head_bias"].astype(logits.dtype)
        return logits, aux_total

    def _scan_layer_step(self, x, per_layer, positions, rng, train):
        """One non-PLD scanned layer iteration: rng split, layer, activation
        constraint. Shared by the plain scan body and the pipelined scan so
        both trace the identical compute (and hence the pipeline stays
        bit-identical to the unpipelined step at every depth)."""
        if rng is not None:
            rng, sub = jax.random.split(rng)
        else:
            sub = None
        y, aux = self._layer(x, per_layer, positions, sub, train)
        return self._activation_constraint(y), rng, aux

    def _pipelined_layer_scan(self, plan, layers, x, base_rng, positions, train):
        """Software-pipelined layer scan: layer *i+depth*'s ZeRO-3 all-gather
        is issued while layer *i* computes, through a ``depth``-deep carry of
        already-gathered per-layer params (prologue gathers layers
        0..depth-1). Depth 0 is the explicit use-point gather — the same
        gather/constraint ops issued at the layer's own iteration, no
        lookahead carry — which is the "unpipelined step" the parity suite
        compares against. Depth only moves where the gather is issued: the
        gather is exact and the rng split order matches the plain scan body,
        so every depth produces bit-identical outputs — only the schedule
        changes. Tail iterations re-gather the last layer into
        never-consumed buffers (index clamp); their cotangents are zero, so
        gradients are untouched."""
        cfg = self.config
        L = cfg.num_layers
        depth = max(0, min(int(plan.depth), L))

        def pbody(carry, i):
            x, rng, bufs = carry
            if depth:
                cur = plan.use_buffered(layers, bufs[0], i)
                bufs = bufs[1:] + (
                    plan.gather_layer(layers, jnp.minimum(i + depth, L - 1)),
                )
            else:
                cur = plan.gather_layer(layers, i)
            cur = plan.reduce_grads(plan.pin_gathered(cur))
            y, rng, aux = self._scan_layer_step(x, cur, positions, rng, train)
            return (y, rng, bufs), aux

        if cfg.remat:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            pbody = jax.checkpoint(pbody, policy=policy, prevent_cse=False)

        bufs = tuple(plan.gather_layer(layers, min(j, L - 1)) for j in range(depth))
        (x, _, _), aux_per_layer = jax.lax.scan(
            pbody, (x, base_rng, bufs), jnp.arange(L, dtype=jnp.int32)
        )
        return x, jnp.sum(aux_per_layer)

    # --- layer streaming (ZeRO-Infinity param offload) -------------------
    def stream_fns(self):
        """Split the forward into (embed, layer, head) programs for the
        layer-streamed param-offload engine (``runtime/zero/param_offload.py``;
        reference analog: ``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:36``
        + the fetch/release hooks of ``zero/parameter_offload.py:342``).

        Contract: ``embed_fwd(resident, tokens) -> h``,
        ``layer_fwd(layer_params, h, positions, rng, train=True) -> h``,
        ``head_loss(resident, h, labels) -> scalar`` (``labels=None`` →
        logits, the inference head) — where ``resident`` is the param tree
        minus the stacked ``"layers"`` entry and ``layer_params`` is one
        unstacked per-layer tree. MoE aux losses are not routed through this
        path (``MoETransformerLM.stream_fns`` raises)."""
        cfg = self.config

        def embed_fwd(resident, tokens):
            tokens = jnp.asarray(tokens)
            x = resident["embed"]["tokens"].astype(self.dtype)[tokens]
            if cfg.position == "learned":
                T = tokens.shape[1]
                x = x + resident["embed"]["pos"].astype(self.dtype)[
                    jnp.arange(T, dtype=jnp.int32)
                ][None]
            if cfg.embed_norm:
                x = _norm(
                    x,
                    resident["embed"]["norm_scale"],
                    resident["embed"].get("norm_bias"),
                    cfg.norm,
                    cfg.norm_eps,
                )
            return x

        def layer_fwd(layer_params, h, positions, rng, train=True):
            out, _aux = self._layer(h, layer_params, positions, rng, train=train)
            return out

        def head_loss(resident, h, labels):
            x = h
            if cfg.prenorm:
                x = _norm(
                    x,
                    resident["final_norm_scale"],
                    resident.get("final_norm_bias"),
                    cfg.norm,
                    cfg.norm_eps,
                )
            if cfg.tie_embeddings:
                logits = x @ resident["embed"]["tokens"].astype(self.dtype).T
            else:
                logits = x @ resident["lm_head"].astype(self.dtype)
                if cfg.lm_head_bias:
                    logits = logits + resident["lm_head_bias"].astype(logits.dtype)
            if labels is None:
                return logits
            return cross_entropy_loss(logits, labels)

        return embed_fwd, layer_fwd, head_loss

    def apply(self, params, batch, *, rngs=None, train: bool = True, pld_theta=None, ltd_idx=None):
        tokens, labels = _split_batch(batch)
        logits, aux = self._forward(
            params, tokens, rngs, train, pld_theta=pld_theta, ltd_idx=ltd_idx
        )
        if labels is None:
            return logits
        loss = cross_entropy_loss(logits, labels)
        if train:
            # aux is the (already coefficient-scaled) MoE load-balance loss;
            # zero for dense families. Train-only, so eval loss stays pure CE
            # (the reference adds l_aux only in training client code).
            loss = loss + aux
        return loss


def _expand_gqa(q, k, v):
    """Repeat kv heads up to q's head count — ONLY for consumers whose
    contract requires equal head counts (the fused flash kernel, the
    Ulysses head scatter when sp does not divide NKV). Regular attention
    math must use the grouped einsum path instead (DS-R001)."""
    NH, NKV = q.shape[2], k.shape[2]
    if NKV != NH:
        k = jnp.repeat(k, NH // NKV, axis=2)  # lint: allow(DS-R001)
        v = jnp.repeat(v, NH // NKV, axis=2)  # lint: allow(DS-R001)
    return k, v


def _split_batch(batch):
    if isinstance(batch, dict):
        return batch["input_ids"], batch.get("labels")
    if isinstance(batch, (tuple, list)) and len(batch) == 2:
        return batch[0], batch[1]
    return batch, None
