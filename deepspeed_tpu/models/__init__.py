from deepspeed_tpu.models.config import TransformerConfig, bert_config, gpt2_config, llama_config, qwen2_config
from deepspeed_tpu.models.moe_transformer import (
    MoETransformerConfig,
    MoETransformerLM,
    mixtral_config,
    moe_llama_config,
)
from deepspeed_tpu.models.transformer import TransformerLM, cross_entropy_loss
from deepspeed_tpu.models.unet import (
    AutoencoderKL,
    UNet2DConditionModel,
    UNetConfig,
    VAEConfig,
)
