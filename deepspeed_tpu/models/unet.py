"""Diffusers-style spatial models (UNet + VAE), TPU-native.

Reference scope: the generic diffusers injection
(``deepspeed/module_inject/replace_module.py:86`` walks UNet/VAE/CLIP and
swaps attention + norm blocks for DS modules; ``csrc/spatial/csrc/
opt_bias_add.cu`` fuses the conv bias-adds). The TPU-first counterpart:

* **NHWC layout end to end** — XLA:TPU's native conv layout; conv channels
  map onto the MXU's lane dimension without transposes (NCHW would insert a
  layout pass around every conv).
* **bias-add / GroupNorm / SiLU fusion** — XLA fuses the elementwise tail
  into the convolution; the reference needs a hand-written CUDA kernel
  (`opt_bias_add.cu`) for exactly this, here it falls out of the compiler.
* **Tensor parallelism as sharding specs, not module surgery** —
  ``tp_partition_rules`` emits Megatron-style channel-parallel specs
  (attention qkv/out and the resnet conv pair column→row sharded over the
  'model' axis); the GSPMD partitioner inserts the psum the reference's
  LinearAllreduce does by hand (``module_inject/layers.py:15``).

The UNet is a faithful miniature of the diffusers UNet2DConditionModel
topology (timestep MLP, down/mid/up resnet+cross-attention blocks, skip
concatenation, nearest-upsample); the VAE is the encoder/decoder conv stack
with a diagonal-Gaussian bottleneck. Both are sized by config — tests run
tiny instances, the structure (and the sharding story) is what parity means
here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.module import DSModule

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_channels: Tuple[int, ...] = (32, 64)
    layers_per_block: int = 1
    attn_levels: Tuple[int, ...] = (1,)  # which down/up levels carry attention
    num_heads: int = 4
    context_dim: Optional[int] = 32  # cross-attention width; None = self-attn only
    groups: int = 8  # GroupNorm groups
    time_embed_dim: Optional[int] = None  # default 4 * block_channels[0]
    dtype: str = "float32"

    def __post_init__(self):
        if self.time_embed_dim is None:
            self.time_embed_dim = 4 * self.block_channels[0]


@dataclasses.dataclass
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_channels: Tuple[int, ...] = (32, 64)
    groups: int = 8
    dtype: str = "float32"


# ---------------------------------------------------------------------------
# functional pieces (NHWC)


def _conv(x, w, b=None, stride: int = 1):
    """3x3/1x1 NHWC conv; bias-add left to XLA fusion (the reference's
    opt_bias_add kernel is this fusion, hand-written)."""
    out = jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def _group_norm(x, scale, bias, groups: int, eps: float = 1e-5):
    B, H, W, C = x.shape
    xg = x.reshape(B, H, W, groups, C // groups).astype(jnp.float32)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    out = xg.reshape(B, H, W, C).astype(x.dtype)
    return out * scale.astype(x.dtype) + bias.astype(x.dtype)


def _timestep_embedding(t, dim: int):
    """Sinusoidal embedding (DDPM convention)."""
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _init_conv(rng, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return {
        "w": jax.random.normal(rng, (kh, kw, cin, cout)) / np.sqrt(fan_in),
        "b": jnp.zeros((cout,)),
    }


def _init_linear(rng, cin, cout):
    return {
        "w": jax.random.normal(rng, (cin, cout)) / np.sqrt(cin),
        "b": jnp.zeros((cout,)),
    }


class _SpatialBase(DSModule):
    """Shared init helpers for the conv families."""

    def _resnet_init(self, k, cin, cout, temb_dim=None):
        p = {
            "norm1_scale": jnp.ones((cin,)),
            "norm1_bias": jnp.zeros((cin,)),
            "conv1": _init_conv(next(k), 3, 3, cin, cout),
            "norm2_scale": jnp.ones((cout,)),
            "norm2_bias": jnp.zeros((cout,)),
            "conv2": _init_conv(next(k), 3, 3, cout, cout),
        }
        if temb_dim is not None:
            p["temb_proj"] = _init_linear(next(k), temb_dim, cout)
        if cin != cout:
            p["skip"] = _init_conv(next(k), 1, 1, cin, cout)
        return p

    def _resnet_apply(self, p, x, temb, groups):
        h = jax.nn.silu(_group_norm(x, p["norm1_scale"], p["norm1_bias"], groups))
        h = _conv(h, p["conv1"]["w"], p["conv1"]["b"])
        if temb is not None and "temb_proj" in p:
            t = jax.nn.silu(temb) @ p["temb_proj"]["w"].astype(temb.dtype) + p["temb_proj"]["b"].astype(temb.dtype)
            h = h + t[:, None, None, :].astype(h.dtype)
        h = jax.nn.silu(_group_norm(h, p["norm2_scale"], p["norm2_bias"], groups))
        h = _conv(h, p["conv2"]["w"], p["conv2"]["b"])
        if "skip" in p:
            x = _conv(x, p["skip"]["w"], p["skip"]["b"])
        return x + h

    def _attn_init(self, k, ch, context_dim):
        p = {
            "norm_scale": jnp.ones((ch,)),
            "norm_bias": jnp.zeros((ch,)),
            "wq": _init_linear(next(k), ch, ch),
            "wk": _init_linear(next(k), context_dim or ch, ch),
            "wv": _init_linear(next(k), context_dim or ch, ch),
            "wo": _init_linear(next(k), ch, ch),
        }
        return p

    def _attn_apply(self, p, x, context, num_heads, groups):
        """Spatial (cross-)attention: flatten HW to tokens. The einsum shapes
        keep heads on the MXU lane dim; TP shards the head dim via the qkv
        specs (column) and wo (row) like the decoder families."""
        B, H, W, C = x.shape
        D = C // num_heads
        h = _group_norm(x, p["norm_scale"], p["norm_bias"], groups)
        tokens = h.reshape(B, H * W, C)
        ctx = tokens if context is None else context.astype(tokens.dtype)
        q = (tokens @ p["wq"]["w"].astype(tokens.dtype) + p["wq"]["b"].astype(tokens.dtype)).reshape(B, -1, num_heads, D)
        kk = (ctx @ p["wk"]["w"].astype(ctx.dtype) + p["wk"]["b"].astype(ctx.dtype)).reshape(B, -1, num_heads, D)
        v = (ctx @ p["wv"]["w"].astype(ctx.dtype) + p["wv"]["b"].astype(ctx.dtype)).reshape(B, -1, num_heads, D)
        scores = jnp.einsum("btnd,bsnd->bnts", q, kk).astype(jnp.float32) / np.sqrt(D)
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        out = jnp.einsum("bnts,bsnd->btnd", probs, v).reshape(B, H * W, C)
        out = out @ p["wo"]["w"].astype(out.dtype) + p["wo"]["b"].astype(out.dtype)
        return x + out.reshape(B, H, W, C)

    @staticmethod
    def _attn_specs(mp_axis="model"):
        return {
            "norm_scale": P(),
            "norm_bias": P(),
            "wq": {"w": P(None, mp_axis), "b": P(mp_axis)},
            "wk": {"w": P(None, mp_axis), "b": P(mp_axis)},
            "wv": {"w": P(None, mp_axis), "b": P(mp_axis)},
            "wo": {"w": P(mp_axis, None), "b": P()},
        }

    @staticmethod
    def _resnet_specs(p, mp_axis="model"):
        """Megatron pair over the conv stack: conv1 output-channel (column)
        sharded, conv2 input-channel (row) sharded → one psum per block,
        inserted by GSPMD from these specs alone."""
        specs = {
            "norm1_scale": P(),
            "norm1_bias": P(),
            "conv1": {"w": P(None, None, None, mp_axis), "b": P(mp_axis)},
            "norm2_scale": P(mp_axis),
            "norm2_bias": P(mp_axis),
            "conv2": {"w": P(None, None, mp_axis, None), "b": P()},
        }
        if "temb_proj" in p:
            specs["temb_proj"] = {"w": P(None, mp_axis), "b": P(mp_axis)}
        if "skip" in p:
            specs["skip"] = {"w": P(), "b": P()}
        return specs


class UNet2DConditionModel(_SpatialBase):
    """Miniature diffusers UNet (reference injection target
    ``module_inject/containers/unet.py``). Batch forms: ``(sample, timesteps,
    context)`` or a dict with those keys; ``apply`` returns the predicted
    noise (inference contract — diffusion training wraps its own loss)."""

    def __init__(self, config: UNetConfig):
        self.config = config
        self.dtype = _DTYPES[config.dtype]

    def init(self, rng, batch=None) -> Dict[str, Any]:
        cfg = self.config
        keys = iter(jax.random.split(rng, 4096))
        k = lambda: next(keys)  # noqa: E731
        kiter = keys
        ch0 = cfg.block_channels[0]
        params: Dict[str, Any] = {
            "time_mlp": {
                "fc1": _init_linear(k(), ch0, cfg.time_embed_dim),
                "fc2": _init_linear(k(), cfg.time_embed_dim, cfg.time_embed_dim),
            },
            "conv_in": _init_conv(k(), 3, 3, cfg.in_channels, ch0),
        }
        # skip_ch mirrors apply()'s skip stack exactly: the up-path resnets
        # concat skips whose channel counts vary WITHIN a block (the last
        # resnet of each up block reads the previous level's skip)
        downs = []
        cin = ch0
        skip_ch = [ch0]
        for lvl, ch in enumerate(cfg.block_channels):
            blk: Dict[str, Any] = {"resnets": [], "attns": []}
            for _ in range(cfg.layers_per_block):
                blk["resnets"].append(self._resnet_init(kiter, cin, ch, cfg.time_embed_dim))
                blk["attns"].append(
                    self._attn_init(kiter, ch, cfg.context_dim) if lvl in cfg.attn_levels else {}
                )
                cin = ch
                skip_ch.append(ch)
            if lvl < len(cfg.block_channels) - 1:
                blk["down"] = _init_conv(k(), 3, 3, ch, ch)
                skip_ch.append(ch)
            downs.append(blk)
        params["down"] = downs
        mid_ch = cfg.block_channels[-1]
        params["mid"] = {
            "res1": self._resnet_init(kiter, mid_ch, mid_ch, cfg.time_embed_dim),
            "attn": self._attn_init(kiter, mid_ch, cfg.context_dim),
            "res2": self._resnet_init(kiter, mid_ch, mid_ch, cfg.time_embed_dim),
        }
        ups = []
        for lvl in reversed(range(len(cfg.block_channels))):
            ch = cfg.block_channels[lvl]
            blk = {"resnets": [], "attns": []}
            for _ in range(cfg.layers_per_block + 1):
                skip = skip_ch.pop()
                blk["resnets"].append(
                    self._resnet_init(kiter, cin + skip, ch, cfg.time_embed_dim)
                )
                blk["attns"].append(
                    self._attn_init(kiter, ch, cfg.context_dim) if lvl in cfg.attn_levels else {}
                )
                cin = ch
            if lvl > 0:
                blk["up"] = _init_conv(k(), 3, 3, ch, ch)
            ups.append(blk)
        params["up"] = ups
        params["norm_out_scale"] = jnp.ones((ch0,))
        params["norm_out_bias"] = jnp.zeros((ch0,))
        params["conv_out"] = _init_conv(k(), 3, 3, ch0, cfg.out_channels)
        return params

    def _split_batch(self, batch):
        if isinstance(batch, dict):
            return batch["sample"], batch["timesteps"], batch.get("context")
        if isinstance(batch, (tuple, list)):
            items = list(batch)[:3]
            if len(items) == 1:
                items.append(jnp.zeros((items[0].shape[0],), jnp.int32))
            while len(items) < 3:
                items.append(None)
            return tuple(items)
        return batch, jnp.zeros((batch.shape[0],), jnp.int32), None

    def apply(self, params, batch, *, rngs=None, train: bool = True):  # noqa: ARG002
        cfg = self.config
        sample, timesteps, context = self._split_batch(batch)
        x = jnp.asarray(sample, self.dtype)
        g = cfg.groups

        temb = _timestep_embedding(jnp.asarray(timesteps), cfg.block_channels[0])
        tm = params["time_mlp"]
        temb = jax.nn.silu(temb @ tm["fc1"]["w"] + tm["fc1"]["b"]) @ tm["fc2"]["w"] + tm["fc2"]["b"]

        x = _conv(x, params["conv_in"]["w"], params["conv_in"]["b"])
        skips = [x]
        for lvl, blk in enumerate(params["down"]):
            for rp, ap in zip(blk["resnets"], blk["attns"]):
                x = self._resnet_apply(rp, x, temb, g)
                if ap:
                    x = self._attn_apply(ap, x, context, cfg.num_heads, g)
                skips.append(x)
            if "down" in blk:
                x = _conv(x, blk["down"]["w"], blk["down"]["b"], stride=2)
                skips.append(x)
        mid = params["mid"]
        x = self._resnet_apply(mid["res1"], x, temb, g)
        x = self._attn_apply(mid["attn"], x, context, cfg.num_heads, g)
        x = self._resnet_apply(mid["res2"], x, temb, g)
        for i, blk in enumerate(params["up"]):
            for rp, ap in zip(blk["resnets"], blk["attns"]):
                x = jnp.concatenate([x, skips.pop()], axis=-1)
                x = self._resnet_apply(rp, x, temb, g)
                if ap:
                    x = self._attn_apply(ap, x, context, cfg.num_heads, g)
            if "up" in blk:
                B, H, W, C = x.shape
                x = jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")
                x = _conv(x, blk["up"]["w"], blk["up"]["b"])
        x = jax.nn.silu(_group_norm(x, params["norm_out_scale"], params["norm_out_bias"], g))
        return _conv(x, params["conv_out"]["w"], params["conv_out"]["b"])

    def tp_partition_rules(self, params_shapes=None) -> Any:
        """Spec tree mirroring init()'s structure — the sharding-emission
        counterpart of the reference's UNetPolicy module walk."""
        if params_shapes is None:
            params_shapes = self.init(jax.random.PRNGKey(0))
        mp = "model"

        def block_specs(blk):
            out = {
                "resnets": [self._resnet_specs(rp, mp) for rp in blk["resnets"]],
                "attns": [self._attn_specs(mp) if ap else {} for ap in blk["attns"]],
            }
            for extra in ("down", "up"):
                if extra in blk:
                    out[extra] = {"w": P(), "b": P()}
            return out

        return {
            "time_mlp": {
                "fc1": {"w": P(None, mp), "b": P(mp)},
                "fc2": {"w": P(mp, None), "b": P()},
            },
            "conv_in": {"w": P(), "b": P()},
            "down": [block_specs(b) for b in params_shapes["down"]],
            "mid": {
                "res1": self._resnet_specs(params_shapes["mid"]["res1"], mp),
                "attn": self._attn_specs(mp),
                "res2": self._resnet_specs(params_shapes["mid"]["res2"], mp),
            },
            "up": [block_specs(b) for b in params_shapes["up"]],
            "norm_out_scale": P(),
            "norm_out_bias": P(),
            "conv_out": {"w": P(), "b": P()},
        }


class AutoencoderKL(_SpatialBase):
    """VAE (reference injection target ``module_inject/containers/vae.py``):
    conv encoder → diagonal Gaussian latents → conv decoder. ``apply`` on a
    dict/array batch returns the reconstruction; ``encode``/``decode`` give
    the serving surface."""

    def __init__(self, config: VAEConfig):
        self.config = config
        self.dtype = _DTYPES[config.dtype]

    def init(self, rng, batch=None) -> Dict[str, Any]:
        cfg = self.config
        keys = iter(jax.random.split(rng, 1024))
        k = lambda: next(keys)  # noqa: E731
        kiter = keys
        chans = cfg.block_channels
        enc: Dict[str, Any] = {"conv_in": _init_conv(k(), 3, 3, cfg.in_channels, chans[0])}
        cin = chans[0]
        enc_blocks = []
        for ch in chans:
            blk = {"res": self._resnet_init(kiter, cin, ch), "down": _init_conv(k(), 3, 3, ch, ch)}
            enc_blocks.append(blk)
            cin = ch
        enc["blocks"] = enc_blocks
        enc["norm_scale"] = jnp.ones((cin,))
        enc["norm_bias"] = jnp.zeros((cin,))
        enc["conv_out"] = _init_conv(k(), 3, 3, cin, 2 * cfg.latent_channels)
        dec: Dict[str, Any] = {"conv_in": _init_conv(k(), 3, 3, cfg.latent_channels, cin)}
        dec_blocks = []
        for ch in reversed(chans):
            blk = {"res": self._resnet_init(kiter, cin, ch), "up": _init_conv(k(), 3, 3, ch, ch)}
            dec_blocks.append(blk)
            cin = ch
        dec["blocks"] = dec_blocks
        dec["norm_scale"] = jnp.ones((cin,))
        dec["norm_bias"] = jnp.zeros((cin,))
        dec["conv_out"] = _init_conv(k(), 3, 3, cin, cfg.in_channels)
        return {"encoder": enc, "decoder": dec}

    def encode(self, params, x):
        cfg = self.config
        enc = params["encoder"]
        x = _conv(jnp.asarray(x, self.dtype), enc["conv_in"]["w"], enc["conv_in"]["b"])
        for blk in enc["blocks"]:
            x = self._resnet_apply(blk["res"], x, None, cfg.groups)
            x = _conv(x, blk["down"]["w"], blk["down"]["b"], stride=2)
        x = jax.nn.silu(_group_norm(x, enc["norm_scale"], enc["norm_bias"], cfg.groups))
        moments = _conv(x, enc["conv_out"]["w"], enc["conv_out"]["b"])
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def decode(self, params, z):
        cfg = self.config
        dec = params["decoder"]
        x = _conv(jnp.asarray(z, self.dtype), dec["conv_in"]["w"], dec["conv_in"]["b"])
        for blk in dec["blocks"]:
            x = self._resnet_apply(blk["res"], x, None, cfg.groups)
            B, H, W, C = x.shape
            x = jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")
            x = _conv(x, blk["up"]["w"], blk["up"]["b"])
        x = jax.nn.silu(_group_norm(x, dec["norm_scale"], dec["norm_bias"], cfg.groups))
        return _conv(x, dec["conv_out"]["w"], dec["conv_out"]["b"])

    def apply(self, params, batch, *, rngs=None, train: bool = True):  # noqa: ARG002
        x = batch["sample"] if isinstance(batch, dict) else batch
        mean, _ = self.encode(params, x)
        return self.decode(params, mean)

    def tp_partition_rules(self, params_shapes=None) -> Any:
        if params_shapes is None:
            params_shapes = self.init(jax.random.PRNGKey(0))
        mp = "model"

        def half(tree):
            out: Dict[str, Any] = {"conv_in": {"w": P(), "b": P()}}
            out["blocks"] = [
                {
                    "res": self._resnet_specs(blk["res"], mp),
                    **{kk: {"w": P(), "b": P()} for kk in ("down", "up") if kk in blk},
                }
                for blk in tree["blocks"]
            ]
            out["norm_scale"] = P()
            out["norm_bias"] = P()
            out["conv_out"] = {"w": P(), "b": P()}
            return out

        return {
            "encoder": half(params_shapes["encoder"]),
            "decoder": half(params_shapes["decoder"]),
        }
