"""MoE decoder model family.

Counterpart of the reference's MoE model usage (``deepspeed/moe/layer.py``
``MoE`` wrapping each FFN; tests/unit/simple_model.py ``SimpleMoEModel``/
``SimplePRMoEModel``): a ``TransformerLM`` whose MLP blocks are Mixture-of-
Experts layers dispatched over the ``expert`` mesh axis.

TPU-shaping: when every layer is MoE (``moe_layer_freq == 1``) the expert
weights stack as ``[L, E, ...]`` and the block still runs under ``lax.scan``;
with interleaved dense/MoE layers the loop unrolls (two param stacks).
The load-balance aux loss is scaled by ``moe_aux_loss_coef`` at the layer and
accumulated through the scan carry into the training loss.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.models.transformer import TransformerLM
from deepspeed_tpu.moe.layer import MoE


@dataclasses.dataclass
class MoETransformerConfig(TransformerConfig):
    num_experts: int = 8
    moe_layer_freq: int = 1  # every k-th layer is MoE (reference "ep_interval")
    moe_top_k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    use_residual: bool = False  # PR-MoE
    noisy_gate_policy: Optional[str] = None  # None | 'RSample' | 'Jitter'
    moe_drop_tokens: bool = True
    moe_use_rts: bool = True
    moe_aux_loss_coef: float = 0.01
    expert_intermediate_size: Optional[int] = None
    # int8 wire format for the expert-parallel dispatch/combine all-to-alls
    # (EQuARX-style per-chunk scales, moe/a2a.py:quantized_all_to_all)
    moe_quantized_a2a: bool = False

    def __post_init__(self):
        super().__post_init__()
        if self.expert_intermediate_size is None:
            self.expert_intermediate_size = self.intermediate_size
        if self.moe_layer_freq > 1:
            # mixed dense/MoE stacks can't share one scanned param stack
            self.scan_layers = False


class MoETransformerLM(TransformerLM):
    def __init__(self, config: MoETransformerConfig):
        super().__init__(config)
        cfg = config
        self.moe = MoE(
            hidden_size=cfg.hidden_size,
            num_experts=cfg.num_experts,
            k=cfg.moe_top_k,
            capacity_factor=cfg.capacity_factor,
            eval_capacity_factor=cfg.eval_capacity_factor,
            min_capacity=cfg.min_capacity,
            use_residual=cfg.use_residual,
            noisy_gate_policy=cfg.noisy_gate_policy,
            drop_tokens=cfg.moe_drop_tokens,
            use_rts=cfg.moe_use_rts,
            intermediate_size=cfg.expert_intermediate_size,
            activation=cfg.activation if cfg.activation in ("gelu", "relu", "swiglu", "geglu") else "gelu",
            use_bias=cfg.use_bias,
            out_std=0.02 / np.sqrt(2 * cfg.num_layers),
            quantized_a2a=cfg.moe_quantized_a2a,
        )
        moe_layers = [i for i in range(cfg.num_layers) if self._is_moe_layer(i)]
        dense_layers = [i for i in range(cfg.num_layers) if not self._is_moe_layer(i)]
        self._moe_index = {li: j for j, li in enumerate(moe_layers)}
        self._dense_index = {li: j for j, li in enumerate(dense_layers)}

    def _is_moe_layer(self, i: int) -> bool:
        return (i + 1) % self.config.moe_layer_freq == 0

    def stream_fns(self):
        raise NotImplementedError(
            "offload_param layer streaming does not support MoE families: the "
            "expert params live outside the stacked layer tree and the "
            "load-balance aux loss cannot ride the per-layer stream programs"
        )

    # --- params ---------------------------------------------------------
    def init(self, rng, batch) -> Dict[str, Any]:
        cfg = self.config
        rng, moe_rng = jax.random.split(rng)
        params = super().init(rng, batch)
        L = cfg.num_layers
        moe_layers = [i for i in range(L) if self._is_moe_layer(i)]
        dense_mlp_keys = {"w_in", "b_in", "w_gate", "w_up", "w_out", "b_out"}
        present = dense_mlp_keys & set(params["layers"])
        if cfg.moe_layer_freq == 1:
            # every layer is MoE: drop the dense FFN stack, scan over [L, E, ...]
            for key in present:
                del params["layers"][key]
            keys = jax.random.split(moe_rng, L)
            params["layers"]["moe"] = jax.vmap(self.moe.init)(keys)
        else:
            # interleaved: dense FFN weights restack over dense layers only
            # ([L_dense, ...]) so MoE layers carry no dead dense params
            dense_idx = np.asarray([i for i in range(L) if i not in set(moe_layers)])
            params["dense_mlp"] = {k: params["layers"].pop(k)[dense_idx] for k in present}
            keys = jax.random.split(moe_rng, len(moe_layers))
            params["moe_layers"] = jax.vmap(self.moe.init)(keys)
        return params

    def _layer_params(self, params, i: int):
        """Unrolled path (moe_layer_freq > 1): merge the layer's attention
        stack slice with its dense-FFN or MoE params by layer index."""
        per_layer = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
        if self.config.moe_layer_freq == 1:
            return per_layer
        if self._is_moe_layer(i):
            j = self._moe_index[i]
            per_layer["moe"] = jax.tree_util.tree_map(lambda a: a[j], params["moe_layers"])
        else:
            j = self._dense_index[i]
            for k, v in params["dense_mlp"].items():
                per_layer[k] = v[j]
        return per_layer

    # --- sharding -------------------------------------------------------
    def tp_partition_rules(self, params_shapes=None) -> Any:
        if params_shapes is None:
            return None
        base = super().tp_partition_rules(params_shapes)

        def moe_rules(stacked_moe_shapes):
            """Stacked [L?, E, ...] expert leaves → expert-axis specs."""

            def walk(prefix, tree):
                if isinstance(tree, dict):
                    return {k: walk(f"{prefix}/{k}", v) for k, v in tree.items()}
                nd = len(tree.shape)
                if prefix.startswith("/experts"):
                    # leading stack dim (scanned layer), then the expert dim
                    return P(None, "expert", *([None] * (nd - 2)))
                return P(*([None] * nd))

            return walk("", stacked_moe_shapes)

        if "moe" in params_shapes.get("layers", {}):
            base["layers"]["moe"] = moe_rules(params_shapes["layers"]["moe"])
        if "moe_layers" in params_shapes:
            base["moe_layers"] = moe_rules(params_shapes["moe_layers"])
        # dense_mlp (interleaved mode) already gets correct Megatron col/row
        # specs from the base name-driven walk — nothing to override.
        return base

    def keep_fp32_params(self, params_shapes=None) -> Any:
        """Router (gate) weights stay fp32 under mixed precision — the
        reference's TopKGate holds ``wg`` in fp32 for routing stability."""
        if params_shapes is None:
            return None

        def walk(prefix, tree):
            if isinstance(tree, dict):
                return {k: walk(f"{prefix}/{k}", v) for k, v in tree.items()}
            return prefix.endswith("/gate/wg")

        return walk("", params_shapes)

    # --- forward --------------------------------------------------------
    def _mlp(self, p, h, rng, train):
        cfg = self.config
        if "moe" in p:
            out, l_aux, _counts = self.moe.apply(p["moe"], h, train=train, rng=rng)
            return out, l_aux * jnp.float32(cfg.moe_aux_loss_coef)
        return super()._mlp(p, h, rng, train)

def moe_llama_config(size: str = "tiny", **overrides) -> MoETransformerConfig:
    presets = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=8, vocab_size=32000, max_seq_len=512),
        "1b-8e": dict(hidden_size=2048, num_layers=22, num_heads=32, num_kv_heads=4, vocab_size=32000),
    }
    base = dict(
        norm="rmsnorm",
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=False,
    )
    base.update(presets[size])
    base.update(overrides)
    return MoETransformerConfig(**base)


def mixtral_config(size: str = "8x7b", **overrides) -> MoETransformerConfig:
    """Mixtral presets (BASELINE config 5's model family): GQA llama body,
    8 experts, top-2 routing, every layer MoE."""
    presets = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=8, num_kv_heads=2, vocab_size=32000, max_seq_len=512),
        "8x7b": dict(
            hidden_size=4096,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            intermediate_size=14336,
            vocab_size=32000,
            max_seq_len=32768,
        ),
    }
    base = dict(
        norm="rmsnorm",
        position="rope",
        rope_theta=1e6,
        activation="swiglu",
        use_bias=False,
        tie_embeddings=False,
        num_experts=8,
        moe_top_k=2,
        moe_layer_freq=1,
    )
    base.update(presets[size])
    base.update(overrides)
    return MoETransformerConfig(**base)
