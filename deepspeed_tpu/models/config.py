"""Model configs for the built-in transformer families.

One decoder implementation (``models/transformer.py``) parameterized to cover
the reference's injected model zoo (``deepspeed/module_inject/containers/``:
gpt2, llama, gptj, gptneox, opt, bloom, megatron): norm type, positional
scheme, activation, attention variant (MHA/GQA) are all config switches.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    intermediate_size: Optional[int] = None  # default: 4h (gelu) or 8h/3 rounded (swiglu)
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # GQA; None = MHA
    head_dim: Optional[int] = None
    max_seq_len: int = 2048

    causal: bool = True  # False = bidirectional (encoder) attention
    attn_softmax_scale: Optional[float] = None  # None = 1/sqrt(head_dim); GPT-Neo uses 1.0
    prenorm: bool = True  # False = post-LN (BERT family): norm AFTER residual, no final norm
    parallel_residual: bool = False  # GPT-J/NeoX: x + attn(norm(x)) + mlp(norm'(x))
    shared_parallel_norm: bool = False  # GPT-J: both parallel branches read ONE norm (ln_1)
    rope_dim: Optional[int] = None  # partial rotary (GPT-J rotary_dim / NeoX rotary_pct); None = full head_dim
    lm_head_bias: bool = False  # GPT-J: untied head carries a bias
    embed_norm: bool = False  # LayerNorm on the embedding output (BERT family)
    norm: str = "layernorm"  # layernorm | rmsnorm
    norm_eps: float = 1e-5
    position: str = "learned"  # learned | rope | alibi | none
    rope_theta: float = 10000.0
    activation: str = "gelu"  # gelu | swiglu | relu | geglu | quick_gelu
    tie_embeddings: bool = True
    attn_dropout: float = 0.0
    hidden_dropout: float = 0.0
    use_bias: bool = True  # linear biases (gpt2 yes, llama no)
    qkv_bias: Optional[bool] = None  # override for qkv projs
    dtype: str = "bfloat16"  # computation dtype for activations

    # sparse embedding gradients (reference engine.py:2398: DP-reduce the
    # compact (ids, rows) pairs instead of the dense table; requires an
    # untied table — a tied LM head makes the table grad dense anyway)
    sparse_embedding_grads: bool = False

    # engineering knobs
    remat: bool = True  # jax.checkpoint each layer
    remat_policy: str = "nothing_saveable"
    scan_layers: bool = True  # lax.scan over stacked layer params
    flash_attention: bool = True  # use the Pallas fused-attention kernel when available (falls back to einsum)
    sequence_parallel: bool = False  # sequence parallelism over the 'sequence' axis
    sequence_parallel_mode: str = "ulysses"  # ulysses (all-to-all) | ring (ppermute)

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_heads
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.intermediate_size is None:
            if self.activation in ("swiglu", "geglu"):
                # llama convention: 2/3 * 4h rounded to a multiple of 256
                self.intermediate_size = 256 * round(self.hidden_size * 8 / 3 / 256)
            else:
                self.intermediate_size = 4 * self.hidden_size
        if self.qkv_bias is None:
            self.qkv_bias = self.use_bias
        if self.sequence_parallel_mode not in ("ulysses", "ring"):
            raise ValueError(
                f"unknown sequence_parallel_mode {self.sequence_parallel_mode!r}; "
                "expected 'ulysses' or 'ring'"
            )
        if self.shared_parallel_norm and not self.parallel_residual:
            raise ValueError("shared_parallel_norm requires parallel_residual=True")
        if self.parallel_residual and not self.prenorm:
            raise ValueError(
                "parallel_residual requires prenorm=True (both branches read "
                "normed x; a post-LN parallel layer is not a real architecture)"
            )
        if self.lm_head_bias and self.tie_embeddings:
            raise ValueError("lm_head_bias requires an untied head (tie_embeddings=False)")
        if self.sparse_embedding_grads and self.tie_embeddings:
            raise ValueError(
                "sparse_embedding_grads requires tie_embeddings=False: a tied "
                "LM head contributes a dense gradient to the same table, so "
                "there is nothing sparse to reduce"
            )


def gpt2_config(size: str = "125m", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=8, vocab_size=1024, max_seq_len=512),
        "125m": dict(hidden_size=768, num_layers=12, num_heads=12),
        "350m": dict(hidden_size=1024, num_layers=24, num_heads=16),
        "1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16),
        "2.7b": dict(hidden_size=2560, num_layers=32, num_heads=32),
    }
    base = dict(
        vocab_size=50257,
        max_seq_len=1024,
        norm="layernorm",
        position="learned",
        activation="gelu",
        use_bias=True,
        tie_embeddings=True,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def llama_config(size: str = "7b", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=8, vocab_size=32000, max_seq_len=512),
        "1b": dict(hidden_size=2048, num_layers=22, num_heads=32, num_kv_heads=4, vocab_size=32000),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32, vocab_size=32000, max_seq_len=4096),
        "13b": dict(hidden_size=5120, num_layers=40, num_heads=40, vocab_size=32000, max_seq_len=4096),
        "70b": dict(
            hidden_size=8192,
            num_layers=80,
            num_heads=64,
            num_kv_heads=8,
            intermediate_size=28672,
            vocab_size=32000,
            max_seq_len=4096,
        ),
    }
    base = dict(
        norm="rmsnorm",
        norm_eps=1e-5,
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=False,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def qwen2_config(size: str = "7b", **overrides) -> TransformerConfig:
    """Qwen2 family: the llama body (RMSNorm + RoPE + SwiGLU, no output
    biases) with BIASED q/k/v projections and GQA."""
    presets = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=8, num_kv_heads=2,
                     vocab_size=1024, max_seq_len=512),
        "0.5b": dict(hidden_size=896, num_layers=24, num_heads=14, num_kv_heads=2,
                     intermediate_size=4864, vocab_size=151936, tie_embeddings=True),
        "7b": dict(hidden_size=3584, num_layers=28, num_heads=28, num_kv_heads=4,
                   intermediate_size=18944, vocab_size=152064, max_seq_len=4096),
    }
    base = dict(
        norm="rmsnorm",
        norm_eps=1e-6,
        position="rope",
        rope_theta=1e6,  # all Qwen2 sizes use base 1e6 (like mixtral_config)
        activation="swiglu",
        use_bias=False,
        qkv_bias=True,
        tie_embeddings=False,
        max_seq_len=2048,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


def bert_config(size: str = "large", **overrides) -> TransformerConfig:
    """Encoder config: bidirectional (non-causal) attention."""
    presets = {
        "base": dict(hidden_size=768, num_layers=12, num_heads=12),
        "large": dict(hidden_size=1024, num_layers=24, num_heads=16),
    }
    base = dict(
        vocab_size=30522,
        max_seq_len=512,
        causal=False,
        norm="layernorm",
        position="learned",
        activation="gelu",
        use_bias=True,
        tie_embeddings=False,
    )
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)
