"""Launcher constants (reference: ``deepspeed/launcher/constants.py``)."""

PDSH_LAUNCHER = "pdsh"
PDSH_MAX_FAN_OUT = 1024

OPENMPI_LAUNCHER = "openmpi"
MPICH_LAUNCHER = "mpich"
IMPI_LAUNCHER = "impi"
SLURM_LAUNCHER = "slurm"
MVAPICH_LAUNCHER = "mvapich"
MVAPICH_TMP_HOSTFILE = "/tmp/deepspeed_mvapich_hostfile"

ELASTIC_TRAINING_ID_DEFAULT = "123456789"
