"""``deepspeed`` CLI entry (reference: ``deepspeed/launcher/runner.py:389``).

Parses the hostfile and resource filters, encodes the world info, chooses a
multinode runner (pdsh default), and either execs the per-node launcher
locally (single node) or the runner's fan-out command.

Hostfile syntax matches the reference (runner.py:201)::

    worker-1 slots=4
    worker-2 slots=4

On TPU a "slot" is a host-attached chip; the per-node launcher still starts
ONE worker process per host (chips are mesh-addressed in-process), so slots
inform topology metadata rather than fork count.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
from collections import OrderedDict
from typing import Dict

from deepspeed_tpu.launcher.constants import (
    IMPI_LAUNCHER,
    MPICH_LAUNCHER,
    MVAPICH_LAUNCHER,
    OPENMPI_LAUNCHER,
    PDSH_LAUNCHER,
    SLURM_LAUNCHER,
)
from deepspeed_tpu.launcher.launch import encode_world_info
from deepspeed_tpu.launcher.multinode_runner import (
    IMPIRunner,
    MPICHRunner,
    MVAPICHRunner,
    MultiNodeRunner,
    OpenMPIRunner,
    PDSHRunner,
    SlurmRunner,
)
from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "TPU_", "JAX_", "XLA_", "LIBTPU_", "DS_"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
DEEPSPEED_ENVIRONMENT_PATHS = [".", os.path.expanduser("~")]
PDSH_MAX_FAN_OUT = 1024


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu distributed launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path (host slots=n per line)")
    parser.add_argument("-i", "--include", type=str, default="",
                        help='Include hosts/slots, e.g. "worker-0@worker-1:0,2"')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help='Exclude hosts/slots, e.g. "worker-1:0"')
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Number of nodes to run on (from hostfile)")
    parser.add_argument("--num_gpus", "--num_accelerators", type=int, default=-1,
                        dest="num_gpus", help="Max chips per node")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default=PDSH_LAUNCHER,
                        choices=[PDSH_LAUNCHER, OPENMPI_LAUNCHER, MPICH_LAUNCHER,
                                 IMPI_LAUNCHER, SLURM_LAUNCHER, MVAPICH_LAUNCHER])
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--module", action="store_true")
    parser.add_argument("--no_python", action="store_true")
    parser.add_argument("--no_local_rank", action="store_true")
    parser.add_argument("--no_ssh_check", action="store_true")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--save_pid", action="store_true")
    parser.add_argument("--autotuning", type=str, default="",
                        choices=["", "tune", "run"],
                        help="Run the autotuner to discover optimal config")
    parser.add_argument("--elastic_training", action="store_true")
    parser.add_argument("user_script", type=str, help="User script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Dict[str, int]:
    """Parse ``host slots=n`` lines (reference runner.py:201)."""
    if not os.path.isfile(hostfile_path):
        logger.debug(f"Unable to find hostfile at {hostfile_path}")
        return {}
    resource_pool: Dict[str, int] = OrderedDict()
    with open(hostfile_path) as fd:
        for line in fd:
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                logger.error(f"Hostfile is not formatted correctly, unable to proceed: {line!r}")
                raise ValueError(f"hostfile line malformed: {line!r}")
            if hostname in resource_pool:
                logger.error(f"Hostfile contains duplicate hosts, unable to proceed: {hostname}")
                raise ValueError(f"duplicate host {hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_hostfile_filter(spec: str) -> Dict[str, list]:
    """Parse an include/exclude string ``host1@host2:0,2`` → {host: [slots]}
    (reference runner.py:256 ``parse_resource_filter``)."""
    result: Dict[str, list] = OrderedDict()
    if spec == "":
        return result
    for node_spec in spec.split("@"):
        if ":" in node_spec:
            host, slot_str = node_spec.split(":")
            slots = [int(s) for s in slot_str.split(",")]
            result[host] = slots
        else:
            result[node_spec] = []
    return result


def parse_resource_filter(
    host_info: Dict[str, int], include_str: str = "", exclude_str: str = ""
) -> Dict[str, list]:
    """Apply include/exclude filters to the resource pool
    (reference runner.py:256)."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")

    pool: Dict[str, list] = OrderedDict(
        (host, list(range(slots))) for host, slots in host_info.items()
    )
    if include_str:
        include = _parse_hostfile_filter(include_str)
        filtered: Dict[str, list] = OrderedDict()
        for host, slots in include.items():
            if host not in pool:
                raise ValueError(f"include host {host} not in hostfile")
            use = slots if slots else pool[host]
            for s in use:
                if s not in pool[host]:
                    raise ValueError(f"include slot {host}:{s} not available")
            filtered[host] = use
        return filtered
    if exclude_str:
        exclude = _parse_hostfile_filter(exclude_str)
        for host, slots in exclude.items():
            if host not in pool:
                raise ValueError(f"exclude host {host} not in hostfile")
            if slots:
                pool[host] = [s for s in pool[host] if s not in slots]
                if not pool[host]:
                    del pool[host]
            else:
                del pool[host]
    return pool


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    return parse_resource_filter(dict(resource_pool), include_str=inclusion, exclude_str=exclusion)


def encode_world_info_from_pool(active_resources: Dict[str, list]) -> str:
    return encode_world_info(active_resources)


def main(args=None):
    args = parse_args(args)

    if args.autotuning:
        from deepspeed_tpu.autotuning.autotuner import run_autotuning

        return run_autotuning(args)

    resource_pool = fetch_hostfile(args.hostfile)

    # single-node shortcut: no hostfile → run the per-node launcher directly
    multi_node = bool(resource_pool) and (len(resource_pool) > 1 or args.force_multi)
    if not multi_node:
        env = os.environ.copy()
        master = args.master_addr or "127.0.0.1"
        cmd = [
            sys.executable,
            "-u",
            "-m",
            "deepspeed_tpu.launcher.launch",
            "--world_info=None",
            "--node_rank=0",
            f"--master_addr={master}",
            f"--master_port={args.master_port}",
        ]
        if args.module:
            cmd.append("--module")
        if args.no_python:
            cmd.append("--no_python")
        cmd.append(args.user_script)
        cmd += args.user_args
        logger.info(f"cmd = {' '.join(cmd)}")
        result = subprocess.Popen(cmd, env=env)
        result.wait()
        return result.returncode

    active_resources = parse_inclusion_exclusion(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        hosts = list(active_resources.keys())[: args.num_nodes]
        active_resources = OrderedDict((h, active_resources[h]) for h in hosts)
    if args.num_gpus > 0:
        active_resources = OrderedDict(
            (h, s[: args.num_gpus]) for h, s in active_resources.items()
        )
    if not args.master_addr:
        first_host = re.split(r"[:,@]", list(active_resources.keys())[0])[0]
        args.master_addr = first_host

    world_info_base64 = encode_world_info(active_resources)

    runner: MultiNodeRunner
    if args.launcher == PDSH_LAUNCHER:
        runner = PDSHRunner(args, world_info_base64)
    elif args.launcher == OPENMPI_LAUNCHER:
        runner = OpenMPIRunner(args, world_info_base64, active_resources)
    elif args.launcher == MPICH_LAUNCHER:
        runner = MPICHRunner(args, world_info_base64, active_resources)
    elif args.launcher == IMPI_LAUNCHER:
        runner = IMPIRunner(args, world_info_base64, active_resources)
    elif args.launcher == SLURM_LAUNCHER:
        runner = SlurmRunner(args, world_info_base64, active_resources)
    elif args.launcher == MVAPICH_LAUNCHER:
        runner = MVAPICHRunner(args, world_info_base64, active_resources)
    else:
        raise NotImplementedError(f"Unknown launcher {args.launcher}")

    if not runner.backend_exists():
        raise RuntimeError(f"launcher '{args.launcher}' not installed")
    runner.validate_args()

    # export environment: whitelist prefixes + .deepspeed_env extras
    curr_path = os.path.abspath(".")
    env = os.environ.copy()
    if "PYTHONPATH" in env:
        env["PYTHONPATH"] = curr_path + ":" + env["PYTHONPATH"]
    else:
        env["PYTHONPATH"] = curr_path
    for var, val in env.items():
        if any(var.startswith(name) for name in EXPORT_ENVS):
            runner.add_export(var, val)
    for environ_path in DEEPSPEED_ENVIRONMENT_PATHS:
        environ_file = os.path.join(environ_path, DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(environ_file):
            with open(environ_file) as fd:
                for line in fd.readlines():
                    key, val = line.strip().split("=", 1)
                    runner.add_export(key, val)

    cmd = runner.get_cmd(env, active_resources)
    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
