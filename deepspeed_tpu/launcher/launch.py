"""Per-node launcher.

Counterpart of the reference's ``deepspeed/launcher/launch.py:132``: decodes
``--world_info``, sets the distributed environment, forks worker processes,
and owns their lifecycle (signal forwarding + process-tree cleanup,
reference launch.py:118).

TPU-native delta: the reference forks ``num_local_procs`` = one OS process
per GPU; a TPU host runs ONE worker process that drives all local chips
through the device mesh, so ``local_procs`` defaults to 1 and ``LOCAL_RANK``
is always 0. (``--procs_per_node`` exists for CPU-mesh simulation tests.)

Environment contract (read by ``deepspeed_tpu.comm.init_distributed``):
``RANK``, ``WORLD_SIZE``, ``LOCAL_RANK``, ``MASTER_ADDR``, ``MASTER_PORT``.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import subprocess
import sys
import time
from argparse import ArgumentParser, REMAINDER
from typing import List

from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = ArgumentParser(description="deepspeed_tpu per-node launcher")
    parser.add_argument(
        "--node_rank",
        type=int,
        default=0,
        help="rank of this node in the multi-node deployment",
    )
    parser.add_argument(
        "--master_addr",
        default="127.0.0.1",
        type=str,
        help="coordinator address (rank-0 node)",
    )
    parser.add_argument("--master_port", default=29500, type=int)
    parser.add_argument(
        "--world_info",
        default="None",
        type=str,
        help="base64-encoded dict host → local slot list",
    )
    parser.add_argument(
        "--procs_per_node",
        type=int,
        default=1,
        help="worker processes per node (1 on TPU: chips are mesh-addressed)",
    )
    parser.add_argument("--module", action="store_true")
    parser.add_argument("--no_python", action="store_true")
    parser.add_argument("--save_pid", type=int, default=0)
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(encoded: str) -> dict:
    if encoded in ("None", "", None):
        return {}
    decoded = base64.urlsafe_b64decode(encoded)
    return json.loads(decoded)


def encode_world_info(world_info: dict) -> str:
    json_str = json.dumps(world_info)
    return base64.urlsafe_b64encode(json_str.encode()).decode()


def build_child_env(args, node_rank: int, num_nodes: int, local_rank: int) -> dict:
    env = os.environ.copy()
    procs = args.procs_per_node
    world_size = num_nodes * procs
    rank = node_rank * procs + local_rank
    env["RANK"] = str(rank)
    env["LOCAL_RANK"] = str(local_rank)
    env["WORLD_SIZE"] = str(world_size)
    env["MASTER_ADDR"] = args.master_addr
    env["MASTER_PORT"] = str(args.master_port)
    # standard JAX cluster envs for jax.distributed auto-init
    env["COORDINATOR_ADDRESS"] = f"{args.master_addr}:{args.master_port}"
    return env


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    if world_info:
        num_nodes = len(world_info)
        node_hosts = list(world_info.keys())
        logger.info(
            f"nnodes={num_nodes}, node_rank={args.node_rank}, hosts={node_hosts}"
        )
    else:
        num_nodes = 1

    processes: List[subprocess.Popen] = []
    for local_rank in range(args.procs_per_node):
        env = build_child_env(args, args.node_rank, num_nodes, local_rank)
        cmd = []
        if not args.no_python:
            cmd = [sys.executable, "-u"]
            if args.module:
                cmd.append("-m")
        else:
            if args.module:
                raise ValueError("--module and --no_python cannot be used together")
        cmd.append(args.training_script)
        cmd += args.training_script_args
        logger.info(f"launch rank={env['RANK']}: {' '.join(cmd)}")
        processes.append(subprocess.Popen(cmd, env=env))

    sig_names = {2: "SIGINT", 15: "SIGTERM"}
    last_return_code = None

    def sigkill_handler(signum, frame):  # noqa: ARG001
        """Kill the whole worker tree on signal (reference launch.py:118)."""
        for process in processes:
            logger.info(f"Killing subprocess {process.pid}")
            try:
                process.kill()
            except Exception:
                pass
        if last_return_code is not None:
            logger.error(f"{processes[-1].args} exits with return code = {last_return_code}")
            sys.exit(last_return_code)
        if signum in sig_names:
            logger.info(f"Main process received {sig_names[signum]}, exiting")
        sys.exit(1)

    signal.signal(signal.SIGINT, sigkill_handler)
    signal.signal(signal.SIGTERM, sigkill_handler)

    alive = list(processes)
    while alive:
        finished = []
        for process in alive:
            rc = process.poll()
            if rc is None:
                continue
            finished.append(process)
            if rc != 0:
                last_return_code = rc
                sigkill_handler(signal.SIGTERM, None)
        alive = [p for p in alive if p not in finished]
        if alive:
            time.sleep(0.5)
    return 0


if __name__ == "__main__":
    sys.exit(main())
