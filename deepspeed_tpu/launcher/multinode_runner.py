"""Multi-node runner command builders.

Counterpart of the reference's ``deepspeed/launcher/multinode_runner.py``
(PDSHRunner :51, OpenMPIRunner :109, MPICHRunner :162, IMPIRunner :233,
SlurmRunner :315, MVAPICHRunner :363). Each runner turns (args, world_info,
environment) into the command line that starts one launcher process per
node. TPU-native deltas: one worker process per HOST (chips are addressed
through the in-process mesh, so there is no per-device fork), and the
exported environment carries the JAX coordinator instead of NCCL vars.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from abc import ABC, abstractmethod
from shlex import quote

from deepspeed_tpu.launcher.constants import MVAPICH_TMP_HOSTFILE, PDSH_MAX_FAN_OUT
from deepspeed_tpu.utils.logging import logger


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.user_arguments = self.parse_user_args()
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports = {}

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        """Return the command to launch distributed training."""

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    def parse_user_args(self):
        return self.args.user_args

    @property
    def name(self) -> str:
        return self.__class__.__name__

    def backend_exists(self) -> bool:
        return True

    def validate_args(self) -> None:
        pass


class PDSHRunner(MultiNodeRunner):
    """Default ssh fan-out (reference :51)."""

    def backend_exists(self) -> bool:
        return bool(shutil.which("pdsh"))

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        logger.info(f"Running on the following workers: {active_workers}")

        pdsh_cmd_args = [
            "pdsh",
            "-S",
            "-f",
            str(PDSH_MAX_FAN_OUT),
            "-w",
            active_workers,
        ]
        exports = ""
        for key, val in self.exports.items():
            exports += f"export {key}={quote(val)}; "

        # launch one per-node launcher on each host; it forks the worker(s)
        deepspeed_launch = [
            exports,
            f"cd {os.path.abspath('.')};",
            "python",
            "-u",
            "-m",
            "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            "--node_rank=%n",
            f"--master_addr={self.args.master_addr}",
            f"--master_port={self.args.master_port}",
        ]
        if getattr(self.args, "no_python", False):
            deepspeed_launch.append("--no_python")
        if getattr(self.args, "module", False):
            deepspeed_launch.append("--module")
        return pdsh_cmd_args + deepspeed_launch + [self.user_script] + [
            quote(a) for a in self.user_arguments
        ]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun -hostfile launcher (reference :109)."""

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self) -> bool:
        return bool(shutil.which("ompi_info"))

    def validate_args(self) -> None:
        if self.args.include != "" or self.args.exclude != "":
            raise ValueError(f"{self.name} backend does not support --include/--exclude")

    def get_cmd(self, environment, active_resources):  # noqa: ARG002
        total_process_count = len(self.resource_pool)  # one proc per host
        mpirun_cmd = [
            "mpirun",
            "-n",
            f"{total_process_count}",
            "-hostfile",
            f"{self.args.hostfile}",
            "--mca",
            "btl",
            "^openib",
            "--mca",
            "btl_tcp_if_include",
            "eth0",
        ]
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-x", f"{k}={quote(v)}"]
        python_exec = [] if getattr(self.args, "no_python", False) else ["python", "-u"]
        if getattr(self.args, "module", False):
            python_exec.append("-m")
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + self.user_arguments


class MPICHRunner(MultiNodeRunner):
    """(reference :162)"""

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self) -> bool:
        return bool(shutil.which("mpirun"))

    def validate_args(self) -> None:
        if self.args.include != "" or self.args.exclude != "":
            raise ValueError(f"{self.name} backend does not support --include/--exclude")

    def get_cmd(self, environment, active_resources):  # noqa: ARG002
        total_process_count = len(self.resource_pool)
        mpirun_cmd = [
            "mpirun",
            "-n",
            f"{total_process_count}",
            "-ppn",
            "1",
        ]
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-genv", k, quote(v)]
        python_exec = [] if getattr(self.args, "no_python", False) else ["python", "-u"]
        if getattr(self.args, "module", False):
            python_exec.append("-m")
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + self.user_arguments


class IMPIRunner(MultiNodeRunner):
    """Intel MPI (reference :233)."""

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self) -> bool:
        return bool(shutil.which("mpirun"))

    def validate_args(self) -> None:
        if self.args.include != "" or self.args.exclude != "":
            raise ValueError(f"{self.name} backend does not support --include/--exclude")

    def get_cmd(self, environment, active_resources):  # noqa: ARG002
        total = len(self.resource_pool)
        cmd = ["mpirun", "-ppn", "1"]
        for k, v in self.exports.items():
            cmd += ["-genv", k, quote(v)]
        for rank, host in enumerate(self.resource_pool.keys()):
            cmd += ["-host", host, "-n", "1"]
            python_exec = [] if getattr(self.args, "no_python", False) else ["python", "-u"]
            if getattr(self.args, "module", False):
                python_exec.append("-m")
            cmd += python_exec + [self.user_script] + self.user_arguments
            if rank != total - 1:
                cmd += [":"]
        return cmd


class SlurmRunner(MultiNodeRunner):
    """srun launcher (reference :315)."""

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self) -> bool:
        return bool(shutil.which("sinfo"))

    def get_cmd(self, environment, active_resources):  # noqa: ARG002
        assert not getattr(self.args, "detect_nvlink_pairs", False)
        srun_cmd = [
            "srun",
            "-n",
            f"{len(self.resource_pool)}",
            "--ntasks-per-node=1",
        ]
        if getattr(self.args, "comment", ""):
            srun_cmd += ["--comment", self.args.comment]
        if self.args.include != "":
            srun_cmd += ["--include", f"{self.args.include}"]
        if self.args.exclude != "":
            srun_cmd += ["--exclude", f"{self.args.exclude}"]
        if getattr(self.args, "num_nodes", -1) > 0:
            srun_cmd += ["--nodes", f"{self.args.num_nodes}"]

        exports = ""
        for key, val in self.exports.items():
            exports += f",{key}={val}"
        python_exec = ["python", "-u"]
        return srun_cmd + [f"--export=ALL{exports}"] + python_exec + [self.user_script] + self.user_arguments


class MVAPICHRunner(MultiNodeRunner):
    """(reference :363)"""

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self) -> bool:
        if not shutil.which("mpiname"):
            return False
        try:
            results = subprocess.check_output(["mpiname"], text=True)
        except (subprocess.CalledProcessError, OSError):
            return False
        return "MVAPICH2-GDR" in results

    def get_cmd(self, environment, active_resources):  # noqa: ARG002
        with open(MVAPICH_TMP_HOSTFILE, "w") as fd:
            for host in self.resource_pool.keys():
                fd.write(f"{host}\n")
        total = len(self.resource_pool)
        mpirun_cmd = [
            "mpirun",
            "-np",
            f"{total}",
            "--hostfile",
            MVAPICH_TMP_HOSTFILE,
        ]
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-env", f"{k}={quote(v)}"]
        python_exec = [] if getattr(self.args, "no_python", False) else ["python", "-u"]
        if getattr(self.args, "module", False):
            python_exec.append("-m")
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + self.user_arguments
