"""Monitoring backends.

Counterpart of ``deepspeed/monitor/`` (``MonitorMaster`` monitor.py:29 fanning
out ``write_events`` to TensorBoard / W&B / CSV).
"""

from __future__ import annotations

import os
from typing import List, Tuple

from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.utils.logging import logger


class Monitor:
    def __init__(self, monitor_config):
        self.monitor_config = monitor_config
        self.enabled = False

    def write_events(self, event_list: List[Tuple]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.summary_writer = None
        self.enabled = tensorboard_config.enabled and dist.get_rank() == 0
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter

                log_dir = os.path.join(tensorboard_config.output_path or "./runs", tensorboard_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except ImportError:
                logger.warning("tensorboard not available; disabling TensorBoardMonitor")
                self.enabled = False

    def write_events(self, event_list, flush: bool = True) -> None:
        if not self.enabled or self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self.enabled = wandb_config.enabled and dist.get_rank() == 0
        if self.enabled:
            try:
                import wandb

                self._wandb = wandb
                wandb.init(project=wandb_config.project, group=wandb_config.group, entity=wandb_config.team)
            except ImportError:
                logger.warning("wandb not available; disabling WandbMonitor")
                self.enabled = False

    def write_events(self, event_list) -> None:
        if not self.enabled:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class csvMonitor(Monitor):
    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.enabled = csv_config.enabled and dist.get_rank() == 0
        self.filenames = {}
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, event_list) -> None:
        if not self.enabled:
            return
        import csv

        for name, value, step in event_list:
            safe = name.replace("/", "_")
            fname = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", safe])
                w.writerow([step, value])


class MonitorMaster(Monitor):
    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard)
        self.wandb_monitor = WandbMonitor(monitor_config.wandb)
        self.csv_monitor = csvMonitor(monitor_config.csv_monitor)
        self.enabled = self.tb_monitor.enabled or self.wandb_monitor.enabled or self.csv_monitor.enabled

    def write_events(self, event_list) -> None:
        for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            if m.enabled:
                m.write_events(event_list)
