"""Monitoring backends.

Counterpart of ``deepspeed/monitor/`` (``MonitorMaster`` monitor.py:29 fanning
out ``write_events`` to TensorBoard / W&B / CSV), wired to the unified
observability plane (ISSUE 10):

* every backend consumes the same ``(name, value, step)`` event tuples;
* :class:`JSONLMonitor` is the torch-free, always-available backend — one
  JSON line per event in an append-only file (torn tails are harmless to
  line-wise readers) — and is **default-ON at rank 0** whenever the
  ``monitor`` config block's master switch is set;
* TensorBoard / W&B stay optional imports that degrade to disabled with a
  warning, exactly as before;
* :class:`MonitorMaster` fans one ``write_events`` call out to every
  enabled backend. The training engine feeds it the loss/lr events plus
  the observability hub's periodic metric events
  (``ObservabilityHub.monitor_events``) on the configured cadence.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Tuple

from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.utils.logging import logger


class Monitor:
    def __init__(self, monitor_config):
        self.monitor_config = monitor_config
        self.enabled = False

    def write_events(self, event_list: List[Tuple]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.summary_writer = None
        self.enabled = tensorboard_config.enabled and dist.get_rank() == 0
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter

                log_dir = os.path.join(tensorboard_config.output_path or "./runs", tensorboard_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except ImportError:
                logger.warning("tensorboard not available; disabling TensorBoardMonitor")
                self.enabled = False

    def write_events(self, event_list, flush: bool = True) -> None:
        if not self.enabled or self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self.enabled = wandb_config.enabled and dist.get_rank() == 0
        if self.enabled:
            try:
                import wandb

                self._wandb = wandb
                wandb.init(project=wandb_config.project, group=wandb_config.group, entity=wandb_config.team)
            except ImportError:
                logger.warning("wandb not available; disabling WandbMonitor")
                self.enabled = False

    def write_events(self, event_list) -> None:
        if not self.enabled:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class csvMonitor(Monitor):
    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.enabled = csv_config.enabled and dist.get_rank() == 0
        self.filenames = {}
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, event_list) -> None:
        if not self.enabled:
            return
        import csv

        for name, value, step in event_list:
            safe = name.replace("/", "_")
            fname = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", safe])
                w.writerow([step, value])


class JSONLMonitor(Monitor):
    """Torch-free structured backend: every event is one JSON line
    (``{"name", "value", "step", "t"}``) appended to
    ``output_path/job_name/events.jsonl``. Append-mode by design — a kill
    mid-write tears at most the last line, which line-wise readers skip.
    ``force`` bypasses the master-switch gate (tests / direct use)."""

    def __init__(self, jsonl_config, master_enabled: bool = True, force: bool = False):
        super().__init__(jsonl_config)
        self.enabled = (
            jsonl_config.enabled
            and (master_enabled or force)
            and dist.get_rank() == 0
        )
        self.output_path = jsonl_config.output_path or "./ds_monitor"
        self.job_name = jsonl_config.job_name
        self._path = os.path.join(self.output_path, self.job_name, "events.jsonl")
        if self.enabled:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)

    @property
    def path(self) -> str:
        return self._path

    def write_events(self, event_list) -> None:
        if not self.enabled:
            return
        now = time.time()
        with open(self._path, "a", encoding="utf-8") as f:
            for name, value, step in event_list:
                f.write(
                    json.dumps(
                        {"name": name, "value": float(value), "step": int(step), "t": now}
                    )
                    + "\n"
                )


class MonitorMaster(Monitor):
    """Fanout over every enabled backend (reference monitor.py:29). The
    JSONL backend activates with the ``monitor`` block's master switch;
    TensorBoard / W&B / CSV follow their own enabled flags (legacy
    top-level keys keep working)."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        master_on = bool(getattr(monitor_config, "enabled", False))
        self.jsonl_monitor = JSONLMonitor(monitor_config.jsonl, master_enabled=master_on)
        self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard)
        self.wandb_monitor = WandbMonitor(monitor_config.wandb)
        self.csv_monitor = csvMonitor(monitor_config.csv_monitor)
        self.backends = [
            self.jsonl_monitor,
            self.tb_monitor,
            self.wandb_monitor,
            self.csv_monitor,
        ]
        self.enabled = any(m.enabled for m in self.backends)

    def write_events(self, event_list) -> None:
        for m in self.backends:
            if m.enabled:
                m.write_events(event_list)
