from deepspeed_tpu.monitor.monitor import (  # noqa: F401
    JSONLMonitor,
    MonitorMaster,
    TensorBoardMonitor,
    WandbMonitor,
    csvMonitor,
)
