"""Device-mesh topology.

TPU-native counterpart of the reference's process-group topology layer
(``deepspeed/utils/groups.py:51-528`` and
``deepspeed/runtime/pipe/topology.py:12`` ``ProcessTopology``): instead of
materializing NCCL communicators per group, we build one
``jax.sharding.Mesh`` whose named axes *are* the groups, and every collective
is expressed against an axis name.

Axis layout (outer→inner): ``pipe, data, expert, sequence, model``.

* dense data-parallel (and ZeRO sharding) runs over the **combined**
  ``(data, expert)`` axes — the reference's ``expert_data_parallel`` group —
  so MoE with ``expert>1`` regroups part of DP into EP exactly like
  ``groups._create_expert_and_data_parallel`` (groups.py:113).
* ``model`` is innermost so TP collectives ride the shortest ICI hops;
  ``pipe`` is outermost so stage boundaries cross the slowest links only
  once per microbatch.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.utils.logging import logger

# canonical axis order, outermost first
AXIS_ORDER: Tuple[str, ...] = ("pipe", "data_outer", "data", "expert", "sequence", "model")

_TOPOLOGY: Optional["Topology"] = None


class Topology:
    """A named-axis device mesh + the reference's group-accessor surface."""

    def __init__(self, mesh, mesh_config: MeshConfig):
        self.mesh = mesh
        self.config = mesh_config

    # --- world sizes (reference groups.py accessors) -------------------
    def get_data_parallel_world_size(self) -> int:
        """Dense DP world = data_outer × data × expert (the expert_data group)."""
        return self.config.data_outer * self.config.data * self.config.expert

    def get_expert_parallel_world_size(self) -> int:
        return self.config.expert

    def get_expert_data_parallel_world_size(self) -> int:
        return self.config.data

    def get_model_parallel_world_size(self) -> int:
        return self.config.model

    def get_sequence_parallel_world_size(self) -> int:
        return self.config.sequence

    def get_sequence_data_parallel_world_size(self) -> int:
        return self.config.sequence * self.get_data_parallel_world_size()

    def get_pipe_parallel_world_size(self) -> int:
        return self.config.pipe

    @property
    def world_size(self) -> int:
        return int(np.prod([
            self.config.pipe, self.config.data_outer, self.config.data,
            self.config.expert, self.config.sequence, self.config.model,
        ]))

    # --- axis-name groups ----------------------------------------------
    @property
    def data_parallel_axes(self) -> Tuple[str, ...]:
        """Axes a dense gradient reduction runs over (includes sequence: each
        sequence shard sees a slice of the batch's tokens, so grads reduce over
        seq too — mirroring the reference's seq_data group, engine.py:1111)."""
        axes = ["data"]
        if self.config.data_outer > 1:
            axes.insert(0, "data_outer")
        if self.config.expert > 1:
            axes.append("expert")
        if self.config.sequence > 1:
            axes.append("sequence")
        return tuple(axes)

    @property
    def zero_shard_axes(self) -> Tuple[str, ...]:
        """Axes ZeRO partitions params/opt-state over: the dense DP axes
        MINUS the MiCS replication axis — with data_outer > 1, state shards
        only within each sub-group and replicates across groups
        (reference mics.py shard-group semantics)."""
        return tuple(a for a in self.data_parallel_axes if a != "data_outer")

    @property
    def expert_parallel_axis(self) -> str:
        return "expert"

    @property
    def model_parallel_axis(self) -> str:
        return "model"

    @property
    def sequence_parallel_axis(self) -> str:
        return "sequence"

    @property
    def pipe_parallel_axis(self) -> str:
        return "pipe"

    def axis_size(self, name: str) -> int:
        return dict(zip(self.mesh.axis_names, self.mesh.devices.shape))[name]

    def dense_batch_axes(self):
        """Mesh axes the batch's leading dim is sharded over, normalized to
        None | str | tuple — the single source for batch PartitionSpec entries
        (used by the engine's batch placement and the SP attention specs)."""
        axes = tuple(a for a in ("data_outer", "data", "expert") if self.axis_size(a) > 1)
        if not axes:
            return None
        if len(axes) == 1:
            return axes[0]
        return axes


def build_mesh(
    mesh_config: MeshConfig,
    devices: Optional[List] = None,
) -> Topology:
    """Create the global Mesh from resolved axis sizes.

    Uses ``mesh_utils.create_device_mesh`` so the logical axes map onto the
    physical ICI torus (innermost logical axis → nearest neighbors).
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    resolved = mesh_config.resolve(n)
    shape = (
        resolved.pipe,
        resolved.data_outer,
        resolved.data,
        resolved.expert,
        resolved.sequence,
        resolved.model,
    )
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception as e:  # fallback: row-major reshape (CPU meshes, odd shapes)
        logger.debug(f"create_device_mesh failed ({e}); falling back to reshape")
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    return Topology(mesh, resolved)


def build_serving_mesh(tp_degree: int, devices: Optional[List] = None) -> Topology:
    """Full-world topology with a ``model=tp_degree`` axis (innermost — TP
    all-reduces ride the shortest ICI hops), everything else folded into
    ``data``. ``InferenceEngine.__init__`` re-meshes through this when
    ``tensor_parallel.tp_size`` asks for a model axis the live topology
    does not have (it drives the dense AutoTP forward/generate path). The
    PAGED serving programs instead run on a compact 1-D submesh of the
    first ``tp_degree`` devices (``inference/tp.py:serving_mesh``) — one
    TP group; the devices this topology folds into ``data`` are the fleet
    layer's replica budget."""
    import jax

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if tp_degree < 1 or n % tp_degree:
        raise ValueError(
            f"tp_size={tp_degree} must be >= 1 and divide the {n} visible devices"
        )
    return build_mesh(MeshConfig(model=tp_degree, data=n // tp_degree), devices)


def initialize_topology(mesh_config: Optional[MeshConfig] = None, devices=None) -> Topology:
    global _TOPOLOGY
    _TOPOLOGY = build_mesh(mesh_config or MeshConfig(), devices)
    return _TOPOLOGY


def get_topology() -> Topology:
    if _TOPOLOGY is None:
        return initialize_topology()
    return _TOPOLOGY


def set_topology(topology: Optional[Topology]) -> None:
    global _TOPOLOGY
    _TOPOLOGY = topology


def reset_topology() -> None:
    set_topology(None)
