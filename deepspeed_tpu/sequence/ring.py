"""Ring attention over the ``sequence`` mesh axis.

Extension beyond reference parity (SURVEY §2.3: the reference has no
ring/context-parallel implementation — long context is Ulysses only). Ring
attention removes Ulysses' head-count ceiling (sp ≤ num_heads) by keeping
heads whole and rotating K/V shards around the ICI ring with ``ppermute``
while every device accumulates online-softmax partial results for its local
query block (Liu et al., "Ring Attention with Blockwise Transformers").

Written for ``shard_map`` over the ``sequence`` axis; ``ring_attention``
wraps itself in shard_map when given a mesh. The per-step local block runs
as one fp32 einsum — block sizes are seq_len/sp per device, so XLA tiles it
onto the MXU directly; each ppermute overlaps with the next block's compute
(XLA schedules the rotation concurrently since the permuted buffer is not
needed until the following iteration).

Causality is handled with global-position masks derived from
``lax.axis_index``: a device's q block i attends fully to kv blocks j < i,
causally within j == i, and skips j > i (the mask drives exp() to zero; the
accumulator's running max keeps it stable). Differentiable by construction
(unrolled over sp steps; ppermute transposes to the reverse permutation).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Body run per-device inside shard_map.

    q: [B, t, NH, D]; k/v: [B, t, NKV, D] with NH = G·NKV (GQA) — kv stays
    at NKV heads so each ppermute hop moves only the grouped-kv bytes.
    """
    sp = jax.lax.psum(1, axis_name)  # static: mesh axis size
    idx = jax.lax.axis_index(axis_name)
    B, t, NH, D = q.shape
    NKV = k.shape[2]
    G = NH // NKV
    qf = q.astype(jnp.float32).reshape(B, t, NKV, G, D)

    local_pos = jnp.arange(t, dtype=jnp.int32)
    q_pos = idx * t + local_pos  # global positions of this q block

    m = jnp.full((B, t, NKV, G), NEG_INF, jnp.float32)
    l = jnp.zeros((B, t, NKV, G), jnp.float32)
    acc = jnp.zeros((B, t, NKV, G, D), jnp.float32)

    perm = [(r, (r + 1) % sp) for r in range(sp)]

    k_cur, v_cur = k, v
    for step in range(sp):
        j = (idx - step) % sp  # whose kv block we hold this step
        s = jnp.einsum("btkgd,bskd->btkgs", qf, k_cur.astype(jnp.float32)) * scale
        if causal:
            kv_pos = j * t + local_pos
            mask = (q_pos[:, None] >= kv_pos[None, :])[None, :, None, None, :]  # [1,t,1,1,t]
            s = jnp.where(mask, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        # fully-masked rows keep m == NEG_INF; subtracting it from NEG_INF
        # scores must still yield exp(0)=...=0, so clamp the shift.
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - shift))
        l = corr * l + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "btkgs,bskd->btkgd", p, v_cur.astype(jnp.float32)
        )
        m = m_new
        if step != sp - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l[..., None]).astype(q.dtype).reshape(B, t, NH, D)


def ring_attention(
    q,
    k,
    v,
    *,
    mesh=None,
    axis_name: str = "sequence",
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axes=None,
    head_axes=None,
    in_shard_map: bool = False,
):
    """Ring attention for [B, T, N, D] q/k/v sequence-sharded over ``axis_name``.

    With ``in_shard_map=True`` the inputs are per-device local shards and the
    caller is already inside a shard_map over ``axis_name``. Otherwise global
    arrays are expected and this wraps the body in shard_map over ``mesh``
    (default: the global topology's mesh).
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    body = partial(_ring_attention_local, axis_name=axis_name, causal=causal, scale=float(scale))
    if in_shard_map:
        return body(q, k, v)

    if mesh is None:
        from deepspeed_tpu.parallel.mesh import get_topology

        mesh = get_topology().mesh
    spec = P(batch_axes, axis_name, head_axes, None)
    from deepspeed_tpu.utils.jax_compat import shard_map as _shard_map_fn

    smap = partial(_shard_map_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return smap(body)(q, k, v)
