"""Sequence parallelism (DeepSpeed-Ulysses) + ring-attention extension.

Reference: ``deepspeed/sequence/`` (layer.py — DistributedAttention,
_SeqAllToAll). Ring attention has no reference counterpart (SURVEY §2.3) and
is provided as the TPU-native long-context extension.
"""

from deepspeed_tpu.sequence.layer import DistributedAttention, UlyssesAttention, seq_all_to_all
from deepspeed_tpu.sequence.ring import ring_attention

__all__ = ["DistributedAttention", "UlyssesAttention", "seq_all_to_all", "ring_attention"]
