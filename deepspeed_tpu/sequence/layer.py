"""DeepSpeed-Ulysses sequence parallelism, TPU-native.

Reference: ``deepspeed/sequence/layer.py`` — ``_SeqAllToAll`` (:15) swaps a
sequence-sharded activation to a head-sharded one with a single all-to-all
over the sequence process group, ``DistributedAttention`` (:37) wraps any
local attention with that swap before and its inverse after (:61-85).

On TPU the same dataflow is expressed two ways, both provided here:

* **GSPMD flavor** (`DistributedAttention`, used inside ``jit``): the swap is
  a ``with_sharding_constraint`` from ``P(..., 'sequence', heads, ...)`` to
  ``P(..., None, ('sequence', heads...), ...)``; XLA lowers the resharding to
  exactly one all-to-all over the ICI ring, and fuses it with neighboring
  ops. No manual communication code, and the collective overlaps with
  compute wherever XLA's scheduler finds room.

* **shard_map flavor** (`seq_all_to_all`): explicit ``lax.all_to_all`` with
  the reference's (scatter_idx, gather_idx) signature, for code already
  inside a ``shard_map`` region (e.g. the pipeline engine's stages).

Composition with ZeRO mirrors the reference: the engine's batch spec shards
tokens over the ``sequence`` axis and gradients reduce over seq×data
(``parallel/mesh.py`` ``data_parallel_axes``), matching engine.py:1111's
seq_data group.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def seq_all_to_all(x, scatter_idx: int, gather_idx: int, axis_name: str = "sequence"):
    """Explicit all-to-all for shard_map regions (reference ``_SeqAllToAll``,
    deepspeed/sequence/layer.py:15).

    Scatters local dim ``scatter_idx`` across the axis and gathers the
    (sharded) dim ``gather_idx``: [.., S, .., h/p, ..] ↔ [.., s/p, .., H, ..].
    Differentiable — the transpose of an all-to-all is the inverse
    all-to-all, which JAX derives automatically.
    """
    return jax.lax.all_to_all(x, axis_name, split_axis=scatter_idx, concat_axis=gather_idx, tiled=True)


def _spec_with(entries) -> P:
    return P(*entries)


class DistributedAttention:
    """Ulysses wrapper around any local attention (GSPMD flavor).

    ``local_attn(q, k, v, *args, **kwargs) -> out`` operates on
    ``[B, T, N, D]`` arrays that carry the full sequence but a head shard;
    this wrapper accepts arrays logically sharded ``[B, T/sp, N, D]`` and
    performs the two all-to-alls via resharding constraints.

    Reference: ``DistributedAttention`` deepspeed/sequence/layer.py:37
    (scatter_idx=2 → heads, gather_idx=1 → sequence, matching the
    [B, T, N, D] layout used throughout this framework).
    """

    def __init__(
        self,
        local_attn: Callable,
        mesh=None,
        *,
        seq_axis: str = "sequence",
        head_axes: Union[str, Tuple[str, ...], None] = None,
        batch_axes: Union[str, Tuple[str, ...], None] = None,
        scatter_idx: int = 2,
        gather_idx: int = 1,
    ):
        self.local_attn = local_attn
        self.mesh = mesh
        self.seq_axis = seq_axis
        self.head_axes = head_axes
        self.batch_axes = batch_axes
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def _mesh(self):
        if self.mesh is not None:
            return self.mesh
        from deepspeed_tpu.parallel.mesh import get_topology

        return get_topology().mesh

    def _specs(self, ndim: int) -> Tuple[P, P]:
        """(seq-sharded spec, head-sharded spec) for an ndim-rank array."""
        entries_seq = [None] * ndim
        entries_head = [None] * ndim
        entries_seq[0] = entries_head[0] = self.batch_axes
        entries_seq[self.gather_idx] = self.seq_axis
        head = self.head_axes
        if head is None:
            combined = (self.seq_axis,)
        elif isinstance(head, str):
            combined = (self.seq_axis, head)
            entries_seq[self.scatter_idx] = head
        else:
            combined = (self.seq_axis, *head)
            entries_seq[self.scatter_idx] = tuple(head)
        entries_head[self.scatter_idx] = combined
        return _spec_with(entries_seq), _spec_with(entries_head)

    def __call__(self, query, key, value, *args, **kwargs):
        mesh = self._mesh()
        if mesh.shape.get(self.seq_axis, 1) == 1:
            return self.local_attn(query, key, value, *args, **kwargs)
        seq_spec, head_spec = self._specs(query.ndim)

        def cst(x, spec):
            return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

        # seq-shard → head-shard: one all-to-all each (layer.py:61-66)
        q = cst(cst(query, seq_spec), head_spec)
        k = cst(cst(key, seq_spec), head_spec)
        v = cst(cst(value, seq_spec), head_spec)
        out = self.local_attn(q, k, v, *args, **kwargs)
        # head-shard → seq-shard: the inverse all-to-all (layer.py:79-85)
        return cst(cst(out, head_spec), seq_spec)


class UlyssesAttention(DistributedAttention):
    """Alias matching the blog/API name (blogs/deepspeed-ulysses)."""
