"""Static program analysis for the engine's compiled programs + repo source.

Two layers:

* **Program passes** (``passes.py``) run over the lowered/compiled form of
  the engine's jitted programs — donation-aliasing verification, dtype-
  promotion audit, host-transfer detection, static collective schedule —
  rebuilt from the abstract signatures compile telemetry records at each
  cold dispatch. ``run_program_passes`` aggregates them into the report
  both engines expose as ``analysis_report()``; the ``analysis.verify``
  config knob runs them at first compile (warn or raise).
* **Source lint** (``source_lint.py``, CLI: ``tools/lint.py``) is an AST
  pass over the repo encoding python-level hazards (repeat-on-cache, host
  syncs inside jit, shape branches, undonated buffers).

``memory.py`` adds the static HBM layer on top of both: a per-program
peak-HBM estimator, a sharding auditor, and the whole-run residency
ledger behind ``engine.memory_report()`` / ``analysis.hbm_budget_bytes``.
"""

from .passes import (  # noqa: F401
    PROGRAM_PASSES,
    AnalysisError,
    PassResult,
    ProgramArtifact,
    Violation,
    analyze_program,
    collectives_pass,
    donation_pass,
    dtype_promotion_pass,
    find_aval_shapes,
    host_transfer_pass,
    iter_eqns,
    overlap_pass,
)
from .memory import (  # noqa: F401
    HbmBudgetError,
    MemoryLedger,
    audit_sharding,
    estimate_program_memory,
    memory_pass,
    tree_device_bytes,
)
from .report import (  # noqa: F401
    diff_trace_signatures,
    engine_analysis_report,
    format_violations,
    raise_or_warn,
    run_program_passes,
    verify_program,
)
from .source_lint import LintFinding, lint_paths, lint_source  # noqa: F401
