"""Aggregate analysis over a compile-telemetry registry.

``run_program_passes`` is the single entry point both engines expose as
``analysis_report()``: for every instrumented program that has dispatched
at least once (so its abstract signature is on record), run the selected
program passes and fold the results — plus retrace-cause diffs from the
telemetry trace log — into one report dict that sits next to
``compile_stats()`` in monitors, benches, and tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from .passes import AnalysisError, analyze_program


def diff_trace_signatures(
    before: Dict[str, Any], after: Dict[str, Any]
) -> List[Dict[str, Any]]:
    """Name the arguments whose abstract signature changed between two
    traces of the same program — the answer to "why did this retrace?".
    Inputs are ``describe_signature`` dicts from a ProgramStats trace log."""
    diffs: List[Dict[str, Any]] = []
    for key in sorted(set(before) | set(after)):
        a, b = before.get(key), after.get(key)
        if a == b:
            continue
        if a is None:
            reason = "added"
        elif b is None:
            reason = "removed"
        elif a.get("shape") != b.get("shape"):
            reason = "shape"
        elif a.get("dtype") != b.get("dtype"):
            reason = "dtype"
        elif a.get("sharding") != b.get("sharding"):
            reason = "sharding"
        elif "value" in a or "value" in b:
            reason = "static_value"
        else:
            reason = "changed"
        diffs.append({"arg": key, "reason": reason, "before": a, "after": b})
    return diffs


def _retrace_causes(stats) -> List[Dict[str, Any]]:
    log = getattr(stats, "trace_log", None) or []
    causes = []
    for i in range(1, len(log)):
        causes.append(
            {
                "trace": i,
                "changed": diff_trace_signatures(log[i - 1], log[i]),
            }
        )
    return causes


def run_program_passes(
    telemetry,
    programs: Optional[Sequence[str]] = None,
    passes: Optional[Sequence[str]] = None,
    config: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Run program passes over every (or the named) dispatched program in a
    ``CompileTelemetry`` registry. Never raises on a broken program build —
    the failure lands under that program's ``"error"`` key so one
    unanalyzable program cannot hide the rest."""
    available = telemetry.programs()
    if programs is None:
        selected = {
            name: fn
            for name, fn in available.items()
            if fn.abstract_signature is not None
        }
    else:
        # explicitly-requested names must never vanish silently: an unknown
        # name lands as a failed entry (None wrapper) so the caller cannot
        # read "verified" off a typo'd or not-yet-built program
        selected = {name: available.get(name) for name in programs}

    report: Dict[str, Any] = {"programs": {}, "totals": {}}
    n_err = n_warn = n_failed = 0
    donation_ok = True
    donation_ran = False  # verified means the pass RAN clean, not "not run"
    # a report that never had donation in scope stays None throughout —
    # even its failure entries must not flip a flag nobody asked about
    donation_selected = passes is None or "donation" in passes
    overlap_selected = passes is None or "overlap" in passes
    overlap_ok = True
    overlap_ran = False
    hidden_bytes = exposed_bytes = 0
    # host-stream accounting (ZeRO-Infinity offload): only the anchor
    # program carries stream summaries, so "ran" flips on first sighting
    stream_ok = True
    stream_ran = False
    stream_h2d = stream_d2h = stream_exposed = 0
    coll_ops: Dict[str, Dict[str, int]] = {}
    coll_bytes = coll_count = 0
    # static HBM accounting: the per-chip peak is the largest single
    # program (programs dispatch one at a time), replicated bytes likewise
    memory_ok = True
    memory_ran = False
    peak_hbm = replicated = 0
    undeclared_colls = 0

    for name in sorted(selected):
        fn = selected[name]
        entry: Dict[str, Any] = {"passes": {}}
        stats = telemetry.program_stats(name)
        if stats is not None:
            entry["retraces"] = _retrace_causes(stats)
        if fn is None or fn.abstract_signature is None:
            entry["error"] = (
                "no such instrumented program"
                if fn is None
                else "never dispatched: no captured signature"
            )
            n_failed += 1
            if donation_selected:
                donation_ok = False  # requested but unanalyzable ≠ verified
                donation_ran = True
            if overlap_selected:
                overlap_ok = False
                overlap_ran = True
            report["programs"][name] = entry
            continue
        try:
            results = analyze_program(name, fn, passes=passes, config=config)
        except Exception as e:  # artifact build failed (trace/compile error)
            entry["error"] = f"{type(e).__name__}: {e}"
            n_failed += 1
            if donation_selected:
                donation_ok = False  # unanalyzable ≠ verified
                donation_ran = True
            if overlap_selected:
                overlap_ok = False
                overlap_ran = True
            report["programs"][name] = entry
            continue
        for pname, res in results.items():
            entry["passes"][pname] = res.as_dict()
            for v in res.violations:
                if v.severity == "error":
                    n_err += 1
                else:
                    n_warn += 1
            if pname == "donation":
                donation_ran = True
                if not res.ok:
                    donation_ok = False
            if pname == "overlap":
                overlap_ran = True
                if not res.summary.get("overlap_verified", False):
                    overlap_ok = False
                hidden_bytes += res.summary.get("hidden_bytes", 0)
                exposed_bytes += res.summary.get("exposed_bytes", 0)
                if "stream_transfers" in res.summary:
                    stream_ran = True
                    stream_h2d += res.summary.get("stream_h2d_bytes", 0)
                    stream_d2h += res.summary.get("stream_d2h_bytes", 0)
                    stream_exposed += res.summary.get("exposed_stream_bytes", 0)
                    if not res.summary.get("stream_verified", False):
                        stream_ok = False
            if pname == "memory":
                memory_ran = True
                if not res.ok:
                    memory_ok = False
                est = res.summary.get("estimate", {})
                peak_hbm = max(peak_hbm, est.get("peak_hbm_bytes", 0))
                shard = res.summary.get("sharding", {})
                replicated = max(replicated, shard.get("replicated_bytes", 0))
                undeclared_colls += len(
                    shard.get("undeclared_collectives", ())
                )
            if pname == "collectives":
                for op, rec in res.summary.get("ops", {}).items():
                    agg = coll_ops.setdefault(op, {"count": 0, "bytes": 0})
                    agg["count"] += rec["count"]
                    agg["bytes"] += rec["bytes"]
                coll_bytes += res.summary.get("total_bytes", 0)
                coll_count += res.summary.get("total_count", 0)
        report["programs"][name] = entry

    report["totals"] = {
        "programs": len(report["programs"]),
        "violations": n_err,
        "warnings": n_warn,
        "analysis_failures": n_failed,
        # None (not True) when the donation pass never ran: a report built
        # from passes=["collectives"] must not read as donation-verified
        "donation_verified": donation_ok if donation_ran else None,
        # same tri-state contract: None when the overlap pass never ran
        "overlap_verified": overlap_ok if overlap_ran else None,
        "hidden_collective_bytes": hidden_bytes,
        "exposed_collective_bytes": exposed_bytes,
        # tri-state again: None unless a declared offload stream schedule
        # reached its anchor program this report
        "stream_verified": stream_ok if stream_ran else None,
        "stream_h2d_bytes": stream_h2d,
        "stream_d2h_bytes": stream_d2h,
        "exposed_stream_bytes": stream_exposed,
        "collective_count": coll_count,
        "collective_bytes": coll_bytes,
        "collectives": coll_ops,
        # tri-state like the others: None unless the memory pass ran
        "memory_verified": memory_ok if memory_ran else None,
        "peak_hbm_bytes_per_chip": peak_hbm,
        "replicated_bytes": replicated,
        "undeclared_collectives": undeclared_colls,
    }
    return report


def engine_analysis_report(
    telemetry,
    analysis_config,
    programs: Optional[Sequence[str]] = None,
    passes: Optional[Sequence[str]] = None,
    extra_config: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The one implementation behind BOTH engines' ``analysis_report()``:
    apply the config's pass narrowing + thresholds to
    ``run_program_passes``. ``analysis_config`` is an ``AnalysisConfig``
    (training or inference — same model). ``extra_config`` carries
    engine-declared pass inputs the static config cannot know — e.g. the
    ZeRO-Infinity ``offload_stream`` schedule for the overlap pass."""
    if passes is None and analysis_config.passes:
        passes = list(analysis_config.passes)
    config = {
        "min_donation_bytes": analysis_config.min_donation_bytes,
        "collective_budget_bytes": analysis_config.collective_budget_bytes,
        "stream_budget_bytes": getattr(analysis_config, "stream_budget_bytes", None),
        "hbm_budget_bytes": getattr(analysis_config, "hbm_budget_bytes", None),
        "hbm_budget": getattr(analysis_config, "hbm_budget", "raise"),
    }
    if extra_config:
        config.update(extra_config)
    return run_program_passes(
        telemetry,
        programs=programs,
        passes=passes,
        config=config,
    )


def verify_program(
    telemetry, analysis_config, name: str, logger=None, extra_config=None
) -> None:
    """analysis.verify hook body shared by both engines: run the passes on
    one freshly compiled program, then warn or raise per the config."""
    report = engine_analysis_report(
        telemetry, analysis_config, programs=[name], extra_config=extra_config
    )
    raise_or_warn(report, analysis_config.verify, logger=logger)


def format_violations(report: Dict[str, Any]) -> str:
    """Human-readable one-line-per-violation rendering of a report."""
    lines = []
    for name, entry in report.get("programs", {}).items():
        if entry.get("error"):
            lines.append(f"{name}: analysis failed: {entry['error']}")
        for pname, pres in entry.get("passes", {}).items():
            for v in pres.get("violations", []):
                lines.append(
                    f"{name}: [{pname}/{v.get('severity', 'error')}] {v.get('message')}"
                )
    return "\n".join(lines)


def raise_or_warn(report: Dict[str, Any], mode: str, logger=None) -> None:
    """``analysis.verify`` enforcement: ``raise`` on any error-severity
    violation OR analysis failure (a program the passes could not even
    build — a typo'd pass name, an XLA drift breaking the re-trace — must
    not silently disable the fail-fast gate), else log a warning when
    anything was found."""
    msg = format_violations(report)
    if not msg:
        return
    totals = report["totals"]
    if mode == "raise" and (
        totals.get("violations", 0) > 0 or totals.get("analysis_failures", 0) > 0
    ):
        raise AnalysisError("static analysis failed:\n" + msg)
    if logger is not None:
        logger.warning("static analysis findings:\n%s", msg)
