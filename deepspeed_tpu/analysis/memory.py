"""Static HBM ledger & sharding auditor (ISSUE 18).

Every recent tentpole made a memory claim the analysis layer could not
check: the streamed ZeRO-Infinity offload promises ~2-bucket device
residency, tensor-parallel serving promises KV pools sharded per chip with
only host-side page tables replicated — and PR 12's review caught, by
hand, a transient whole-pool-on-one-chip allocation. This module turns
those claims into statically verified invariants, three layers deep:

* :func:`estimate_program_memory` — per-program peak-HBM estimate. The
  executable's own ``memory_analysis()`` is preferred when the backend
  provides it (argument/output/temp/alias bytes straight from the buffer
  assignment); otherwise an optimized-HLO buffer walk reconstructs the
  same accounting from the ENTRY parameter/result shapes with donation
  aliases deduplicated via the ``input_output_alias`` table the donation
  pass already parses. Shapes in optimized SPMD HLO are per-partition, so
  every number is bytes **per chip**. On backends whose buffer assignment
  reports no temporaries (the CPU test backend) the estimate is a lower
  bound — PERF.md's memory-ledger round carries the disclaimer.
* :func:`audit_sharding` — per-buffer per-chip bytes from the sharding
  annotations of the program's captured abstract call signature, flagging
  (a) large leaves left fully replicated on a multi-chip mesh when a
  declared sharding rule says they shard, and (b) collective op kinds in
  the compiled module that the engine's declared comm schedule does not
  contain — the pjit-inserted resharding all-gathers that silently
  re-materialize a sharded buffer whole.
* :class:`MemoryLedger` — whole-run residency aggregation across the
  engine's persistent buffers (params, optimizer state, paged KV pools,
  offload device buckets — device or host resident) plus the live
  programs' transient footprints, surfaced as ``engine.memory_report()``
  and gated by ``analysis.hbm_budget_bytes`` (``off|warn|raise`` via
  ``analysis.hbm_budget``, like ``analysis.verify``). An over-budget
  ledger raises :class:`HbmBudgetError` with per-buffer attribution.

``memory_pass`` registers the estimator + auditor as the ``"memory"``
program pass: with no budget/rules/declared-schedule configured it is
summary-only (zero violations), so existing green sweeps stay green.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import hlo as hlo_parse
from .passes import (
    PROGRAM_PASSES,
    AnalysisError,
    PassResult,
    ProgramArtifact,
    Violation,
)


class HbmBudgetError(AnalysisError):
    """Raised by ``analysis.hbm_budget: raise`` when the residency ledger's
    per-chip peak exceeds ``analysis.hbm_budget_bytes``. The message
    carries per-buffer attribution (largest entries first)."""


def _nbytes(shape: Sequence[int], dtype) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    try:
        return n * int(np.dtype(dtype).itemsize)
    except Exception:
        return n * 4


# ---------------------------------------------------------------------------
# per-program peak-HBM estimator
# ---------------------------------------------------------------------------
def estimate_program_memory(art: ProgramArtifact) -> Dict[str, Any]:
    """Peak-HBM estimate (bytes per chip) for one compiled program.

    ``peak_hbm_bytes = argument + output + temp - alias``: aliased outputs
    (honored donations) reuse their argument's buffer, so they are counted
    once. ``source`` says which accounting produced the numbers —
    ``"memory_analysis"`` (the executable's buffer assignment) or
    ``"hlo_walk"`` (text fallback, ``temp_bytes`` unknowable → 0, making
    the estimate a lower bound)."""
    stats = None
    try:
        stats = art.compiled.memory_analysis()
    except Exception:
        stats = None
    if stats is not None:
        try:
            arg = int(stats.argument_size_in_bytes)
            out = int(stats.output_size_in_bytes)
            tmp = int(stats.temp_size_in_bytes)
            alias = int(stats.alias_size_in_bytes)
            return {
                "source": "memory_analysis",
                "argument_bytes": arg,
                "output_bytes": out,
                "temp_bytes": tmp,
                "alias_bytes": alias,
                "generated_code_bytes": int(
                    getattr(stats, "generated_code_size_in_bytes", 0) or 0
                ),
                "peak_hbm_bytes": max(arg + out + tmp - alias, 0),
            }
        except Exception:
            pass
    # optimized-HLO buffer walk: ENTRY parameter shapes are the argument
    # buffers, the ENTRY result shape the outputs, and the header's
    # input_output_alias table (the donation pass's machinery) names the
    # parameters whose bytes the outputs reuse
    text = art.hlo_text
    params = hlo_parse.entry_parameter_shapes(text)
    arg = sum(hlo_parse.shape_list_bytes(s) for s in params.values())
    result = hlo_parse.entry_result_shape(text)
    out = hlo_parse.shape_list_bytes(result) if result else 0
    aliased = hlo_parse.parse_input_output_aliases(text)
    alias = sum(
        hlo_parse.shape_list_bytes(params[i]) for i in aliased if i in params
    )
    return {
        "source": "hlo_walk",
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": 0,  # not recoverable from text: lower bound
        "alias_bytes": alias,
        "generated_code_bytes": 0,
        "peak_hbm_bytes": max(arg + out - alias, 0),
    }


# ---------------------------------------------------------------------------
# sharding auditor
# ---------------------------------------------------------------------------
def _signature_buffers(art: ProgramArtifact) -> List[Dict[str, Any]]:
    """Flat per-argument buffer records from the program's captured
    abstract call signature: arg path, global/per-chip bytes, and whether
    the leaf's DECLARED sharding leaves it fully replicated on a
    multi-chip placement. Leaves without a sharding (uncommitted host
    arrays jit replicates at dispatch) report ``devices=None``."""
    sig = getattr(art._wrapper, "abstract_signature", None)
    if sig is None:
        return []
    flat, _ = jax.tree_util.tree_flatten_with_path(sig)
    out: List[Dict[str, Any]] = []
    for path, leaf in flat:
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            continue
        shape = tuple(leaf.shape)
        total = _nbytes(shape, leaf.dtype)
        sharding = getattr(leaf, "sharding", None)
        per_chip = total
        devices = None
        replicated = False
        if sharding is not None:
            try:
                devices = int(sharding.num_devices)
                per_chip = _nbytes(sharding.shard_shape(shape), leaf.dtype)
                replicated = devices > 1 and per_chip == total
            except Exception:
                devices = None
        out.append(
            {
                "arg": jax.tree_util.keystr(path),
                "shape": shape,
                "dtype": str(leaf.dtype),
                "global_bytes": total,
                "per_chip_bytes": per_chip,
                "devices": devices,
                "replicated": replicated,
            }
        )
    return out


def audit_sharding(
    art: ProgramArtifact,
    rules: Optional[Sequence[Dict[str, Any]]] = None,
    declared_collectives: Optional[Sequence[str]] = None,
) -> Tuple[Dict[str, Any], List[Violation]]:
    """Audit one mesh program's buffer placement against its declared
    sharding contract.

    ``rules`` — each ``{"pattern": regex-on-arg-path, "min_bytes": int,
    "rank": optional int}`` declares "leaves matching this are supposed to
    shard": a matching leaf ≥ ``min_bytes`` left fully replicated on a
    multi-chip placement is an error-severity violation (the whole-pool-
    on-every-chip class). ``declared_collectives`` — the collective op
    kinds the engine's comm schedule intentionally contains; any other
    kind found in the compiled module is an undeclared resharding
    collective (pjit re-materializing a sharded buffer), error severity.
    Both inputs default to None = audit summarizes, flags nothing."""
    buffers = _signature_buffers(art)
    violations: List[Violation] = []
    mesh_devices = max((b["devices"] or 1) for b in buffers) if buffers else 1
    replicated_bytes = sum(
        b["per_chip_bytes"] for b in buffers if b["replicated"]
    )
    sharded_bytes = sum(
        b["per_chip_bytes"]
        for b in buffers
        if b["devices"] is not None and not b["replicated"]
    )
    summary: Dict[str, Any] = {
        "buffers": len(buffers),
        "mesh_devices": mesh_devices,
        "per_chip_arg_bytes": sum(b["per_chip_bytes"] for b in buffers),
        "replicated_bytes": replicated_bytes,
        "sharded_bytes": sharded_bytes,
    }
    for rule in rules or ():
        pat = re.compile(rule.get("pattern", ""))
        min_bytes = int(rule.get("min_bytes", 0))
        want_rank = rule.get("rank")
        for b in buffers:
            if not b["replicated"] or b["global_bytes"] < min_bytes:
                continue
            if want_rank is not None and len(b["shape"]) != want_rank:
                continue
            if not pat.search(b["arg"]):
                continue
            violations.append(
                Violation(
                    "memory",
                    art.name,
                    f"arg {b['arg']} ({b['dtype']}{list(b['shape'])}, "
                    f"{b['global_bytes']} bytes) is fully replicated across "
                    f"{b['devices']} chips but the declared sharding rule "
                    f"{rule.get('pattern')!r} says it shards — every chip "
                    "pays the whole buffer",
                    details={"arg": b["arg"], "bytes": b["global_bytes"],
                             "rule": dict(rule)},
                )
            )
    undeclared: List[Dict[str, Any]] = []
    if declared_collectives is not None:
        declared = set(declared_collectives)
        seen: Dict[str, Dict[str, int]] = {}
        for d in hlo_parse.collect_collective_details(art.hlo_text):
            rec = seen.setdefault(d["op"], {"count": 0, "bytes": 0})
            rec["count"] += 1
            rec["bytes"] += d["bytes"]
        for op, rec in sorted(seen.items()):
            if op in declared:
                continue
            undeclared.append({"op": op, **rec})
            violations.append(
                Violation(
                    "memory",
                    art.name,
                    f"{rec['count']} {op} collective(s) ({rec['bytes']} "
                    "bytes/device) in the compiled module are absent from "
                    "the declared comm schedule: pjit inserted a resharding "
                    "exchange the engine never planned (a sharded buffer is "
                    "being re-materialized)",
                    details={"op": op, **rec, "declared": sorted(declared)},
                )
            )
        summary["declared_collectives"] = sorted(declared)
    summary["undeclared_collectives"] = undeclared
    return summary, violations


# ---------------------------------------------------------------------------
# the "memory" program pass
# ---------------------------------------------------------------------------
def memory_pass(
    art: ProgramArtifact, config: Optional[Dict[str, Any]] = None
) -> PassResult:
    """Per-program memory pass: the peak-HBM estimate plus the sharding
    audit. With no ``sharding_rules`` / ``declared_collectives`` /
    ``hbm_budget_bytes`` configured the pass is summary-only."""
    cfg = config or {}
    res = PassResult()
    est = estimate_program_memory(art)
    audit_summary, violations = audit_sharding(
        art,
        rules=cfg.get("sharding_rules"),
        declared_collectives=cfg.get("declared_collectives"),
    )
    res.summary = {"estimate": est, "sharding": audit_summary}
    res.violations.extend(violations)
    budget = cfg.get("hbm_budget_bytes")
    mode = cfg.get("hbm_budget", "raise")
    if budget is not None and mode != "off" and est["peak_hbm_bytes"] > int(budget):
        res.violations.append(
            Violation(
                "memory",
                art.name,
                f"static peak HBM estimate {est['peak_hbm_bytes']} bytes/chip "
                f"exceeds analysis.hbm_budget_bytes={int(budget)} "
                f"(args={est['argument_bytes']} out={est['output_bytes']} "
                f"temp={est['temp_bytes']} alias={est['alias_bytes']})",
                severity="error" if mode == "raise" else "warn",
                details={"estimate": est, "budget": int(budget)},
            )
        )
    return res


PROGRAM_PASSES.setdefault("memory", memory_pass)


# ---------------------------------------------------------------------------
# whole-run residency ledger
# ---------------------------------------------------------------------------
def tree_device_bytes(tree) -> Dict[str, int]:
    """Byte accounting of a pytree of (possibly sharded) arrays:
    ``global_bytes`` (logical), ``per_chip_bytes`` (one device's shard —
    falls back to global when unsharded), and ``replicated_bytes`` (the
    per-chip bytes of leaves placed on >1 device but not partitioned —
    the footprint a sharding rule could reclaim)."""
    total = per_chip = replicated = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            continue
        g = _nbytes(tuple(leaf.shape), leaf.dtype)
        p = g
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                p = _nbytes(sharding.shard_shape(tuple(leaf.shape)), leaf.dtype)
                if int(sharding.num_devices) > 1 and p == g:
                    replicated += p
            except Exception:
                p = g
        total += g
        per_chip += p
    return {
        "global_bytes": total,
        "per_chip_bytes": per_chip,
        "replicated_bytes": replicated,
    }


class MemoryLedger:
    """Engine-level HBM residency ledger: persistent buffers (device- or
    host-resident) plus per-program transient estimates, with the
    ``analysis.hbm_budget_bytes`` gate.

    Peak model: the engine's programs run one at a time, and a program's
    argument buffers ARE the persistent entries (params, optimizer state,
    KV pools) already on the ledger — so the whole-run per-chip peak is

        persistent_device_bytes + max over programs of
            (temp_bytes + max(output_bytes - alias_bytes, 0))

    (un-aliased outputs and temporaries are the only bytes a dispatch adds
    on top of what already lives in HBM)."""

    def __init__(
        self,
        hbm_budget_bytes: Optional[int] = None,
        mode: str = "raise",
    ):
        self.hbm_budget_bytes = (
            int(hbm_budget_bytes) if hbm_budget_bytes is not None else None
        )
        self.mode = mode
        self.entries: List[Dict[str, Any]] = []
        self.programs: Dict[str, Dict[str, Any]] = {}

    def add_persistent(
        self,
        name: str,
        *,
        per_chip_bytes: int,
        global_bytes: Optional[int] = None,
        replicated_bytes: int = 0,
        location: str = "device",
        kind: str = "buffer",
        detail: Optional[Dict[str, Any]] = None,
    ) -> None:
        if location not in ("device", "host"):
            raise ValueError(f"location must be device|host, got {location!r}")
        self.entries.append(
            {
                "name": name,
                "kind": kind,
                "location": location,
                "per_chip_bytes": int(per_chip_bytes),
                "global_bytes": int(
                    global_bytes if global_bytes is not None else per_chip_bytes
                ),
                "replicated_bytes": int(replicated_bytes),
                "detail": detail or {},
            }
        )

    def add_tree(self, name: str, tree, *, kind: str = "buffer") -> None:
        """Convenience: account a pytree of device arrays as one entry."""
        acct = tree_device_bytes(tree)
        self.add_persistent(
            name,
            per_chip_bytes=acct["per_chip_bytes"],
            global_bytes=acct["global_bytes"],
            replicated_bytes=acct["replicated_bytes"],
            kind=kind,
        )

    def add_program(self, name: str, estimate: Dict[str, Any]) -> None:
        self.programs[name] = dict(estimate)

    # -- aggregation -----------------------------------------------------
    def report(self) -> Dict[str, Any]:
        device = [e for e in self.entries if e["location"] == "device"]
        host = [e for e in self.entries if e["location"] == "host"]
        persistent_device = sum(e["per_chip_bytes"] for e in device)
        transient = 0
        transient_program = None
        for name, est in self.programs.items():
            t = int(est.get("temp_bytes", 0)) + max(
                int(est.get("output_bytes", 0)) - int(est.get("alias_bytes", 0)),
                0,
            )
            if t >= transient:
                transient, transient_program = t, name
        peak = persistent_device + transient
        budget = self.hbm_budget_bytes
        verified: Optional[bool] = None
        if budget is not None and self.mode != "off":
            verified = peak <= budget
        return {
            "entries": [dict(e) for e in self.entries],
            "programs": {n: dict(e) for n, e in self.programs.items()},
            "persistent_device_bytes_per_chip": persistent_device,
            "host_bytes": sum(e["per_chip_bytes"] for e in host),
            "replicated_bytes": sum(e["replicated_bytes"] for e in self.entries),
            "transient_program_bytes": transient,
            "transient_program": transient_program,
            "peak_hbm_bytes_per_chip": peak,
            "hbm_budget_bytes": budget,
            "hbm_budget": self.mode,
            "hbm_budget_verified": verified,
        }

    def _attribution(self, report: Dict[str, Any]) -> str:
        lines = []
        device = sorted(
            (e for e in report["entries"] if e["location"] == "device"),
            key=lambda e: -e["per_chip_bytes"],
        )
        for e in device:
            lines.append(
                f"  {e['name']} ({e['kind']}): {e['per_chip_bytes']} "
                "bytes/chip on device"
            )
        if report["transient_program"]:
            lines.append(
                f"  program {report['transient_program']}: "
                f"{report['transient_program_bytes']} transient bytes/chip"
            )
        return "\n".join(lines)

    def enforce(self, logger=None) -> Dict[str, Any]:
        """Build the report and apply the budget gate: ``raise`` →
        :class:`HbmBudgetError` with per-buffer attribution when the
        per-chip peak exceeds the budget, ``warn`` → one logger warning,
        ``off``/no budget → report only."""
        report = self.report()
        if report["hbm_budget_verified"] is False:
            msg = (
                f"static HBM ledger: peak {report['peak_hbm_bytes_per_chip']} "
                f"bytes/chip exceeds analysis.hbm_budget_bytes="
                f"{report['hbm_budget_bytes']}\n" + self._attribution(report)
            )
            if self.mode == "raise":
                raise HbmBudgetError(msg)
            if logger is not None:
                logger.warning(msg)
        return report
